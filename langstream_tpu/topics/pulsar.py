"""Pulsar topic runtime over Pulsar's WebSocket + admin REST APIs.

Reference: ``langstream-pulsar-runtime/src/main/java/ai/langstream/pulsar/
PulsarTopicConnectionsRuntime.java`` (SPI wiring over the Java client).
The TPU build drives Pulsar through its built-in WebSocket proxy
(``/ws/v2/{producer,consumer,reader}/persistent/...``) and admin REST
(``/admin/v2/persistent/...``) — no vendor client library needed, and
the broker keeps its native per-message ack bookkeeping:

- consumers use a **Shared** subscription named by the agent's group;
  out-of-order acks are acknowledged individually to the broker, which
  is exactly the Topic SPI's commit contract (the broker, not a client
  watermark, owns redelivery) — one consumer per (group, topic) per
  process, multiple processes share the subscription.
- readers tail without a subscription (``messageId=earliest|latest``).

Config (``streamingCluster.configuration``):

- ``webServiceUrl``     — admin REST base (default http://localhost:8080)
- ``webSocketUrl``      — WS base; derived from webServiceUrl when unset
- ``tenant``/``namespace`` — Pulsar addressing (public/default)
"""

from __future__ import annotations

import asyncio
import base64
import dataclasses
import json
import logging
import urllib.parse
from typing import Any, Dict, List, Optional

from langstream_tpu.api.records import Record, now_millis
from langstream_tpu.api.topics import (
    OffsetPosition,
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicProducer,
    TopicReader,
    TopicSpec,
)
from langstream_tpu.topics.serde import decode_payload, encode_payload

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class PulsarRecordView(Record):
    """Record plus the Pulsar messageId commit() needs."""

    message_id: str = ""


def _encode_message(record: Record) -> Dict[str, Any]:
    key, key_kind = encode_payload(record.key)
    value, value_kind = encode_payload(record.value)
    properties: Dict[str, str] = {}
    header_kinds: Dict[str, str] = {}
    for name, hvalue in record.headers:
        data, kind = encode_payload(hvalue)
        properties[name] = (
            base64.b64encode(data).decode() if data is not None else ""
        )
        header_kinds[name] = kind
    properties["ls-meta"] = json.dumps(
        {"v": value_kind, "k": key_kind, "h": header_kinds}
    )
    message: Dict[str, Any] = {
        "payload": base64.b64encode(value or b"").decode(),
        "properties": properties,
    }
    if key is not None:
        message["key"] = base64.b64encode(key).decode()
    return message


def _decode_message(message: Dict[str, Any], topic: str) -> PulsarRecordView:
    properties = dict(message.get("properties") or {})
    meta: Dict[str, Any] = {}
    raw_meta = properties.pop("ls-meta", None)
    if raw_meta:
        try:
            meta = json.loads(raw_meta)
        except ValueError:
            meta = {}
    header_kinds = meta.get("h", {})
    headers = []
    for name, encoded in properties.items():
        data = base64.b64decode(encoded) if encoded else None
        headers.append((name, decode_payload(data, header_kinds.get(name))))
    payload = base64.b64decode(message.get("payload") or "")
    key_raw = message.get("key")
    key = (
        decode_payload(base64.b64decode(key_raw), meta.get("k"))
        if key_raw else None
    )
    return PulsarRecordView(
        value=decode_payload(payload, meta.get("v")),
        key=key,
        origin=topic,
        timestamp=message.get("publishTime") or now_millis(),
        headers=tuple(headers),
        message_id=message.get("messageId", ""),
    )


class _WsChannel:
    """One websocket endpoint with lazy connect."""

    def __init__(self, url: str) -> None:
        self.url = url
        self._ws = None

    async def connect(self):
        if self._ws is None:
            import websockets

            self._ws = await websockets.connect(self.url, max_size=None)
        return self._ws

    async def close(self) -> None:
        if self._ws is not None:
            await self._ws.close()
            self._ws = None


class PulsarTopicProducer(TopicProducer):
    def __init__(self, base_ws: str, full_topic: str) -> None:
        self._channel = _WsChannel(f"{base_ws}/producer/{full_topic}")
        self._topic = full_topic.rsplit("/", 1)[-1]
        self._written = 0

    @property
    def topic(self) -> str:
        return self._topic

    async def start(self) -> None:
        await self._channel.connect()

    async def write(self, record: Record) -> None:
        ws = await self._channel.connect()
        await ws.send(json.dumps(_encode_message(record)))
        response = json.loads(await ws.recv())
        if response.get("result") != "ok":
            raise IOError(f"pulsar produce failed: {response}")
        self._written += 1

    async def close(self) -> None:
        await self._channel.close()

    def total_in(self) -> int:
        return self._written


class PulsarTopicConsumer(TopicConsumer):
    """Shared-subscription consumer; acks are per-message to the broker
    (out-of-order safe — redelivery bookkeeping is server-side)."""

    def __init__(self, base_ws: str, full_topic: str, group: str) -> None:
        subscription = urllib.parse.quote(group, safe="")
        self._channel = _WsChannel(
            f"{base_ws}/consumer/{full_topic}/{subscription}"
            "?subscriptionType=Shared&receiverQueueSize=500"
        )
        self._topic = full_topic.rsplit("/", 1)[-1]
        self._delivered = 0

    async def start(self) -> None:
        await self._channel.connect()

    async def read(
        self, max_records: int = 100, timeout: float = 0.1
    ) -> List[Record]:
        ws = await self._channel.connect()
        out: List[Record] = []
        deadline = asyncio.get_event_loop().time() + timeout
        while len(out) < max_records:
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0 and out:
                break
            try:
                frame = await asyncio.wait_for(
                    ws.recv(), timeout=max(remaining, 0.01)
                )
            except asyncio.TimeoutError:
                break
            out.append(_decode_message(json.loads(frame), self._topic))
        self._delivered += len(out)
        return out

    async def commit(self, records: List[Record]) -> None:
        ws = await self._channel.connect()
        for record in records:
            if not isinstance(record, PulsarRecordView):
                raise ValueError(
                    f"cannot commit a non-pulsar record: {record!r}"
                )
            await ws.send(json.dumps({"messageId": record.message_id}))

    async def close(self) -> None:
        await self._channel.close()

    def total_out(self) -> int:
        return self._delivered


class PulsarTopicReader(TopicReader):
    def __init__(
        self, base_ws: str, full_topic: str, position: OffsetPosition
    ) -> None:
        start = (
            "earliest" if position == OffsetPosition.EARLIEST else "latest"
        )
        self._channel = _WsChannel(
            f"{base_ws}/reader/{full_topic}?messageId={start}"
        )
        self._topic = full_topic.rsplit("/", 1)[-1]

    async def start(self) -> None:
        await self._channel.connect()

    async def read(
        self, max_records: int = 100, timeout: float = 0.1
    ) -> List[Record]:
        ws = await self._channel.connect()
        out: List[Record] = []
        deadline = asyncio.get_event_loop().time() + timeout
        while len(out) < max_records:
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                break
            try:
                frame = await asyncio.wait_for(
                    ws.recv(), timeout=max(remaining, 0.01)
                )
            except asyncio.TimeoutError:
                break
            message = json.loads(frame)
            out.append(_decode_message(message, self._topic))
            # readers must ack to advance the proxy's cursor
            await ws.send(json.dumps({"messageId": message.get("messageId")}))
        return out

    async def close(self) -> None:
        await self._channel.close()


class PulsarTopicAdmin(TopicAdmin):
    def __init__(self, web_url: str, tenant: str, namespace: str) -> None:
        self.web_url = web_url.rstrip("/")
        self.tenant = tenant
        self.namespace = namespace
        self._session = None

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    def _topic_url(self, name: str) -> str:
        return (
            f"{self.web_url}/admin/v2/persistent/{self.tenant}/"
            f"{self.namespace}/{urllib.parse.quote(name, safe='')}"
        )

    async def create_topic(self, spec: TopicSpec) -> None:
        session = await self._get_session()
        if spec.partitions > 1:
            url = self._topic_url(spec.name) + "/partitions"
            async with session.put(url, json=spec.partitions) as response:
                if response.status not in (204, 409):
                    raise IOError(
                        f"pulsar create partitions HTTP {response.status}"
                    )
            return
        async with session.put(self._topic_url(spec.name)) as response:
            if response.status not in (204, 409):
                raise IOError(f"pulsar create topic HTTP {response.status}")

    async def delete_topic(self, name: str) -> None:
        session = await self._get_session()
        async with session.delete(self._topic_url(name)) as response:
            if response.status not in (204, 404):
                raise IOError(f"pulsar delete topic HTTP {response.status}")

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()


class PulsarTopicConnectionsRuntime(TopicConnectionsRuntime):
    def __init__(self, configuration: Optional[Dict[str, Any]] = None) -> None:
        configuration = configuration or {}
        web = (
            configuration.get("webServiceUrl")
            or configuration.get("web-service-url")
            or "http://localhost:8080"
        ).rstrip("/")
        ws = configuration.get("webSocketUrl") or configuration.get(
            "web-socket-url"
        )
        if not ws:
            ws = web.replace("https://", "wss://").replace("http://", "ws://")
        self.web_url = web
        self.ws_base = ws.rstrip("/") + "/ws/v2"
        self.tenant = configuration.get("tenant", "public")
        self.namespace = configuration.get("namespace", "default")
        self._owned: List[Any] = []

    def _full_topic(self, name: str) -> str:
        return (
            f"persistent/{self.tenant}/{self.namespace}/"
            f"{urllib.parse.quote(name, safe='')}"
        )

    def create_consumer(
        self, agent_id: str, config: Dict[str, Any]
    ) -> TopicConsumer:
        consumer = PulsarTopicConsumer(
            self.ws_base,
            self._full_topic(config["topic"]),
            config.get("group") or f"langstream-{agent_id}",
        )
        self._owned.append(consumer)
        return consumer

    def create_producer(
        self, agent_id: str, config: Dict[str, Any]
    ) -> TopicProducer:
        producer = PulsarTopicProducer(
            self.ws_base, self._full_topic(config["topic"])
        )
        self._owned.append(producer)
        return producer

    def create_reader(
        self,
        config: Dict[str, Any],
        initial_position: OffsetPosition = OffsetPosition.LATEST,
    ) -> TopicReader:
        reader = PulsarTopicReader(
            self.ws_base, self._full_topic(config["topic"]), initial_position
        )
        self._owned.append(reader)
        return reader

    def create_admin(self) -> TopicAdmin:
        admin = PulsarTopicAdmin(self.web_url, self.tenant, self.namespace)
        self._owned.append(admin)
        return admin

    async def close(self) -> None:
        for owned in self._owned:
            try:
                await owned.close()
            except Exception:  # noqa: BLE001
                pass
        self._owned.clear()
