"""langstream-tpu: a TPU-native streaming-LLM application framework.

A ground-up rebuild of the capabilities of LangStream (reference:
``/root/reference``, github.com/Gagravarr/langstream): declarative YAML
applications composed of agent pipelines connected by topics, compiled into an
execution plan and run by a per-agent runner with exactly-once-ish offset
semantics — but with model inference as a first-class in-process JAX/XLA
backend (the ``jax-local`` service provider) instead of outbound HTTP calls,
record batches coalesced into bucketed-padding XLA calls, and agent
parallelism mapped onto the TPU ICI/DCN mesh (data / tensor / sequence
parallelism).

Layer map (mirrors SURVEY.md §1, re-architected for TPU):

- ``langstream_tpu.api``       — the SPI: records, agents, topics, services.
- ``langstream_tpu.model``     — the application model (parsed YAML).
- ``langstream_tpu.compiler``  — parser + planner → ExecutionPlan.
- ``langstream_tpu.topics``    — broker implementations (in-memory, ...).
- ``langstream_tpu.runtime``   — the per-agent runner hot loop + batching.
- ``langstream_tpu.agents``    — the built-in agent library ("ops").
- ``langstream_tpu.providers`` — AI service providers, incl. ``jax_local``.
- ``langstream_tpu.ops``       — JAX/Pallas kernels (attention, norms, ...).
- ``langstream_tpu.parallel``  — mesh / sharding / collectives helpers.
- ``langstream_tpu.gateway``   — WebSocket/HTTP gateway.
- ``langstream_tpu.training``  — fine-tuning (sharded train step).
- ``langstream_tpu.cli``       — command-line interface.
"""

__version__ = "0.1.0"
