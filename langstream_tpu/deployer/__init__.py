"""Kubernetes deployer: CRD documents, resource factories, and the
operator reconcile loop.

Reference: ``langstream-k8s-deployer/`` (SURVEY §2.6) — CRDs
``applications.langstream.ai`` / ``agents.langstream.ai``, the
``AppResourcesFactory``/``AgentResourcesFactory`` manifest generators, and
the Quarkus JOSDK operator (``AppController``/``AgentController``). Here:

- :mod:`crds`      — custom-resource documents + CRD schemas.
- :mod:`resources` — manifest generation (StatefulSets targeting GKE TPU
  node pools, setup/deployer Jobs, Secrets, Services, PVCs).
- :mod:`kube`      — a minimal K8s API abstraction with an in-memory mock
  (the reference tests against a fabric8 mock the same way,
  ``KubeTestServer.java:46``); a real-cluster client can implement the
  same interface over the REST API.
- :mod:`operator`  — reconcile app CRs → agent CRs → StatefulSets, status
  aggregation, retry with backoff.
"""

from langstream_tpu.deployer.crds import (  # noqa: F401
    AgentCustomResource,
    ApplicationCustomResource,
    agent_crd_schema,
    application_crd_schema,
)
from langstream_tpu.deployer.kube import MockKubeApi  # noqa: F401
from langstream_tpu.deployer.operator import Operator  # noqa: F401
from langstream_tpu.deployer.resources import (  # noqa: F401
    generate_agent_secret,
    generate_deployer_job,
    generate_gateway_service,
    generate_setup_job,
    generate_statefulset,
)
