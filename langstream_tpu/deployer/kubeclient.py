"""Real Kubernetes API client (same verbs as :class:`MockKubeApi`).

Reference: ``langstream-k8s-common/src/main/java/ai/langstream/impl/k8s/
KubernetesClientFactory.java`` (fabric8 client, in-cluster or kubeconfig).
This client is dependency-free (stdlib ``urllib``): the operator and
deployer only need apply/get/list/delete/patch_status over a handful of
well-known kinds, so a full client library isn't warranted.

Configuration resolution order (:func:`create_kube_api`):

1. ``LANGSTREAM_KUBE_URL`` (+ optional ``LANGSTREAM_KUBE_TOKEN``) — used
   by tests and non-standard clusters; plain HTTP allowed.
2. In-cluster service account (``KUBERNETES_SERVICE_HOST`` + the mounted
   token/CA under ``/var/run/secrets/kubernetes.io/serviceaccount``).
3. ``LANGSTREAM_KUBE=mock`` → the in-memory :class:`MockKubeApi`
   (single-process stacks and unit tests).
"""

from __future__ import annotations

import json
import os
import ssl
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from langstream_tpu.deployer.crds import (
    AGENTS_PLURAL,
    API_GROUP,
    APPLICATIONS_PLURAL,
)

Manifest = Dict[str, Any]

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# kind → (api prefix, plural). CRs use the langstream API group.
_KIND_ROUTES: Dict[str, Any] = {
    "Secret": ("/api/v1", "secrets"),
    "Service": ("/api/v1", "services"),
    "ConfigMap": ("/api/v1", "configmaps"),
    "Pod": ("/api/v1", "pods"),
    "Namespace": ("/api/v1", "namespaces"),
    "StatefulSet": ("/apis/apps/v1", "statefulsets"),
    "Deployment": ("/apis/apps/v1", "deployments"),
    "Job": ("/apis/batch/v1", "jobs"),
    "Application": (f"/apis/{API_GROUP}/v1", APPLICATIONS_PLURAL),
    "Agent": (f"/apis/{API_GROUP}/v1", AGENTS_PLURAL),
    "CustomResourceDefinition": (
        "/apis/apiextensions.k8s.io/v1", "customresourcedefinitions"
    ),
}

_CLUSTER_SCOPED = {"Namespace", "CustomResourceDefinition"}


class KubeApiError(RuntimeError):
    def __init__(self, status: int, body: str, url: str) -> None:
        super().__init__(f"kube API {status} for {url}: {body[:500]}")
        self.status = status
        self.body = body


class RealKubeApi:
    """apply/get/list/delete/patch_status over the Kubernetes REST API."""

    def __init__(
        self,
        base_url: str,
        *,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
        timeout: float = 30.0,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        if self.base_url.startswith("https"):
            if insecure:
                context = ssl.create_default_context()
                context.check_hostname = False
                context.verify_mode = ssl.CERT_NONE
            else:
                context = ssl.create_default_context(cafile=ca_file)
            self._context: Optional[ssl.SSLContext] = context
        else:
            self._context = None

    # -- plumbing ------------------------------------------------------ #
    def _url(self, kind: str, namespace: Optional[str], name: Optional[str],
             *, subresource: str = "", query: str = "") -> str:
        try:
            prefix, plural = _KIND_ROUTES[kind]
        except KeyError:
            raise ValueError(f"unsupported kind {kind!r}") from None
        if kind in _CLUSTER_SCOPED:
            path = f"{prefix}/{plural}"
        else:
            path = f"{prefix}/namespaces/{namespace or 'default'}/{plural}"
        if name:
            path += f"/{name}"
        if subresource:
            path += f"/{subresource}"
        if query:
            path += f"?{query}"
        return self.base_url + path

    def _request(
        self, method: str, url: str, body: Optional[Dict[str, Any]] = None,
        content_type: str = "application/json",
    ) -> Dict[str, Any]:
        data = json.dumps(body).encode() if body is not None else None
        request = urllib.request.Request(url, data=data, method=method)
        request.add_header("Accept", "application/json")
        if data is not None:
            request.add_header("Content-Type", content_type)
        if self.token:
            request.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(
                request, timeout=self.timeout, context=self._context
            ) as response:
                payload = response.read()
        except urllib.error.HTTPError as error:
            raise KubeApiError(
                error.code, error.read().decode(errors="replace"), url
            ) from None
        return json.loads(payload) if payload else {}

    # -- verbs (MockKubeApi-compatible) -------------------------------- #
    def apply(self, doc: Manifest) -> Manifest:
        kind = doc.get("kind", "")
        meta = doc.get("metadata", {})
        namespace, name = meta.get("namespace", "default"), meta["name"]
        # create-or-replace: POST, then on conflict GET the live object's
        # resourceVersion and PUT (the fabric8 createOrReplace pattern)
        try:
            return self._request(
                "POST", self._url(kind, namespace, None), doc
            )
        except KubeApiError as error:
            if error.status != 409:
                raise
        live = self.get(kind, namespace, name)
        if live is None:  # deleted between POST and GET — retry create
            return self._request(
                "POST", self._url(kind, namespace, None), doc
            )
        replacement = dict(doc)
        replacement["metadata"] = dict(meta)
        replacement["metadata"]["resourceVersion"] = (
            live.get("metadata", {}).get("resourceVersion")
        )
        # status is only written through patch_status
        replacement.pop("status", None)
        return self._request(
            "PUT", self._url(kind, namespace, name), replacement
        )

    def get(self, kind: str, namespace: str, name: str) -> Optional[Manifest]:
        try:
            return self._request("GET", self._url(kind, namespace, name))
        except KubeApiError as error:
            if error.status == 404:
                return None
            raise

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Manifest]:
        query = ""
        if label_selector:
            selector = ",".join(f"{k}={v}" for k, v in label_selector.items())
            query = "labelSelector=" + urllib.parse.quote(selector)
        if namespace is None and kind not in _CLUSTER_SCOPED:
            # all-namespaces listing
            prefix, plural = _KIND_ROUTES[kind]
            url = f"{self.base_url}{prefix}/{plural}"
            if query:
                url += f"?{query}"
        else:
            url = self._url(kind, namespace, None, query=query)
        result = self._request("GET", url)
        items = result.get("items", []) or []
        for item in items:
            # list items omit kind/apiVersion; restore for manifest_key use
            item.setdefault("kind", kind)
        return items

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        try:
            self._request("DELETE", self._url(kind, namespace, name))
            return True
        except KubeApiError as error:
            if error.status == 404:
                return False
            raise

    def pod_logs(
        self, namespace: str, pod: str, *, tail_lines: int = 200,
        container: Optional[str] = None,
    ) -> str:
        """Pod log read (reference: the webservice's kubectl-free log
        streaming, ApplicationResource.java:311-459)."""
        query = f"tailLines={tail_lines}"
        if container:
            query += f"&container={urllib.parse.quote(container)}"
        url = (
            f"{self.base_url}/api/v1/namespaces/{namespace}/pods/"
            f"{pod}/log?{query}"
        )
        request = urllib.request.Request(url, method="GET")
        if self.token:
            request.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(
                request, timeout=self.timeout, context=self._context
            ) as response:
                return response.read().decode(errors="replace")
        except urllib.error.HTTPError as error:
            return f"<no logs: HTTP {error.code}>"

    def patch_status(
        self, kind: str, namespace: str, name: str, status: Dict[str, Any]
    ) -> Optional[Manifest]:
        try:
            return self._request(
                "PATCH",
                self._url(kind, namespace, name, subresource="status"),
                {"status": status},
                content_type="application/merge-patch+json",
            )
        except KubeApiError as error:
            if error.status == 404:
                return None
            raise


def in_cluster_available() -> bool:
    return bool(os.environ.get("KUBERNETES_SERVICE_HOST")) and os.path.exists(
        os.path.join(SERVICE_ACCOUNT_DIR, "token")
    )


def create_kube_api():
    """Resolve a kube API client from the environment (see module doc)."""
    explicit = os.environ.get("LANGSTREAM_KUBE_URL")
    if explicit:
        return RealKubeApi(
            explicit,
            token=os.environ.get("LANGSTREAM_KUBE_TOKEN"),
            ca_file=os.environ.get("LANGSTREAM_KUBE_CA"),
            insecure=os.environ.get("LANGSTREAM_KUBE_INSECURE") == "true",
        )
    if in_cluster_available():
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(os.path.join(SERVICE_ACCOUNT_DIR, "token")) as handle:
            token = handle.read().strip()
        ca = os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
        return RealKubeApi(
            f"https://{host}:{port}",
            token=token,
            ca_file=ca if os.path.exists(ca) else None,
        )
    if os.environ.get("LANGSTREAM_KUBE", "").lower() in ("mock", "memory"):
        from langstream_tpu.deployer.kube import MockKubeApi

        return MockKubeApi()
    raise RuntimeError(
        "no Kubernetes API configured: set LANGSTREAM_KUBE_URL, run "
        "in-cluster with a service account, or set LANGSTREAM_KUBE=mock"
    )
