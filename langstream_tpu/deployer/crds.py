"""Custom-resource documents and CRD schemas.

Reference CRDs: ``applications.langstream.ai`` and ``agents.langstream.ai``
(``helm/crds/{applications,agents}.langstream.ai-v1.yml``; spec classes
``langstream-k8s-deployer-api/.../crds/apps/ApplicationSpec.java:33`` and
``crds/agents/AgentSpec.java:33``). The documents here are plain dicts in
Kubernetes shape (apiVersion/kind/metadata/spec/status) so they serialize
directly to manifests and round-trip through any API server.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

API_GROUP = "langstream.tpu"
API_VERSION = f"{API_GROUP}/v1"
APPLICATIONS_PLURAL = "applications"
AGENTS_PLURAL = "agents"


@dataclasses.dataclass
class ApplicationCustomResource:
    """The stored-app CR the control plane writes and the operator
    reconciles (reference ``ApplicationCustomResource``; apps are stored
    AS these, ``KubernetesApplicationStore.java:137-190``)."""

    name: str                       # application id
    namespace: str                  # tenant namespace
    application: Dict[str, Any]     # serialized application definition
    instance: Dict[str, Any]
    code_archive_id: Optional[str] = None
    checksum: Optional[str] = None
    generation: int = 1
    status: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_manifest(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": "Application",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "generation": self.generation,
            },
            "spec": {
                "application": json.dumps(self.application),
                "instance": json.dumps(self.instance),
                "codeArchiveId": self.code_archive_id,
                "checksum": self.checksum,
            },
            "status": self.status,
        }

    @classmethod
    def from_manifest(cls, doc: Dict[str, Any]) -> "ApplicationCustomResource":
        meta, spec = doc.get("metadata", {}), doc.get("spec", {})
        return cls(
            name=meta["name"],
            namespace=meta.get("namespace", "default"),
            application=json.loads(spec.get("application") or "{}"),
            instance=json.loads(spec.get("instance") or "{}"),
            code_archive_id=spec.get("codeArchiveId"),
            checksum=spec.get("checksum"),
            generation=meta.get("generation", 1),
            status=doc.get("status", {}) or {},
        )


@dataclasses.dataclass
class AgentCustomResource:
    """One execution-plan node as a CR (reference ``AgentCustomResource``
    written per plan node by ``KubernetesClusterRuntime.java:93-144``)."""

    name: str                        # <application-id>-<node-id>
    namespace: str
    application_id: str
    agent_node: Dict[str, Any]       # serialized AgentNode (runner config)
    streaming_cluster: Dict[str, Any]
    # the application's AI-provider/datasource resource configs — agents
    # resolve providers from these at runtime, so the pod needs them
    resources: Dict[str, Any] = dataclasses.field(default_factory=dict)
    parallelism: int = 1
    size: int = 1                    # compute units → TPU chips per replica
    disk: Optional[Dict[str, Any]] = None
    code_archive_id: Optional[str] = None
    checksum: Optional[str] = None
    generation: int = 1
    status: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_manifest(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": "Agent",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "generation": self.generation,
                "labels": {
                    "app.kubernetes.io/managed-by": "langstream-tpu",
                    "langstream.tpu/application": self.application_id,
                },
            },
            "spec": {
                "applicationId": self.application_id,
                "agentNode": json.dumps(self.agent_node),
                "streamingCluster": json.dumps(self.streaming_cluster),
                "resources": json.dumps(self.resources),
                "parallelism": self.parallelism,
                "size": self.size,
                "disk": self.disk,
                "codeArchiveId": self.code_archive_id,
                "checksum": self.checksum,
            },
            "status": self.status,
        }

    @classmethod
    def from_manifest(cls, doc: Dict[str, Any]) -> "AgentCustomResource":
        meta, spec = doc.get("metadata", {}), doc.get("spec", {})
        return cls(
            name=meta["name"],
            namespace=meta.get("namespace", "default"),
            application_id=spec.get("applicationId", ""),
            agent_node=json.loads(spec.get("agentNode") or "{}"),
            streaming_cluster=json.loads(spec.get("streamingCluster") or "{}"),
            resources=json.loads(spec.get("resources") or "{}"),
            parallelism=int(spec.get("parallelism", 1)),
            size=int(spec.get("size", 1)),
            disk=spec.get("disk"),
            code_archive_id=spec.get("codeArchiveId"),
            checksum=spec.get("checksum"),
            generation=meta.get("generation", 1),
            status=doc.get("status", {}) or {},
        )


def _crd(plural: str, kind: str, spec_properties: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{API_GROUP}"},
        "spec": {
            "group": API_GROUP,
            "names": {
                "kind": kind,
                "plural": plural,
                "singular": kind.lower(),
            },
            "scope": "Namespaced",
            "versions": [{
                "name": "v1",
                "served": True,
                "storage": True,
                "schema": {
                    "openAPIV3Schema": {
                        "type": "object",
                        "properties": {
                            "spec": {
                                "type": "object",
                                "properties": spec_properties,
                            },
                            "status": {
                                "type": "object",
                                "x-kubernetes-preserve-unknown-fields": True,
                            },
                        },
                    }
                },
                "subresources": {"status": {}},
            }],
        },
    }


def application_crd_schema() -> Dict[str, Any]:
    return _crd(APPLICATIONS_PLURAL, "Application", {
        "application": {"type": "string"},
        "instance": {"type": "string"},
        "codeArchiveId": {"type": "string"},
        "checksum": {"type": "string"},
    })


def agent_crd_schema() -> Dict[str, Any]:
    return _crd(AGENTS_PLURAL, "Agent", {
        "applicationId": {"type": "string"},
        "agentNode": {"type": "string"},
        "streamingCluster": {"type": "string"},
        "resources": {"type": "string"},
        "parallelism": {"type": "integer"},
        "size": {"type": "integer"},
        "disk": {
            "type": "object",
            "x-kubernetes-preserve-unknown-fields": True,
        },
        "codeArchiveId": {"type": "string"},
        "checksum": {"type": "string"},
    })
