"""Operator: reconcile Application CRs into Agent CRs into runtime
resources (StatefulSets, Secrets, Services).

Reference: ``AppController.java:50`` / ``AgentController.java:58`` (Quarkus
JOSDK) with ``InfiniteRetry``; deploy path SURVEY §3.1 steps 3-5. The
reference splits plan building into a deployer Job pod; this operator
builds the plan in-process (it is the same compiler) and keeps the Job
manifests available for clusters that want the Job-based split.

Reconcile is level-based: every pass converges the world to the CRs —
orphaned agent CRs/StatefulSets of deleted or re-planned apps are removed
by ownership labels, and spec changes are detected by checksum+generation
(reference ``SpecDiffer``).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
from typing import Any, Dict, List, Optional

from langstream_tpu.compiler.planner import build_execution_plan
from langstream_tpu.deployer.crds import (
    AgentCustomResource,
    ApplicationCustomResource,
)
from langstream_tpu.deployer.kube import MockKubeApi
from langstream_tpu.deployer.resources import (
    DEFAULT_IMAGE,
    generate_agent_secret,
    generate_headless_service,
    generate_statefulset,
)
from langstream_tpu.model.application import Application

logger = logging.getLogger(__name__)

_APP_LABEL = "langstream.tpu/application"
# set by Operator.scale: the fleet autoscaler owns this StatefulSet's
# replica count, and level-based reconcile must not snap it back to
# the plan's parallelism (HPA-ownership semantics)
_FLEET_REPLICAS_ANNOTATION = "langstream.tpu/fleet-replicas"


class Operator:
    def __init__(
        self,
        kube: MockKubeApi,
        *,
        image: str = DEFAULT_IMAGE,
        accelerator: str = "tpu-v5-lite-podslice",
        code_storage_config: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.kube = kube
        self.image = image
        self.accelerator = accelerator
        self.code_storage_config = code_storage_config or {}
        self._backoff: Dict[str, float] = {}

    # -- application level -------------------------------------------- #
    def reconcile_application(self, app_doc: Dict[str, Any]) -> None:
        app = ApplicationCustomResource.from_manifest(app_doc)
        application = Application.from_document(app.application, app.instance)
        application.application_id = app.name
        application.tenant = app.namespace
        plan = build_execution_plan(application)

        desired: Dict[str, AgentCustomResource] = {}
        for node in plan.agents:
            name = f"{app.name}-{node.id}"
            desired[name] = AgentCustomResource(
                name=name,
                namespace=app.namespace,
                application_id=app.name,
                agent_node=_node_document(node),
                streaming_cluster=application.instance.streaming_cluster,
                resources=application.resources,
                parallelism=node.resources.parallelism,
                size=node.resources.size,
                disk=node.resources.disk,
                code_archive_id=app.code_archive_id,
                checksum=app.checksum,
            )

        existing = {
            doc["metadata"]["name"]: doc
            for doc in self.kube.list(
                "Agent", app.namespace, {_APP_LABEL: app.name}
            )
        }
        for name, agent in desired.items():
            self.kube.apply(agent.to_manifest())
        for name in set(existing) - set(desired):
            self._delete_agent(app.namespace, name)

        self.kube.patch_status(
            "Application", app.namespace, app.name,
            {
                "phase": "DEPLOYED",
                "agents": sorted(desired),
                "observedGeneration": app.generation,
                "checksum": app.checksum,
            },
        )

    def delete_application(self, namespace: str, name: str) -> None:
        for doc in self.kube.list("Agent", namespace, {_APP_LABEL: name}):
            self._delete_agent(namespace, doc["metadata"]["name"])

    # -- agent level --------------------------------------------------- #
    def reconcile_agent(self, agent_doc: Dict[str, Any]) -> None:
        agent = AgentCustomResource.from_manifest(agent_doc)
        self.kube.apply(generate_agent_secret(agent))
        self.kube.apply(generate_headless_service(agent))
        manifest = generate_statefulset(
            agent, image=self.image, accelerator=self.accelerator,
            code_storage_config=self.code_storage_config,
        )
        existing = self.kube.get("StatefulSet", agent.namespace, agent.name)
        if existing is not None:
            autoscaled = (
                existing.get("metadata", {}).get("annotations") or {}
            ).get(_FLEET_REPLICAS_ANNOTATION)
            if autoscaled is not None:
                # the fleet autoscaler owns the count: re-applying the
                # plan's parallelism would silently undo a live scale
                # decision on every reconcile pass
                manifest["spec"]["replicas"] = int(autoscaled)
                manifest.setdefault("metadata", {}).setdefault(
                    "annotations", {}
                )[_FLEET_REPLICAS_ANNOTATION] = autoscaled
        self.kube.apply(manifest)
        sts = self.kube.get("StatefulSet", agent.namespace, agent.name)
        self.kube.patch_status(
            "Agent", agent.namespace, agent.name,
            {
                "phase": "DEPLOYED",
                "replicas": sts["spec"]["replicas"] if sts else 0,
                "observedGeneration": agent.generation,
            },
        )

    def scale(self, namespace: str, name: str, replicas: int) -> int:
        """Patch an agent StatefulSet's replica count — the fleet
        autoscaler's actuator (``fleet/autoscaler.py``). Goes through
        the same apply path as reconcile (generation bump on spec
        change), and mirrors the count into the Agent CR status so
        ``apps get`` tells the truth. Returns the applied count."""
        replicas = max(0, int(replicas))
        sts = self.kube.get("StatefulSet", namespace, name)
        if sts is None:
            raise LookupError(f"no StatefulSet {namespace}/{name} to scale")
        annotations = sts.setdefault("metadata", {}).setdefault(
            "annotations", {}
        )
        if (
            sts["spec"].get("replicas") != replicas
            or annotations.get(_FLEET_REPLICAS_ANNOTATION) != str(replicas)
        ):
            sts["spec"]["replicas"] = replicas
            # mark autoscaler ownership so reconcile_agent preserves
            # the count instead of re-applying the plan's parallelism
            annotations[_FLEET_REPLICAS_ANNOTATION] = str(replicas)
            self.kube.apply(sts)
            logger.info(
                "scaled StatefulSet %s/%s to %d replicas",
                namespace, name, replicas,
            )
        if self.kube.get("Agent", namespace, name) is not None:
            self.kube.patch_status(
                "Agent", namespace, name, {"replicas": replicas}
            )
        return replicas

    def _delete_agent(self, namespace: str, name: str) -> None:
        self.kube.delete("StatefulSet", namespace, name)
        self.kube.delete("Service", namespace, name)
        self.kube.delete("Secret", namespace, name)
        self.kube.delete("Agent", namespace, name)

    # -- level-based sweep -------------------------------------------- #
    def reconcile(self) -> None:
        """One full convergence pass over every namespace."""
        apps = self.kube.list("Application")
        app_names = {
            (doc["metadata"].get("namespace", "default"),
             doc["metadata"]["name"])
            for doc in apps
        }
        for doc in apps:
            name = doc["metadata"]["name"]
            try:
                status = doc.get("status", {}) or {}
                if status.get("observedGeneration") != doc["metadata"].get(
                    "generation"
                ) or status.get("phase") != "DEPLOYED":
                    self.reconcile_application(doc)
            except Exception as err:  # noqa: BLE001 — reconcile must not die
                logger.exception("reconcile failed for app %s", name)
                self.kube.patch_status(
                    "Application",
                    doc["metadata"].get("namespace", "default"), name,
                    {"phase": "ERROR", "detail": f"{type(err).__name__}: {err}"},
                )
        # agents: converge + orphan cleanup
        for doc in self.kube.list("Agent"):
            namespace = doc["metadata"].get("namespace", "default")
            owner = (doc["metadata"].get("labels") or {}).get(_APP_LABEL)
            if owner and (namespace, owner) not in app_names:
                self._delete_agent(namespace, doc["metadata"]["name"])
                continue
            status = doc.get("status", {}) or {}
            if status.get("observedGeneration") != doc["metadata"].get(
                "generation"
            ):
                try:
                    self.reconcile_agent(doc)
                except Exception as err:  # noqa: BLE001
                    logger.exception(
                        "reconcile failed for agent %s", doc["metadata"]["name"]
                    )
                    self.kube.patch_status(
                        "Agent", namespace, doc["metadata"]["name"],
                        {"phase": "ERROR",
                         "detail": f"{type(err).__name__}: {err}"},
                    )

    async def run(
        self, *, interval: float = 2.0, stop: Optional[asyncio.Event] = None
    ) -> None:
        """The reconcile loop (reference: JOSDK event loop with
        ``InfiniteRetry`` — errors back off but never stop the operator)."""
        stop = stop or asyncio.Event()
        delay = interval
        while not stop.is_set():
            try:
                self.reconcile()
                delay = interval
            except Exception:  # noqa: BLE001
                logger.exception("operator sweep failed")
                delay = min(delay * 2, 60.0)
            try:
                await asyncio.wait_for(stop.wait(), timeout=delay)
            except asyncio.TimeoutError:
                pass


def _node_document(node: Any) -> Dict[str, Any]:
    doc = dataclasses.asdict(node)
    return doc


class KubernetesExecutor:
    """ApplicationExecutor that deploys by writing Application CRs —
    plugs the control plane into the operator (reference:
    ``KubernetesClusterRuntime.java:93-144`` writes CRs the same way)."""

    def __init__(self, kube: MockKubeApi, operator: Optional[Operator] = None):
        self.kube = kube
        self.operator = operator

    async def deploy(self, stored, application) -> None:
        cr = ApplicationCustomResource(
            name=stored.application_id,
            namespace=stored.tenant,
            application=stored.definition,
            instance=stored.instance,
            code_archive_id=stored.code_archive_id,
            checksum=stored.checksum,
        )
        self.kube.apply(cr.to_manifest())
        if self.operator is not None:
            self.operator.reconcile()

    async def delete(self, tenant: str, application_id: str) -> None:
        self.kube.delete("Application", tenant, application_id)
        if self.operator is not None:
            self.operator.delete_application(tenant, application_id)
            self.operator.reconcile()

    def logs(self, tenant: str, application_id: str) -> List[str]:
        out = []
        doc = self.kube.get("Application", tenant, application_id)
        if doc:
            out.append(f"application status: {doc.get('status', {})}")
        for agent in self.kube.list(
            "Agent", tenant, {_APP_LABEL: application_id}
        ):
            out.append(
                f"agent {agent['metadata']['name']}: {agent.get('status', {})}"
            )
        # real clusters: stream each runner pod's log tail (reference:
        # ApplicationResource.java:311-459)
        if hasattr(self.kube, "pod_logs"):
            for pod in self.kube.list(
                "Pod", tenant, {_APP_LABEL: application_id}
            ):
                name = pod["metadata"]["name"]
                out.append(f"--- pod {name} ---")
                out.append(self.kube.pod_logs(tenant, name))
        return out
