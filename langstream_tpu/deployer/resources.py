"""Kubernetes manifest generation for agent runners on TPU node pools.

Reference: ``AgentResourcesFactory.java`` (StatefulSet generation 136-311:
init containers, ports, PVC 356, probes 419-434, Secret 494-510,
parallelism→replicas 520-542) and ``AppResourcesFactory.java`` (setup Job
214, deployer Job 75). The TPU-native changes:

- ``resources.size`` means **TPU chips per replica** (the reference's
  abstract cpu/mem units); it maps to ``google.com/tpu`` resource requests
  plus GKE TPU node-pool selectors
  (``cloud.google.com/gke-tpu-accelerator``/``-topology``).
- replicas keep the reference's data-parallel semantics (one consumer
  group across replicas); each replica's chips form its ICI mesh for
  tensor/sequence parallelism, configured by the agent's ``mesh`` config.
- multi-host slices (chips > 8 on v5e) use a headless service +
  ``TPU_WORKER_HOSTNAMES`` so jax initializes the DCN mesh across the
  StatefulSet's pods — the SPMD sidecar pattern the reference never needed
  (SURVEY §7 hard part (e)).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from langstream_tpu.deployer.crds import (
    AgentCustomResource,
    ApplicationCustomResource,
)

DEFAULT_IMAGE = "langstream-tpu/runtime:latest"
AGENT_HTTP_PORT = 8080   # /metrics, /info (reference AgentRunner.java:99-113)
AGENT_SERVICE_PORT = 8000

# v5e chips → GKE topology string (per-host slices up to 8 chips; larger
# slices are multi-host: topology columns × rows, 4 chips per host).
_V5E_TOPOLOGY = {1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8", 64: "8x8"}


def tpu_topology(chips: int, accelerator: str = "tpu-v5-lite-podslice") -> Dict[str, str]:
    if chips not in _V5E_TOPOLOGY:
        raise ValueError(
            f"unsupported chips-per-replica {chips}; supported: "
            f"{sorted(_V5E_TOPOLOGY)}"
        )
    return {
        "cloud.google.com/gke-tpu-accelerator": accelerator,
        "cloud.google.com/gke-tpu-topology": _V5E_TOPOLOGY[chips],
    }


def hosts_per_replica(chips: int) -> int:
    return max(1, chips // 8) if chips >= 8 else 1


def _runtime_pod_configuration(agent: AgentCustomResource) -> Dict[str, Any]:
    """The mounted pod config (reference ``RuntimePodConfiguration`` read
    by ``AgentRunnerStarter.java:39``)."""
    return {
        "agentNode": agent.agent_node,
        "streamingCluster": agent.streaming_cluster,
        "resources": agent.resources,
        "applicationId": agent.application_id,
        "codeArchiveId": agent.code_archive_id,
        "tenant": agent.namespace,
    }


def generate_agent_secret(agent: AgentCustomResource) -> Dict[str, Any]:
    import base64

    payload = json.dumps(_runtime_pod_configuration(agent)).encode()
    return {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": {"name": agent.name, "namespace": agent.namespace},
        "data": {
            "pod-configuration.json": base64.b64encode(payload).decode()
        },
    }


def generate_statefulset(
    agent: AgentCustomResource,
    *,
    image: str = DEFAULT_IMAGE,
    accelerator: str = "tpu-v5-lite-podslice",
    code_storage_config: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    chips = agent.size
    labels = {
        "app": agent.name,
        "app.kubernetes.io/managed-by": "langstream-tpu",
        "langstream.tpu/application": agent.application_id,
    }
    volume_mounts = [
        {"name": "pod-config", "mountPath": "/app/config", "readOnly": True},
        {"name": "code", "mountPath": "/app/code"},
    ]
    volumes: List[Dict[str, Any]] = [
        {"name": "pod-config", "secret": {"secretName": agent.name}},
        {"name": "code", "emptyDir": {}},
    ]
    volume_claims: List[Dict[str, Any]] = []
    if agent.disk:
        # reference: DiskSpec → PVC (AgentResourcesFactory.java:356)
        volume_mounts.append(
            {"name": "state", "mountPath": "/app/state"}
        )
        claim_spec: Dict[str, Any] = {
            "accessModes": ["ReadWriteOnce"],
            "resources": {
                "requests": {"storage": agent.disk.get("size", "1Gi")}
            },
        }
        # omit, don't null: storageClassName: null means "delete the
        # field" in a strategic merge and fails schema validation —
        # absence is how "cluster default storage class" is spelled
        if agent.disk.get("type"):
            claim_spec["storageClassName"] = str(agent.disk["type"])
        volume_claims.append({
            "metadata": {"name": "state"},
            "spec": claim_spec,
        })

    container_resources: Dict[str, Any] = {}
    node_selector: Dict[str, str] = {}
    env = [
        {"name": "LANGSTREAM_POD_CONFIG",
         "value": "/app/config/pod-configuration.json"},
        {"name": "LANGSTREAM_CODE_DIR", "value": "/app/code"},
        {"name": "LANGSTREAM_STATE_DIR", "value": "/app/state"},
    ]
    if chips > 0:
        per_host = min(chips, 8) if chips >= 8 else chips
        container_resources = {
            "requests": {"google.com/tpu": str(per_host)},
            "limits": {"google.com/tpu": str(per_host)},
        }
        node_selector = tpu_topology(chips, accelerator)
    else:
        # size 0 = CPU-only agent (pure transforms / IO)
        container_resources = {
            "requests": {"cpu": "500m", "memory": "512Mi"},
        }

    init_containers = [{
        # reference: AgentCodeDownloader init container
        "name": "code-download",
        "image": image,
        "command": [
            "python", "-m", "langstream_tpu", "code-download",
            "--config", "/app/config/pod-configuration.json",
            "--target", "/app/code",
        ],
        "env": [{
            "name": "LANGSTREAM_CODE_STORAGE",
            "value": json.dumps(code_storage_config or {}),
        }],
        "volumeMounts": volume_mounts,
    }]

    probe = {
        "httpGet": {"path": "/info", "port": AGENT_HTTP_PORT},
        "initialDelaySeconds": 10,
        "periodSeconds": 10,
        "timeoutSeconds": 5,
    }

    hosts = hosts_per_replica(chips)
    replicas = agent.parallelism * hosts
    if hosts > 1:
        # all hosts of one replica must enter the same pjit program; the
        # runner derives its slice group from the ordinal (pods r*hosts ..
        # r*hosts+hosts-1 form replica r's DCN mesh)
        env.append({"name": "LANGSTREAM_HOSTS_PER_REPLICA", "value": str(hosts)})

    return {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {
            "name": agent.name,
            "namespace": agent.namespace,
            "labels": labels,
            "annotations": {"langstream.tpu/checksum": agent.checksum or ""},
        },
        "spec": {
            "replicas": replicas,
            "podManagementPolicy": "Parallel",
            "serviceName": agent.name,
            "selector": {"matchLabels": {"app": agent.name}},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "nodeSelector": node_selector,
                    "initContainers": init_containers,
                    "containers": [{
                        "name": "runner",
                        "image": image,
                        "command": [
                            "python", "-m", "langstream_tpu", "agent-runner",
                            "--config",
                            "/app/config/pod-configuration.json",
                        ],
                        "env": env,
                        "ports": [
                            {"name": "http", "containerPort": AGENT_HTTP_PORT},
                            {"name": "service",
                             "containerPort": AGENT_SERVICE_PORT},
                        ],
                        "resources": container_resources,
                        "livenessProbe": probe,
                        "readinessProbe": probe,
                        "volumeMounts": volume_mounts,
                    }],
                    "volumes": volumes,
                    "terminationGracePeriodSeconds": 75,  # > 60s drain
                },
            },
            "volumeClaimTemplates": volume_claims,
        },
    }


def generate_headless_service(agent: AgentCustomResource) -> Dict[str, Any]:
    """Headless service for the StatefulSet (stable DNS for multi-host
    DCN mesh bootstrap and the gateway's service-gateway proxy)."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": agent.name, "namespace": agent.namespace},
        "spec": {
            "clusterIP": "None",
            "selector": {"app": agent.name},
            "ports": [
                {"name": "http", "port": AGENT_HTTP_PORT},
                {"name": "service", "port": AGENT_SERVICE_PORT},
            ],
        },
    }


# kept under its factory-style alias used by the package __init__
generate_gateway_service = generate_headless_service


def _job(name: str, namespace: str, command: List[str], image: str,
         app: ApplicationCustomResource) -> Dict[str, Any]:
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "backoffLimit": 6,
            "template": {
                "metadata": {"labels": {"job-name": name}},
                "spec": {
                    "restartPolicy": "OnFailure",
                    "containers": [{
                        "name": "main",
                        "image": image,
                        "command": command,
                        "env": [{
                            "name": "LANGSTREAM_APPLICATION",
                            "value": json.dumps(app.to_manifest()["spec"]),
                        }],
                    }],
                },
            },
        },
    }


def generate_setup_job(
    app: ApplicationCustomResource, *, image: str = DEFAULT_IMAGE
) -> Dict[str, Any]:
    """Topics + assets setup (reference ``AppResourcesFactory.java:214`` →
    ``ApplicationSetupRunner``)."""
    return _job(
        f"{app.name}-setup", app.namespace,
        ["python", "-m", "langstream_tpu", "application-setup"],
        image, app,
    )


def generate_deployer_job(
    app: ApplicationCustomResource, *, image: str = DEFAULT_IMAGE,
    delete: bool = False,
) -> Dict[str, Any]:
    """Plan build + agent-CR writes (reference ``AppResourcesFactory.java:75``
    → ``RuntimeDeployer``)."""
    suffix = "cleanup" if delete else "deployer"
    command = ["python", "-m", "langstream_tpu", "deployer"]
    if delete:
        command.append("--delete")
    return _job(f"{app.name}-{suffix}", app.namespace, command, image, app)
