"""Retrace-count budget rule (PR 13 REMAINING → ISSUE 14 satellite).

Every engine dispatch builder (``_get_decode``, ``_get_mixed``, ...)
memoizes its jitted closure per static key — ``_get_decode(8)`` must
return the SAME function object every call. A builder that rebuilds the
closure hands XLA a fresh Python callable per dispatch: jax's jit cache
keys on function identity, so the same program is lowered (and, cache
miss by cache miss, compiled) again and again — a silent serving stall
that no output ever betrays, because the re-lowered program computes the
identical thing. The rule name is the budget: ONE lowering per
(builder, static key).

Two checks, both over the engine's own :meth:`_variant_jobs` contract
(the single source of truth for what the engine can ever dispatch):

- ``retrace-budget`` per builder accessor: calling the accessor twice
  with the same static key must return the identical object. A second
  object is a second static closure over the same dispatch — exactly
  "lowered more than once with different static closures".
- ``retrace-budget`` over ``_variant_jobs()`` called twice: the fn in
  every job slot must be pairwise identical (catches a broken memo in
  any builder the accessor list does not name, since ``_variant_jobs``
  calls them all).

The pass builds tiny never-started CPU engines (no jit is ever lowered
— only Python object identity is inspected), so it runs in seconds and
rides the pre-commit ``langstream-tpu check --skip hlo`` gate.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from langstream_tpu.analysis.common import Finding

RULE = "retrace-budget"


def _builder_probes(engine) -> List[Tuple[str, Callable[[], Any]]]:
    """(name, zero-arg accessor) per cached dispatch builder this
    engine configuration actually serves through — mirrors the builder
    set :meth:`DecodeEngine._variant_jobs` drives."""
    probes: List[Tuple[str, Callable[[], Any]]] = []
    if getattr(engine, "mixed", False):
        for width in engine._mixed_widths:
            probes.append(
                (f"_get_mixed({width})",
                 lambda w=width: engine._get_mixed(w))
            )
    else:
        bucket = min(engine.prefill_buckets)
        probes.append(
            (f"_get_prefill({bucket})",
             lambda b=bucket: engine._get_prefill(b))
        )
        probes.append(
            (f"_get_prefill_offset({bucket})",
             lambda b=bucket: engine._get_prefill_offset(b))
        )
    for steps in sorted({1, engine.decode_chunk}):
        probes.append(
            (f"_get_decode({steps})",
             lambda s=steps: engine._get_decode(s))
        )
    if getattr(engine, "paged", False):
        probes.append(("_get_block_copy()", engine._get_block_copy))
        # KV-handoff gather/scatter (ISSUE 15): memoized per pow2-
        # padded chain width — a broken memo would re-lower the import
        # scatter on EVERY handoff admission
        for width in (1, 4):
            probes.append(
                (f"_get_handoff_export({width})",
                 lambda w=width: engine._get_handoff_export(w))
            )
            probes.append(
                (f"_get_handoff_import({width})",
                 lambda w=width: engine._get_handoff_import(w))
            )
    elif engine.prefix_cache:
        bucket = min(engine.prefill_buckets)
        probes.append(
            (f"_get_copy_prefix({bucket})",
             lambda b=bucket: engine._get_copy_prefix(b))
        )
    return probes


def check_engine(engine, config_name: str = "") -> List[Finding]:
    """Evaluate the retrace budget against one engine. Pure host-side
    object-identity checks — nothing is lowered or compiled."""
    findings: List[Finding] = []
    prefix = f"{config_name}:" if config_name else ""
    for name, probe in _builder_probes(engine):
        first, second = probe(), probe()
        if first is not second:
            findings.append(
                Finding(
                    RULE, f"<retrace:{prefix}{name}>", 0,
                    f"{name} returned a NEW jit closure on the second "
                    "call — the builder memo is broken, so every "
                    "dispatch re-lowers (and cold-cache recompiles) "
                    "the same program under a different static closure",
                )
            )
    jobs_a = engine._variant_jobs()
    jobs_b = engine._variant_jobs()
    if len(jobs_a) != len(jobs_b):
        findings.append(
            Finding(
                RULE, f"<retrace:{prefix}_variant_jobs>", 0,
                f"_variant_jobs() is unstable: {len(jobs_a)} jobs on "
                f"the first call vs {len(jobs_b)} on the second — "
                "precompile and the HLO lint would cover different "
                "programs than the ones serving traffic",
            )
        )
        return findings
    for index, ((fn_a, avals), (fn_b, _)) in enumerate(zip(jobs_a, jobs_b)):
        if fn_a is not fn_b:
            shapes = ", ".join(
                str(getattr(a, "shape", "?")) for a in avals[2:5]
            )
            findings.append(
                Finding(
                    RULE, f"<retrace:{prefix}job[{index}]>", 0,
                    f"_variant_jobs()[{index}] (args {shapes}, ...) "
                    "resolved to a different fn object on the second "
                    "call — some builder in the job list rebuilds its "
                    "closure per call and will be lowered more than "
                    "once for the same static key",
                )
            )
    return findings


# the cheap retrace matrix: two engines cover every builder family —
# dense (bucketed prefill lattice + prefix copy + plain decode) and
# paged/fused/mixed/spec (mixed width ladder + spec decode scan +
# block copy). Kept smaller than the HLO matrix on purpose: this pass
# rides the pre-commit gate, so construction cost is the budget.
def default_matrix() -> List[Tuple[str, Dict[str, Any]]]:
    paged = dict(kv_layout="paged", kv_block_size=8)
    return [
        ("dense-tp1", {}),
        # kv_host_blocks rides the paged leg: the tier's demote gather
        # and promote scatter ARE the handoff export/import builders,
        # so this sweeps their memos on the exact engine shape the
        # tiered pool serves through (a broken memo would re-lower the
        # H2D scatter on every promotion)
        ("paged-fused-mixed-spec-tp1",
         dict(paged, paged_kernel="fused", prefill_mode="mixed",
              prefill_chunk=16, spec_decode="ngram", spec_k=2,
              kv_host_blocks=16)),
    ]


def run_retrace_pass(
    matrix: Optional[List[Tuple[str, Dict[str, Any]]]] = None,
    progress: Optional[Callable[[str], None]] = None,
) -> List[Finding]:
    """Evaluate the retrace budget across the engine matrix. Engines
    are constructed but never started and retired from the /metrics
    registry afterwards (same discipline as the HLO pass)."""
    from langstream_tpu.analysis.hlo_lint import build_engine

    findings: List[Finding] = []
    for name, overrides in matrix if matrix is not None else default_matrix():
        if progress:
            progress(f"retrace: probing {name}")
        engine = build_engine(overrides)
        try:
            findings.extend(check_engine(engine, config_name=name))
        finally:
            engine.retire()
    return findings
