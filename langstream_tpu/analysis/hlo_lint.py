"""Compiled-HLO invariant engine: the rule library the engine-dispatch
tests and ``langstream-tpu check`` share.

Three PRs in a row copy-pasted the same ``lower(...).as_text()`` scans
(``tests/test_multichip_paged.py``, ``tests/test_mixed_dispatch.py``,
``tests/test_paged_kernel.py``); this module owns the scans so the
assertions cannot drift apart, and adds a config-matrix driver that
evaluates every rule against every engine dispatch builder
(dense/paged × fused/reference × tp ∈ {1, 2} × spec × mixed).

Rule catalog (docs/analysis.md):

- ``no-full-pool-all-gather`` (compiled HLO, paged × tp>1) — no
  ``all-gather`` whose result is a FULL (unsharded) pool block: that
  collective is exactly the tp× HBM blow-up the sharding constraints on
  ``paged_write_rows`` / ``_get_block_copy`` exist to forbid.
  Activation-level collectives (einsum partials) are expected and pass.
- ``no-pool-shaped-gather`` (lowered StableHLO, paged × fused) — no
  ``gather`` whose operand is the per-layer pool: the signature of the
  reference leg's materialized ``gather_blocks`` copy (3× KV traffic)
  leaking back into a fused dispatch.
- ``donation-respected`` (compiled HLO) — every dispatch aliases at
  least its donated cache buffers (``input_output_alias`` present): a
  dropped donation silently doubles peak cache memory.
- ``collective-census`` (compiled HLO) — per-dispatch counts of
  all-gather / all-reduce / reduce-scatter / collective-permute /
  all-to-all; on a tp=1 mesh ANY cross-partition collective is a
  finding (there is nothing to communicate with).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from langstream_tpu.analysis.common import Finding


# ---------------------------------------------------------------------- #
# text scans (pure string → lines; unit-testable without an engine)
# ---------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class PoolDims:
    """The paged pool's per-layer block shape [N, Bs, KVH, D]."""

    num_blocks: int
    block_size: int
    kv_heads: int
    head_dim: int
    dtype: str = "f32"  # stablehlo element type of the pool


def pool_dims(engine) -> PoolDims:
    config = engine.config
    return PoolDims(
        num_blocks=engine.num_blocks,
        block_size=engine.block_size,
        kv_heads=config.num_kv_heads,
        head_dim=config.dims_per_head,
        dtype="i8" if engine.kv_quant else "f32",
    )


def full_pool_allgather_lines(text: str, dims: PoolDims) -> List[str]:
    """Compiled (post-SPMD) HLO lines all-gathering a full pool block.
    Post-SPMD HLO spells shapes with comma-separated dims; the full
    (unsharded) per-layer pool is [N, Bs, KVH, D] and the layer-stacked
    one [L, N, Bs, KVH, D] — both contain this run."""
    pattern = (
        f"{dims.num_blocks},{dims.block_size},"
        f"{dims.kv_heads},{dims.head_dim}"
    )
    return [
        line for line in text.splitlines()
        if "all-gather" in line and pattern in line
    ]


def pool_gather_lines(text: str, dims: PoolDims) -> List[str]:
    """Lowered StableHLO lines gathering the per-layer pool
    [N, Bs, KVH, D] — the signature of the reference's materialized
    ``gather_blocks`` copy. Other gathers (embedding lookup, table row
    lookup) have different operand shapes and don't count."""
    pool_type = (
        f"{dims.num_blocks}x{dims.block_size}"
        f"x{dims.kv_heads}x{dims.head_dim}x{dims.dtype}"
    )
    return [
        line for line in text.splitlines()
        if "gather" in line and pool_type in line
    ]


def pool_shaped_return_lines(text: str, dims: PoolDims) -> List[str]:
    """Lowered StableHLO ``return`` lines carrying a full-pool-shaped
    tensor [.., N, Bs, KVH, D]. On the tier demote gather this is the
    bug the bounded-tier-transfer rule exists for: the D2H payload the
    host arena reads back would be the ENTIRE pool, not the evicted
    block's rows."""
    pool_type = (
        f"{dims.num_blocks}x{dims.block_size}"
        f"x{dims.kv_heads}x{dims.head_dim}x{dims.dtype}"
    )
    return [
        line for line in text.splitlines()
        if line.strip().startswith("return") and pool_type in line
    ]


def unaliased_pool_param_chunks(text: str, dims: PoolDims) -> List[str]:
    """``@main`` parameters of a lowered dispatch that are pool-shaped
    but NOT donation-aliased (no ``tf.aliasing_output`` attr). On the
    tier promote scatter every pool-shaped input must be the donated
    cache itself — an unaliased one is an H2D upload of a whole pool
    per promotion. Returns a truncated chunk per offending param."""
    pool_type = (
        f"{dims.num_blocks}x{dims.block_size}"
        f"x{dims.kv_heads}x{dims.head_dim}x{dims.dtype}"
    )
    start = text.find("@main(")
    if start < 0:
        return []
    arrow = text.find("->", start)
    end = arrow if arrow > 0 else text.find("{", start)
    header = text[start:end] if end > 0 else text[start:]
    return [
        ("%arg" + chunk.strip().rstrip(", "))[:120]
        for chunk in header.split("%arg")[1:]
        if pool_type in chunk and "aliasing_output" not in chunk
    ]


_COLLECTIVE_RE = re.compile(
    r"=\s+\S*\s*(all-gather|all-reduce|reduce-scatter|"
    r"collective-permute|all-to-all)"
)


def collective_census(text: str) -> Dict[str, int]:
    """Per-op counts of cross-partition collectives in compiled HLO
    (op-definition lines only, not metadata mentions)."""
    census: Dict[str, int] = {}
    for line in text.splitlines():
        match = _COLLECTIVE_RE.search(line)
        if match:
            census[match.group(1)] = census.get(match.group(1), 0) + 1
    return census


def donation_alias_present(text: str) -> bool:
    """Compiled HLO advertises buffer donation in the module header
    (``input_output_alias={ {0}: (1, {}, may-alias) ... }``). An EMPTY
    alias map does not count — that is exactly the dropped-donation
    failure this rule exists to catch."""
    stripped = text.replace(" ", "")
    marker = "input_output_alias={"
    index = stripped.find(marker)
    return index >= 0 and not stripped[index + len(marker):].startswith("}")


# ---------------------------------------------------------------------- #
# engine plumbing (the helpers the tests import)
# ---------------------------------------------------------------------- #
def variant_avals(engine, fn) -> Tuple[Any, Tuple[Any, ...]]:
    """The (fn, arg avals) pair ``engine._variant_jobs()`` lowers this
    dispatch with — the same avals precompile uses, so the linted HLO is
    the HLO that serves."""
    jobs = [(f, a) for f, a in engine._variant_jobs() if f is fn]
    assert jobs, "variant not in the engine's job list"
    return jobs[0]


def lowered_text(engine, fn) -> str:
    """StableHLO text of a jitted engine variant (pre-compile)."""
    fn, avals = variant_avals(engine, fn)
    with engine.mesh:
        return fn.lower(*avals).as_text()


def compiled_text(engine, fn) -> str:
    """Post-SPMD compiled HLO text of a jitted engine variant."""
    fn, avals = variant_avals(engine, fn)
    with engine.mesh:
        return fn.lower(*avals).compile().as_text()


def tier_transfer_avals(engine, width: int):
    """(params, cache, blocks, payload) avals for the tier-transfer
    jits at ``width`` — mirrors what ``_demote_block_data`` and
    ``_promote_host_chain`` pass. These builders live outside
    ``_variant_jobs`` (the export's arg order breaks its params/cache
    contract), so the lint supplies their avals directly."""
    import jax
    import jax.numpy as jnp

    def aval(x):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)

    params_aval = jax.tree_util.tree_map(aval, engine.params)
    cache_aval = jax.tree_util.tree_map(aval, engine.cache)
    blocks_aval = jax.ShapeDtypeStruct((width,), jnp.int32)
    payload_aval = jax.tree_util.tree_map(
        lambda c: jax.ShapeDtypeStruct(
            (c.shape[0], width) + c.shape[2:], c.dtype
        ),
        cache_aval,
    )
    return params_aval, cache_aval, blocks_aval, payload_aval


def named_dispatches(engine) -> Dict[str, Any]:
    """The curated dispatch set every rule is evaluated on: the builders
    an engine of this configuration actually serves traffic through.
    Values are either a jitted fn (avals resolved through the engine's
    ``_variant_jobs`` contract) or an explicit ``(fn, avals)`` pair for
    builders that live outside the serving job list."""
    out: Dict[str, Any] = {}
    if getattr(engine, "mixed", False):
        for width in engine._mixed_widths:
            out[f"mixed[{width}]"] = engine._get_mixed(width)
    else:
        bucket = min(engine.prefill_buckets)
        out[f"prefill[{bucket}]"] = engine._get_prefill(bucket)
        out[f"prefill_offset[{bucket}]"] = engine._get_prefill_offset(bucket)
    out["decode[1]"] = engine._get_decode(1)
    if engine.decode_chunk != 1:
        out[f"decode[{engine.decode_chunk}]"] = engine._get_decode(
            engine.decode_chunk
        )
    if getattr(engine, "paged", False):
        out["block_copy"] = engine._get_block_copy()
        if getattr(engine, "kv_host_arena", None) is not None:
            # tier data plane (host-DRAM demotion): the demote gather
            # produces every D2H payload, the promote scatter consumes
            # every H2D one. Width 1 is the shape single-block demotion
            # always dispatches; wider promotions are the same program
            # modulo the leading dim, so one width lints the family.
            params_aval, cache_aval, blocks, payload = tier_transfer_avals(
                engine, 1
            )
            out["host_demote_gather[1]"] = (
                engine._get_handoff_export(1), (cache_aval, blocks)
            )
            out["host_promote_scatter[1]"] = (
                engine._get_handoff_import(1),
                (params_aval, cache_aval, blocks, payload),
            )
    return out


# ---------------------------------------------------------------------- #
# rules
# ---------------------------------------------------------------------- #
@dataclasses.dataclass
class HloRule:
    name: str
    needs: str  # "lowered" | "compiled"
    description: str
    applies: Callable[[Any], bool]
    check: Callable[[Any, str, str], List[Finding]]


def _tp(engine) -> int:
    return dict(engine.mesh.shape).get("tp", 1)


def _rule_no_full_pool_all_gather(engine, dispatch: str, text: str):
    dims = pool_dims(engine)
    lines = full_pool_allgather_lines(text, dims)
    if not lines:
        return []
    return [
        Finding(
            "no-full-pool-all-gather", f"<hlo:{dispatch}>", 0,
            f"tp={_tp(engine)} {dispatch} gathers a full pool block "
            f"[{dims.num_blocks},{dims.block_size},{dims.kv_heads},"
            f"{dims.head_dim}] — the tp× HBM blow-up the kv-shard "
            "constraints forbid:\n" + "\n".join(lines[:4]),
        )
    ]


def _is_tier_transfer(dispatch: str) -> bool:
    return (
        "host_demote_gather" in dispatch
        or "host_promote_scatter" in dispatch
    )


def _rule_no_pool_shaped_gather(engine, dispatch: str, text: str):
    if _is_tier_transfer(dispatch):
        # gathering/scattering pool rows IS these dispatches' job; the
        # bounded-tier-transfer rule polices their payload shape instead
        return []
    dims = pool_dims(engine)
    lines = pool_gather_lines(text, dims)
    if not lines:
        return []
    return [
        Finding(
            "no-pool-shaped-gather", f"<hlo:{dispatch}>", 0,
            f"fused {dispatch} still gathers the pool (the reference "
            "leg's 3x-KV-traffic copy):\n" + "\n".join(lines[:4]),
        )
    ]


def _rule_donation_respected(engine, dispatch: str, text: str):
    if "host_demote_gather" in dispatch:
        # deliberately undonated: the demoted chain is still published
        # and serving while its rows are read out, so the pool must
        # survive the gather (the export builder's own contract)
        return []
    if donation_alias_present(text):
        return []
    return [
        Finding(
            "donation-respected", f"<hlo:{dispatch}>", 0,
            f"{dispatch} compiled without any input/output alias — the "
            "donated cache is being copied, doubling peak cache memory",
        )
    ]


def _rule_collective_census(engine, dispatch: str, text: str):
    census = collective_census(text)
    if _tp(engine) > 1 or not census:
        return []  # tp>1 collectives are reported, not flagged
    detail = ", ".join(f"{op}×{n}" for op, n in sorted(census.items()))
    return [
        Finding(
            "collective-census", f"<hlo:{dispatch}>", 0,
            f"tp=1 {dispatch} contains cross-partition collectives "
            f"({detail}) — on a single-shard mesh there is nothing to "
            "communicate with",
        )
    ]


def _rule_bounded_tier_transfer(engine, dispatch: str, text: str):
    dims = pool_dims(engine)
    if "host_demote_gather" in dispatch:
        lines = pool_shaped_return_lines(text, dims)
        if not lines:
            return []
        return [
            Finding(
                "bounded-tier-transfer", f"<hlo:{dispatch}>", 0,
                f"{dispatch} returns a full-pool-shaped payload — every "
                "demotion on the steady-state decode path would ship "
                f"the ENTIRE [{dims.num_blocks},{dims.block_size},"
                f"{dims.kv_heads},{dims.head_dim}] pool over D2H "
                "instead of the evicted block's rows:\n"
                + "\n".join(lines[:4]),
            )
        ]
    if "host_promote_scatter" in dispatch:
        chunks = unaliased_pool_param_chunks(text, dims)
        if not chunks:
            return []
        return [
            Finding(
                "bounded-tier-transfer", f"<hlo:{dispatch}>", 0,
                f"{dispatch} takes a pool-shaped input WITHOUT a "
                "donation alias — each promotion would upload a whole "
                "pool over H2D instead of writing the chain's rows "
                "into the donated cache:\n" + "\n".join(chunks[:4]),
            )
        ]
    return []


RULES: List[HloRule] = [
    HloRule(
        "no-full-pool-all-gather", "compiled",
        "no all-gather materializes a full (unsharded) pool block",
        applies=lambda e: getattr(e, "paged", False) and _tp(e) > 1,
        check=_rule_no_full_pool_all_gather,
    ),
    HloRule(
        "no-pool-shaped-gather", "lowered",
        "fused paged dispatches contain no pool-shaped gather",
        applies=lambda e: (
            getattr(e, "paged", False) and e.paged_kernel == "fused"
        ),
        check=_rule_no_pool_shaped_gather,
    ),
    HloRule(
        "donation-respected", "compiled",
        "every dispatch aliases its donated cache buffers",
        applies=lambda e: True,
        check=_rule_donation_respected,
    ),
    HloRule(
        "collective-census", "compiled",
        "collective op counts per dispatch; any collective on tp=1 fails",
        applies=lambda e: True,
        check=_rule_collective_census,
    ),
    HloRule(
        "bounded-tier-transfer", "lowered",
        "tier transfers move width-bounded rows, never a full pool "
        "(demote gather returns no pool-shaped payload; promote "
        "scatter's only pool-shaped input is the donated cache)",
        applies=lambda e: (
            getattr(e, "paged", False)
            and getattr(e, "kv_host_arena", None) is not None
        ),
        check=_rule_bounded_tier_transfer,
    ),
]


def check_engine(
    engine,
    dispatches: Optional[Dict[str, Any]] = None,
    rules: Optional[List[HloRule]] = None,
    config_name: str = "",
) -> Tuple[List[Finding], Dict[str, Dict[str, int]]]:
    """Evaluate the rule library against one engine's dispatch builders.
    Returns (findings, census-per-dispatch). Lowered text is always
    produced; compiled text only when a compiled-HLO rule applies (the
    compile is the expensive step)."""
    rules = RULES if rules is None else rules
    dispatches = named_dispatches(engine) if dispatches is None else dispatches
    active = [r for r in rules if r.applies(engine)]
    findings: List[Finding] = []
    census: Dict[str, Dict[str, int]] = {}
    prefix = f"{config_name}:" if config_name else ""
    for name, fn in dispatches.items():
        # lower ONCE per dispatch; both text forms derive from the same
        # Lowered object (re-tracing for the compiled form would double
        # the matrix's trace time)
        texts: Dict[str, str] = {}
        if active:
            if isinstance(fn, tuple):  # (fn, avals) — outside _variant_jobs
                jit_fn, avals = fn
            else:
                jit_fn, avals = variant_avals(engine, fn)
            with engine.mesh:
                lowered = jit_fn.lower(*avals)
                if any(r.needs == "lowered" for r in active):
                    texts["lowered"] = lowered.as_text()
                if any(r.needs == "compiled" for r in active):
                    texts["compiled"] = lowered.compile().as_text()
        if "compiled" in texts:
            census[prefix + name] = collective_census(texts["compiled"])
        for rule in active:
            for finding in rule.check(engine, prefix + name, texts[rule.needs]):
                findings.append(finding)
    return findings, census


# ---------------------------------------------------------------------- #
# config-matrix driver (`langstream-tpu check --hlo`)
# ---------------------------------------------------------------------- #
def default_matrix(device_count: int) -> List[Tuple[str, Dict[str, Any]]]:
    """The engine configurations worth linting: every serving-relevant
    combination of layout × kernel × tp × spec × mixed that differs at
    the HLO level. tp=2 legs need ≥2 devices (CI forces an 8-device
    virtual CPU mesh; a 1-chip host just skips them)."""
    paged = dict(kv_layout="paged", kv_block_size=8)
    matrix: List[Tuple[str, Dict[str, Any]]] = [
        ("dense-tp1", {}),
        ("paged-fused-tp1", dict(paged, paged_kernel="fused")),
        ("paged-reference-tp1", dict(paged, paged_kernel="reference")),
        ("paged-fused-spec-tp1",
         dict(paged, paged_kernel="fused", spec_decode="ngram", spec_k=2)),
        ("paged-fused-mixed-tp1",
         dict(paged, paged_kernel="fused", prefill_mode="mixed",
              prefill_chunk=16)),
        # host-DRAM demotion tier: adds the demote gather / promote
        # scatter dispatches and arms the bounded-tier-transfer rule
        ("paged-fused-tiered-tp1",
         dict(paged, paged_kernel="fused", kv_host_blocks=16)),
    ]
    if device_count >= 2:
        matrix += [
            ("paged-fused-tp2", dict(paged, paged_kernel="fused", tp=2)),
            ("paged-fused-mixed-tp2",
             dict(paged, paged_kernel="fused", prefill_mode="mixed",
                  prefill_chunk=16, tp=2)),
        ]
    return matrix


def build_engine(overrides: Dict[str, Any]):
    """A tiny CPU-lintable engine for one matrix entry. Fused paged
    kernels gate on the Pallas interpret hook off-TPU, exactly like the
    engine-dispatch tests."""
    import dataclasses as _dc

    from langstream_tpu.parallel.mesh import MeshConfig
    from langstream_tpu.providers.jax_local.engine import DecodeEngine
    from langstream_tpu.providers.jax_local.model import (
        LlamaConfig,
        init_params,
    )

    overrides = dict(overrides)
    tp = overrides.pop("tp", 1)
    config = LlamaConfig.tiny(max_seq_len=128)
    if overrides.get("paged_kernel") == "fused":
        config = _dc.replace(config, flash_interpret=True)
    params = init_params(config)
    kwargs: Dict[str, Any] = dict(
        max_slots=4, max_seq_len=128, prefill_buckets=[16, 32],
        decode_chunk=4,
    )
    kwargs.update(overrides)
    if tp > 1:
        kwargs["mesh_config"] = MeshConfig(tp=tp)
    return DecodeEngine(config, params, **kwargs)


def run_hlo_pass(
    matrix: Optional[List[Tuple[str, Dict[str, Any]]]] = None,
    progress: Optional[Callable[[str], None]] = None,
) -> Tuple[List[Finding], Dict[str, Dict[str, int]]]:
    """Evaluate the rule library across the engine config matrix.
    Engines are constructed but never started (lowering needs no device
    thread) and retired from the /metrics registry afterwards."""
    import jax

    matrix = default_matrix(len(jax.devices())) if matrix is None else matrix
    findings: List[Finding] = []
    census: Dict[str, Dict[str, int]] = {}
    for name, overrides in matrix:
        if progress:
            progress(f"hlo: linting {name}")
        engine = build_engine(overrides)
        try:
            engine_findings, engine_census = check_engine(
                engine, config_name=name
            )
            findings.extend(engine_findings)
            census.update(engine_census)
        finally:
            engine.retire()
    return findings, census
