"""Shared scaffolding for the AST passes: findings, comment extraction,
and the one suppression grammar.

A finding names (rule, file, line, message). Suppressions are explicit
and auditable — the grammar REQUIRES a reason so a clean run documents
every accepted risk:

    x = self._cache[key]  # lint: allow(guarded-by-violation) -- benign
                          #   stale read; writer holds the lock

An allow comment covers its own line and the next code line; when that
next line opens a ``def`` or ``class``, it covers the whole definition
(method-level suppression for e.g. a drain method that runs only after
the owning thread is joined). An allow WITHOUT a reason is itself a
finding (``suppression-missing-reason``) — the audit trail is the point.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def format(self) -> str:
        mark = " (suppressed: %s)" % self.reason if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{mark}"


def iter_py_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted .py file list (skipping
    __pycache__ and anything that is not Python source). A path that
    does not exist raises — a typo'd `langstream-tpu check <path>` must
    fail loudly, not report CLEAN over zero files."""
    out: List[str] = []
    for path in paths:
        if not os.path.exists(path):
            raise FileNotFoundError(f"no such file or directory: {path!r}")
        if os.path.isfile(path):
            if path.endswith(".py"):
                out.append(path)
            continue
        for root, dirs, names in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for name in sorted(names):
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
    return sorted(set(out))


def file_comments(source: str) -> Dict[int, str]:
    """``line -> comment text`` (without the leading ``#``) via tokenize,
    so strings containing ``#`` never read as comments. A file with a
    tokenization error (analyzed before it parses) yields no comments —
    the AST pass will report the syntax error instead."""
    comments: Dict[int, str] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for token in tokens:
            if token.type == tokenize.COMMENT:
                text = token.string.lstrip("#").strip()
                line = token.start[0]
                # two comment tokens on consecutive wrapped lines of one
                # block each keep their own line number
                comments[line] = (
                    comments[line] + " " + text if line in comments else text
                )
    except tokenize.TokenError:
        pass
    return comments


_ALLOW_RE = re.compile(
    r"lint:\s*allow\(\s*([\w\-, ]+?)\s*\)\s*(?:--\s*(.+))?$"
)


class Suppressions:
    """Per-file suppression index built from the comments + the AST (the
    AST supplies def/class spans for definition-level allows)."""

    def __init__(self, source: str, tree: Optional[ast.AST] = None) -> None:
        comments = file_comments(source)
        if tree is None:
            try:
                tree = ast.parse(source)
            except SyntaxError:
                tree = ast.Module(body=[], type_ignores=[])
        code_lines = sorted(
            {
                node.lineno
                for node in ast.walk(tree)
                if hasattr(node, "lineno")
            }
        )
        spans: List[Tuple[int, int]] = [
            (
                min(
                    [node.lineno]
                    + [d.lineno for d in node.decorator_list]
                ),
                node.end_lineno or node.lineno,
            )
            for node in ast.walk(tree)
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            )
        ]
        # rule -> sorted covered line ranges, with reasons per anchor
        self._covered: Dict[str, List[Tuple[int, int, str]]] = {}
        self.missing_reason: List[int] = []
        for line, text in sorted(comments.items()):
            match = _ALLOW_RE.search(text)
            if not match:
                continue
            rules = [r.strip() for r in match.group(1).split(",") if r.strip()]
            reason = (match.group(2) or "").strip()
            if not reason:
                self.missing_reason.append(line)
            anchor = line
            following = [l for l in code_lines if l > line]
            nxt = following[0] if following else line
            end = max(line, nxt)
            # definition-level: the allow covers the whole def/class it
            # introduces
            for start, stop in spans:
                if start == nxt:
                    end = max(end, stop)
            for rule in rules:
                self._covered.setdefault(rule, []).append(
                    (anchor, end, reason)
                )

    def lookup(self, rule: str, line: int) -> Optional[str]:
        """Reason string when (rule, line) is suppressed, else None."""
        for start, end, reason in self._covered.get(rule, []):
            if start <= line <= end:
                return reason or "(no reason given)"
        return None

    def apply(self, finding: Finding) -> Finding:
        reason = self.lookup(finding.rule, finding.line)
        if reason is not None:
            finding.suppressed = True
            finding.reason = reason
        return finding


def attach_comment_annotations(
    pattern: "re.Pattern[str]",
    comments: Dict[int, str],
    tree: ast.AST,
) -> Dict[int, "re.Match[str]"]:
    """Match annotation comments and key each by the code line it
    annotates: the comment's own line when code shares it, else the next
    code line (standalone comment above the statement)."""
    code_lines = sorted(
        {node.lineno for node in ast.walk(tree) if hasattr(node, "lineno")}
    )
    out: Dict[int, "re.Match[str]"] = {}
    code_set = set(code_lines)
    for line, text in comments.items():
        match = pattern.search(text)
        if not match:
            continue
        if line in code_set:
            out[line] = match
        else:
            following = [l for l in code_lines if l > line]
            if following:
                out[following[0]] = match
    return out


def parse_file(path: str) -> Tuple[str, Optional[ast.AST], List[Finding]]:
    """Read + parse one file; a syntax error becomes a finding instead of
    an analyzer crash."""
    with open(path, encoding="utf-8") as handle:
        source = handle.read()
    try:
        return source, ast.parse(source), []
    except SyntaxError as error:
        return source, None, [
            Finding(
                "syntax-error", path, error.lineno or 0,
                f"file does not parse: {error.msg}",
            )
        ]


def finalize(
    findings: Iterable[Finding], suppressions: Suppressions, path: str
) -> List[Finding]:
    """Apply suppressions and surface reason-less allows."""
    out = [suppressions.apply(f) for f in findings]
    for line in suppressions.missing_reason:
        out.append(
            Finding(
                "suppression-missing-reason", path, line,
                "lint: allow(...) without a '-- reason' — suppressions "
                "must document why the finding is acceptable",
            )
        )
    return out
