"""Static-analysis passes for the serving runtime (`langstream-tpu check`).

Four passes, one Finding vocabulary, one suppression grammar
(docs/analysis.md):

- :mod:`.lock_discipline` — AST lock/thread-ownership checking driven by
  ``# guarded-by:`` / ``# owned-by:`` attribute annotations on the
  threaded classes (engine device thread, supervisor, watchdog, fleet
  router, mirror, flight recorder, metrics registry ...).
- :mod:`.jit_hazards` — host-sync and retrace hazards in functions
  reachable from ``jax.jit`` / ``shard_map`` call sites (tracer
  ``.item()``/``float()``/``np.asarray``, Python branching on runtime
  tensor values, closure-captured mutable config).
- :mod:`.hlo_lint` — the compiled/lowered-HLO invariant rule library
  (no-full-pool-all-gather, no-pool-shaped-gather, donation-respected,
  collective census) shared by the engine-dispatch tests and the
  ``langstream-tpu check`` config-matrix driver.
- :mod:`.retrace` — the retrace-count budget: every engine dispatch
  builder returns the identical jit closure per static key (probed
  twice over ``_variant_jobs`` on tiny never-started engines — a
  broken memo re-lowers the same program per dispatch).

Every PR since the paged pool landed had re-implemented the HLO scans by
copy-paste and re-found lock bugs by review; these passes make both
machine-checked (ISSUE 13).
"""

from langstream_tpu.analysis.common import (  # noqa: F401
    Finding,
    iter_py_files,
)
from langstream_tpu.analysis.jit_hazards import run_jit_pass  # noqa: F401
from langstream_tpu.analysis.lock_discipline import run_lock_pass  # noqa: F401
from langstream_tpu.analysis.retrace import run_retrace_pass  # noqa: F401

__all__ = [
    "Finding",
    "iter_py_files",
    "run_jit_pass",
    "run_lock_pass",
    "run_retrace_pass",
]
