"""Lock-discipline pass: annotation-driven AST checking of the threaded
classes.

Annotation grammar (full catalog in docs/analysis.md):

- ``# guarded-by: <lock>`` on an attribute assignment: every read AND
  write of ``self.<attr>`` anywhere in the class must happen inside a
  ``with self.<lock>:`` block (or in a method annotated
  ``# requires-lock: <lock>`` — the caller holds it).
- ``# guarded-by: <lock> (writes)``: only writes need the lock; lock-free
  reads are declared stale-tolerant (single-word snapshots a reader may
  observe one update late — e.g. a 503-availability check that must not
  block behind a multi-second rebuild held under the lock).
- ``# owned-by: <method>`` on an attribute assignment: the attribute is
  thread-confined to the thread whose body is ``<method>`` (typically a
  ``threading.Thread(target=self.<method>)`` body). Writes from methods
  not reachable from ``<method>`` via the intra-class call graph are
  findings (reads are allowed: cross-thread reads of owned state are
  point-in-time snapshots, the pattern the engine documents for
  ``queue_depth``).
- ``# requires-lock: <lock>`` anywhere inside a method: the method is
  only ever called with ``<lock>`` held.

Rules:

- ``guarded-by-violation`` — guarded attribute touched outside the lock.
- ``owned-by-violation`` — owned attribute mutated off its thread.
- ``cross-thread-mutation`` — in a class that SPAWNS threads, an
  attribute with no annotation at all is mutated both from a
  thread-body-reachable method and from an external (caller-thread)
  method. This is the rule that would have caught PR 10's
  ``build_heartbeat`` dict-resize race class before review did.
- ``unknown-lock`` / ``unknown-owner`` — an annotation names a lock or
  thread-body method the class never defines (typo guard: a misspelled
  annotation must not silently disable checking).

``__init__`` is exempt everywhere: construction happens-before
publication of ``self``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from langstream_tpu.analysis.common import (
    Finding,
    Suppressions,
    attach_comment_annotations,
    file_comments,
    finalize,
    parse_file,
)

_GUARDED_RE = re.compile(
    r"guarded-by:\s*([A-Za-z_]\w*)\s*(\(writes\))?"
)
_OWNED_RE = re.compile(r"owned-by:\s*([A-Za-z_]\w*)")
_REQUIRES_RE = re.compile(r"requires-lock:\s*([A-Za-z_]\w*)")

# method calls that mutate the receiver in place — the dict/list/set/
# deque surface the runtime actually uses; a resize racing an iterator
# is exactly the build_heartbeat failure class
_MUTATORS = frozenset((
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "clear", "update", "add", "discard", "setdefault",
    "sort", "reverse",
))


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef) -> None:
        self.node = node
        self.name = node.name
        # method name -> def node (class-body level only; nested defs
        # belong to their enclosing method)
        self.methods: Dict[str, ast.FunctionDef] = {
            stmt.name: stmt
            for stmt in node.body
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # guarded: attr -> (lock, writes_only, annotation line)
        self.guarded: Dict[str, Tuple[str, bool, int]] = {}
        # owned: attr -> (owner method, annotation line)
        self.owned: Dict[str, Tuple[str, int]] = {}
        self.requires: Dict[str, Set[str]] = {}
        self.thread_bodies: Set[str] = set()
        self.calls: Dict[str, Set[str]] = {}

    def reachable(self, roots: Sequence[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.methods]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            stack.extend(
                callee for callee in self.calls.get(name, ())
                if callee in self.methods and callee not in seen
            )
        return seen


def _collect_annotations(
    info: _ClassInfo, comments: Dict[int, str], path: str
) -> List[Finding]:
    """Attach guarded-by/owned-by comments to the ``self.X`` assignment
    they annotate (same line, or the next code line for standalone
    comments)."""
    findings: List[Finding] = []
    # scope to THIS class's span: a trailing annotation in the previous
    # class must not attach to this one's first statement
    end = info.node.end_lineno or info.node.lineno
    comments = {
        line: text
        for line, text in comments.items()
        if info.node.lineno <= line <= end
    }
    guarded_lines = attach_comment_annotations(
        _GUARDED_RE, comments, info.node
    )
    owned_lines = attach_comment_annotations(_OWNED_RE, comments, info.node)
    targets_by_line: Dict[int, List[str]] = {}
    for node in ast.walk(info.node):
        attr = None
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for target in targets:
                attr = _self_attr(target)
                if attr:
                    targets_by_line.setdefault(node.lineno, []).append(attr)
    for line, match in guarded_lines.items():
        attrs = targets_by_line.get(line, [])
        for attr in attrs:
            info.guarded[attr] = (
                match.group(1), match.group(2) is not None, line
            )
        if not attrs:
            findings.append(
                Finding(
                    "unanchored-annotation", path, line,
                    f"guarded-by annotation in {info.name} attaches to "
                    "no `self.<attr>` assignment — the contract it "
                    "declares checks nothing",
                )
            )
    for line, match in owned_lines.items():
        attrs = targets_by_line.get(line, [])
        for attr in attrs:
            info.owned[attr] = (match.group(1), line)
        if not attrs:
            findings.append(
                Finding(
                    "unanchored-annotation", path, line,
                    f"owned-by annotation in {info.name} attaches to "
                    "no `self.<attr>` assignment — the contract it "
                    "declares checks nothing",
                )
            )
    return findings


def _scan_methods(info: _ClassInfo, comments: Dict[int, str]) -> None:
    """Fill per-method call edges, requires-lock marks, and thread-body
    targets (``threading.Thread(target=self.<m>)``)."""
    for name, method in info.methods.items():
        called: Set[str] = set()
        for node in ast.walk(method):
            if isinstance(node, ast.Call):
                attr = _self_attr(node.func)
                if attr:
                    called.add(attr)
                # threading.Thread(target=self.<m>) / Thread(target=...)
                func = node.func
                callee = (
                    func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name) else ""
                )
                if callee == "Thread":
                    for keyword in node.keywords:
                        if keyword.arg == "target":
                            target = _self_attr(keyword.value)
                            if target:
                                info.thread_bodies.add(target)
        info.calls[name] = called
        marks: Set[str] = set()
        end = method.end_lineno or method.lineno
        # include the line above the def (and any decorators): the
        # natural place to write the contract is above the signature
        start = min(
            [method.lineno]
            + [d.lineno for d in method.decorator_list]
        ) - 1
        for line in range(start, end + 1):
            text = comments.get(line)
            if text:
                match = _REQUIRES_RE.search(text)
                if match:
                    marks.add(match.group(1))
        info.requires[name] = marks


class _Access:
    __slots__ = ("attr", "line", "write", "method", "held")

    def __init__(self, attr: str, line: int, write: bool, method: str,
                 held: frozenset) -> None:
        self.attr = attr
        self.line = line
        self.write = write
        self.method = method
        self.held = held


def _with_locks(node: ast.With) -> Set[str]:
    locks: Set[str] = set()
    for item in node.items:
        expr = item.context_expr
        attr = _self_attr(expr)
        if attr:
            locks.add(attr)
    return locks


def _collect_accesses(info: _ClassInfo) -> List[_Access]:
    accesses: List[_Access] = []

    def classify(node: ast.Attribute, parents: Dict[int, ast.AST]) -> bool:
        """True when this self.X occurrence mutates X."""
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        parent = parents.get(id(node))
        # self.X[...] = v  /  del self.X[...]
        if (
            isinstance(parent, ast.Subscript)
            and parent.value is node
            and isinstance(parent.ctx, (ast.Store, ast.Del))
        ):
            return True
        # self.X.append(...) and friends
        if (
            isinstance(parent, ast.Attribute)
            and parent.value is node
            and parent.attr in _MUTATORS
        ):
            grand = parents.get(id(parent))
            if isinstance(grand, ast.Call) and grand.func is parent:
                return True
        # self.X.attr = v (mutating a member of the referenced object):
        #   counts as a write to the OBJECT, which guarded-by covers
        if (
            isinstance(parent, ast.Attribute)
            and parent.value is node
            and isinstance(parent.ctx, (ast.Store, ast.Del))
        ):
            return True
        return False

    for name, method in info.methods.items():
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(method):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node

        def visit(node: ast.AST, held: frozenset) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = frozenset(held | _with_locks(node))
                for item in node.items:
                    visit(item.context_expr, held)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            attr = _self_attr(node)
            if attr is not None:
                accesses.append(
                    _Access(
                        attr, node.lineno,
                        classify(node, parents), name, held,
                    )
                )
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        held0 = frozenset(info.requires.get(name, ()))
        for stmt in method.body:
            visit(stmt, held0)
    return accesses


def _check_class(
    info: _ClassInfo, path: str, comments: Dict[int, str]
) -> List[Finding]:
    findings = _collect_annotations(info, comments, path)
    _scan_methods(info, comments)
    accesses = _collect_accesses(info)
    defined_attrs = {a.attr for a in accesses}

    # annotation typo guards; an attr guarded by a lock the class never
    # references reports ONLY the typo (per-access violations against a
    # misspelled lock would be noise on top of the actionable finding)
    unknown_locks: Set[str] = set()
    for attr, (lock, _writes, line) in info.guarded.items():
        if lock not in defined_attrs:
            unknown_locks.add(attr)
            findings.append(
                Finding(
                    "unknown-lock", path, line,
                    f"{info.name}.{attr} is guarded-by {lock!r} but the "
                    "class never references such a lock attribute",
                )
            )
    # same policy as unknown-lock: a typo'd owner reports ONLY the typo
    # (per-write violations against a method that does not exist would
    # be noise on top of the actionable finding)
    unknown_owners: Set[str] = set()
    for attr, (owner, line) in info.owned.items():
        if owner not in info.methods:
            unknown_owners.add(attr)
            findings.append(
                Finding(
                    "unknown-owner", path, line,
                    f"{info.name}.{attr} is owned-by {owner!r} but the "
                    "class defines no such method",
                )
            )

    # rule 1: guarded-by
    for access in accesses:
        if access.method == "__init__":
            continue
        annotation = info.guarded.get(access.attr)
        if annotation is None or access.attr in unknown_locks:
            continue
        lock, writes_only, _line = annotation
        if writes_only and not access.write:
            continue
        if lock in access.held:
            continue
        kind = "write" if access.write else "read"
        findings.append(
            Finding(
                "guarded-by-violation", path, access.line,
                f"{kind} of {info.name}.{access.attr} (guarded-by "
                f"{lock}) outside `with self.{lock}:` in "
                f"{access.method}()",
            )
        )

    # rule 2: owned-by (mutations off the owning thread)
    owner_reach: Dict[str, Set[str]] = {}
    for attr, (owner, _line) in info.owned.items():
        if owner not in owner_reach and owner in info.methods:
            owner_reach[owner] = info.reachable([owner])
    for access in accesses:
        if not access.write or access.method == "__init__":
            continue
        annotation = info.owned.get(access.attr)
        if annotation is None or access.attr in unknown_owners:
            continue
        owner, _line = annotation
        if access.method in owner_reach.get(owner, {owner}):
            continue
        findings.append(
            Finding(
                "owned-by-violation", path, access.line,
                f"{info.name}.{access.attr} is owned by the {owner}() "
                f"thread but is mutated from {access.method}(), which "
                f"{owner}() never reaches",
            )
        )

    # rule 3: unannotated cross-thread mutation (thread-spawning
    # classes only — the heuristic needs a thread boundary to reason
    # about)
    if info.thread_bodies:
        reach: Dict[str, Set[str]] = {
            body: info.reachable([body]) for body in info.thread_bodies
        }
        writes_by_attr: Dict[str, List[_Access]] = {}
        for access in accesses:
            if not access.write or access.method == "__init__":
                continue
            if access.attr in info.guarded or access.attr in info.owned:
                continue
            writes_by_attr.setdefault(access.attr, []).append(access)
        for attr, writes in sorted(writes_by_attr.items()):
            domains: Dict[str, List[_Access]] = {}
            for access in writes:
                owners = [
                    body for body, members in reach.items()
                    if access.method in members
                ]
                for domain in owners or ["<caller>"]:
                    domains.setdefault(domain, []).append(access)
            if len(domains) < 2:
                continue
            # anchor the finding on a caller-side write when one exists
            # (that is the line a suppression most likely belongs on)
            anchor = min(
                domains.get("<caller>", writes),
                key=lambda a: a.line,
            )
            names = ", ".join(
                f"{domain}:{sorted({a.method for a in sub})}"
                for domain, sub in sorted(domains.items())
            )
            findings.append(
                Finding(
                    "cross-thread-mutation", path, anchor.line,
                    f"{info.name}.{attr} is mutated from multiple "
                    f"thread contexts ({names}) with no guarded-by/"
                    "owned-by annotation",
                )
            )
    return findings


def analyze_source(path: str, source: str, tree: ast.AST) -> List[Finding]:
    comments = file_comments(source)
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_check_class(_ClassInfo(node), path, comments))
    return findings


def run_lock_pass(paths: Sequence[str]) -> List[Finding]:
    """Analyze every file (annotation-driven: classes without
    annotations and without threads produce nothing). Returns ALL
    findings; suppressed ones carry their reason."""
    from langstream_tpu.analysis.common import iter_py_files

    out: List[Finding] = []
    for path in iter_py_files(paths):
        source, tree, errors = parse_file(path)
        out.extend(errors)
        if tree is None:
            continue
        suppressions = Suppressions(source, tree)
        out.extend(
            finalize(analyze_source(path, source, tree), suppressions, path)
        )
    return out
