"""Jit-hazard pass: host-sync and retrace hazards in device-context
functions.

Device context = any function reachable (same-module, via direct-name
calls and ``self.<m>()`` calls) from:

- a ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated def,
- the function argument of a ``jax.jit(...)`` / ``shard_map(...)`` /
  ``compat_shard_map(...)`` call,
- a def annotated ``# jit: device-context`` (for modules like
  ``providers/jax_local/model.py`` whose functions are jitted by their
  CALLERS in another module — cross-module reachability is out of scope
  for an AST pass, the annotation closes the gap explicitly).

Taint: parameters of a device-context function (minus ``static_argnums``
/ ``static_argnames`` of the wrapping jit and parameters whose names are
conventionally static — ``self``, ``config``, ``mesh``, ``kernel``) are
runtime tracers; so is anything produced by ``jnp.*`` / ``jax.*`` /
``lax.*`` calls or arithmetic over tainted values. ``x.shape`` /
``x.dtype`` / ``len(x)`` / ``x is None`` escape taint (static under
trace).

Rules:

- ``tracer-host-sync`` — ``.item()`` anywhere in device context, or
  ``float()``/``int()``/``bool()``/``np.asarray()``/``np.array()``
  applied to a tainted value: each forces a device→host transfer that
  serializes the dispatch pipeline (and fails outright under jit).
- ``tracer-branch`` — ``if``/``while``/ternary conditions on tainted
  values: Python control flow on runtime tensor values is a
  ConcretizationError under jit and a retrace-per-value hazard with
  static args; ``jnp.where``/``lax.cond`` are the device-side forms.
- ``closure-mutable-config`` — a device-context function closes over a
  name bound to a mutable literal (``dict``/``list``/``set``) in the
  enclosing function scope: jit bakes the value at trace time, later
  mutations are silently ignored, and passing it as a static arg raises
  unhashable-type (module-level tables are fine — they are constants by
  convention).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from langstream_tpu.analysis.common import (
    Finding,
    Suppressions,
    file_comments,
    finalize,
    parse_file,
)

_DEVICE_CONTEXT_RE = re.compile(r"jit:\s*device-context")

# names whose call results are tainted (runtime arrays)
_ARRAY_MODULES = ("jnp", "lax", "jax")
# parameters that are static config by convention in this codebase even
# inside jitted closures (they are closure-bound, not traced, when the
# builder partials them in)
_STATIC_PARAM_NAMES = frozenset(("self", "cls", "config", "mesh", "kernel"))
_SHAPE_ATTRS = frozenset(("shape", "dtype", "ndim", "size"))


def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def _is_jit_expr(node: ast.AST) -> Optional[ast.Call]:
    """The Call node configuring jit, for ``jax.jit`` / ``jit`` /
    ``partial(jax.jit, ...)`` / ``functools.partial(jax.jit, ...)``."""
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name in ("jax.jit", "jit"):
            return node
        if name.endswith("partial") and node.args:
            inner = _dotted(node.args[0])
            if inner in ("jax.jit", "jit"):
                return node
    return None


_SCALAR_TYPES = frozenset(("int", "float", "bool", "str", "bytes"))


def _scalar_annotated(ann: Optional[ast.AST]) -> bool:
    """True for parameter annotations naming Python scalars — ``x: int``,
    ``x: Optional[float]`` — which are host config by construction, not
    tracers (shapes, block sizes, flags)."""
    if ann is None:
        return False
    if isinstance(ann, ast.Name):
        return ann.id in _SCALAR_TYPES
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        # forward-reference string: match whole type names only —
        # substring matching would read "Interval" as int
        return any(
            re.search(rf"\b{t}\b", ann.value) for t in _SCALAR_TYPES
        )
    if isinstance(ann, ast.Subscript):  # Optional[int], Union[int, None]
        return any(
            isinstance(n, ast.Name) and n.id in _SCALAR_TYPES
            for n in ast.walk(ann.slice)
        )
    return False


def _static_params(jit_call: Optional[ast.Call], fn: ast.FunctionDef) -> Set[str]:
    """Parameter names pinned static by the jit configuration."""
    static: Set[str] = set()
    if jit_call is None:
        return static
    params = [a.arg for a in fn.args.args]
    for keyword in jit_call.keywords:
        if keyword.arg == "static_argnums":
            for value in ast.walk(keyword.value):
                if isinstance(value, ast.Constant) and isinstance(
                    value.value, int
                ):
                    if 0 <= value.value < len(params):
                        static.add(params[value.value])
        elif keyword.arg == "static_argnames":
            for value in ast.walk(keyword.value):
                if isinstance(value, ast.Constant) and isinstance(
                    value.value, str
                ):
                    static.add(value.value)
    return static


class _Scope:
    """One module's function defs, call edges, and jit roots. Defs are
    tracked as NODES (the engine defines eight nested ``run_impl``s —
    keying by name would collapse them); call edges resolve a name to
    every same-named def (over-approximation is the right direction for
    a lint)."""

    def __init__(self, tree: ast.AST, comments: Dict[int, str]) -> None:
        self.defs_by_name: Dict[str, List[ast.FunctionDef]] = {}
        self.jit_of: Dict[int, Optional[ast.Call]] = {}
        self.roots: List[ast.FunctionDef] = []
        root_ids: Set[int] = set()

        def add_root(fn: ast.FunctionDef, jit: Optional[ast.Call]) -> None:
            if id(fn) not in root_ids:
                root_ids.add(id(fn))
                self.roots.append(fn)
            if jit is not None:
                self.jit_of.setdefault(id(fn), jit)

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)
                for decorator in node.decorator_list:
                    jit = _is_jit_expr(decorator)
                    if jit is not None or _dotted(decorator) in (
                        "jax.jit", "jit"
                    ):
                        add_root(node, jit)
                # explicit device-context annotation on the def line or
                # the line above it
                for line in (node.lineno, node.lineno - 1):
                    text = comments.get(line, "")
                    if _DEVICE_CONTEXT_RE.search(text):
                        add_root(node, None)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = _dotted(node.func)
                is_jit = name in ("jax.jit", "jit")
                is_smap = name.split(".")[-1] in (
                    "shard_map", "compat_shard_map", "_shard_map"
                )
                if (is_jit or is_smap) and node.args:
                    target = node.args[0]
                    bare = (
                        target.id if isinstance(target, ast.Name)
                        else target.attr
                        if isinstance(target, ast.Attribute) else None
                    )
                    if bare:
                        for fn in self.defs_by_name.get(bare, []):
                            add_root(fn, node if is_jit else None)

    def reachable(self) -> List[ast.FunctionDef]:
        seen: Set[int] = set()
        out: List[ast.FunctionDef] = []
        stack = list(self.roots)
        while stack:
            fn = stack.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            out.append(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = None
                    if isinstance(node.func, ast.Name):
                        callee = node.func.id
                    elif isinstance(node.func, ast.Attribute) and isinstance(
                        node.func.value, ast.Name
                    ) and node.func.value.id == "self":
                        callee = node.func.attr
                    if callee:
                        for target in self.defs_by_name.get(callee, []):
                            if id(target) not in seen:
                                stack.append(target)
        return out


def _analyze_function(
    path: str,
    fn: ast.FunctionDef,
    jit_call: Optional[ast.Call],
    enclosing_mutables: Dict[str, int],
) -> List[Finding]:
    findings: List[Finding] = []
    static = _static_params(jit_call, fn) | _STATIC_PARAM_NAMES
    tainted: Set[str] = {
        a.arg
        for a in (
            fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs
        )
        if a.arg not in static and not _scalar_annotated(a.annotation)
    }

    def is_tainted(node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _SHAPE_ATTRS:
                return False
            return is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return is_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return is_tainted(node.left) or is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # identity tests are static under trace (`x is None` is the
            # optional-operand idiom, not a value branch)
            if all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ):
                return False
            return is_tainted(node.left) or any(
                is_tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return is_tainted(node.body) or is_tainted(node.orelse)
        if isinstance(node, ast.Tuple):
            return any(is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            head = name.split(".")[0]
            if head in _ARRAY_MODULES and "ShapeDtypeStruct" not in name:
                return True
            if name == "len":
                return False
            # method call on a tainted receiver stays tainted
            # (x.astype(...), x.reshape(...), x.at[...].set(...))
            if isinstance(node.func, ast.Attribute):
                return is_tainted(node.func.value)
            return False
        return False

    # flow-insensitive propagation to convergence: assignments of
    # tainted expressions taint their targets
    for _ in range(4):
        grew = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and is_tainted(node.value):
                for target in node.targets:
                    for name_node in ast.walk(target):
                        if (
                            isinstance(name_node, ast.Name)
                            and name_node.id not in tainted
                        ):
                            tainted.add(name_node.id)
                            grew = True
            elif isinstance(node, ast.AugAssign) and is_tainted(node.value):
                if isinstance(node.target, ast.Name) and (
                    node.target.id not in tainted
                ):
                    tainted.add(node.target.id)
                    grew = True
        if not grew:
            break

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
            ):
                findings.append(
                    Finding(
                        "tracer-host-sync", path, node.lineno,
                        f"`.item()` in device context {fn.name}() — a "
                        "blocking device→host sync (and a trace error "
                        "under jit)",
                    )
                )
            elif name in ("float", "int", "bool") and node.args and (
                is_tainted(node.args[0])
            ):
                findings.append(
                    Finding(
                        "tracer-host-sync", path, node.lineno,
                        f"`{name}(...)` on a traced value in "
                        f"{fn.name}() — concretizes the tracer "
                        "(host sync / trace error)",
                    )
                )
            elif name in (
                "np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "onp.asarray", "onp.array",
            ) and node.args and is_tainted(node.args[0]):
                findings.append(
                    Finding(
                        "tracer-host-sync", path, node.lineno,
                        f"`{name}(...)` on a traced value in "
                        f"{fn.name}() — materializes the array on host "
                        "mid-dispatch; use jnp equivalents",
                    )
                )
        elif isinstance(node, (ast.If, ast.While)) and is_tainted(node.test):
            keyword = "while" if isinstance(node, ast.While) else "if"
            findings.append(
                Finding(
                    "tracer-branch", path, node.lineno,
                    f"Python `{keyword}` on a traced value in "
                    f"{fn.name}() — runtime tensor values cannot drive "
                    "host control flow (jnp.where / lax.cond / "
                    "lax.while_loop are the device-side forms)",
                )
            )
        elif isinstance(node, ast.Assert) and is_tainted(node.test):
            findings.append(
                Finding(
                    "tracer-branch", path, node.lineno,
                    f"`assert` on a traced value in {fn.name}() — "
                    "asserts concretize under trace; use "
                    "checkify/debug.check or assert on static shapes",
                )
            )

    # closure-captured mutable config
    for name, line in sorted(enclosing_mutables.items()):
        used = any(
            isinstance(node, ast.Name)
            and node.id == name
            and isinstance(node.ctx, ast.Load)
            for node in ast.walk(fn)
        )
        local = any(
            isinstance(node, ast.Name)
            and node.id == name
            and isinstance(node.ctx, ast.Store)
            for node in ast.walk(fn)
        ) or name in {a.arg for a in fn.args.args}
        if used and not local:
            findings.append(
                Finding(
                    "closure-mutable-config", path, fn.lineno,
                    f"device-context {fn.name}() closes over mutable "
                    f"{name!r} (bound at line {line}): jit bakes the "
                    "value at trace time, later mutations are silently "
                    "ignored, and static-arg use raises unhashable",
                )
            )
    return findings


def _mutable_bindings(fn: ast.FunctionDef) -> Dict[str, int]:
    """Names bound to dict/list/set literals directly in this function's
    body (not inside nested defs)."""
    out: Dict[str, int] = {}
    for stmt in fn.body:
        if isinstance(stmt, ast.Assign) and isinstance(
            stmt.value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)
        ):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = stmt.lineno
    return out


def analyze_source(path: str, source: str, tree: ast.AST) -> List[Finding]:
    comments = file_comments(source)
    scope = _Scope(tree, comments)
    device = scope.reachable()
    if not device:
        return []
    device_ids = {id(fn) for fn in device}
    # map each device-context def to its enclosing function's mutable
    # literal bindings (builder-closure pattern: `def _get_x(): cfg = {}
    # ... @jax.jit def run(...): use(cfg)`)
    enclosing: Dict[int, Dict[str, int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bindings = _mutable_bindings(node)
            if not bindings:
                continue
            for child in ast.walk(node):
                if (
                    isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    )
                    and child is not node
                    and id(child) in device_ids
                ):
                    enclosing.setdefault(id(child), {}).update(bindings)
    findings: List[Finding] = []
    for fn in sorted(device, key=lambda f: f.lineno):
        findings.extend(
            _analyze_function(
                path, fn, scope.jit_of.get(id(fn)),
                enclosing.get(id(fn), {}),
            )
        )
    return findings


def run_jit_pass(paths: Sequence[str]) -> List[Finding]:
    from langstream_tpu.analysis.common import iter_py_files

    out: List[Finding] = []
    for path in iter_py_files(paths):
        source, tree, errors = parse_file(path)
        out.extend(errors)
        if tree is None:
            continue
        suppressions = Suppressions(source, tree)
        out.extend(
            finalize(analyze_source(path, source, tree), suppressions, path)
        )
    return out
