"""``langstream-tpu check`` — run the three analysis passes and gate on
unsuppressed findings (non-zero exit), so the same invariants that run
as the CI ``analysis`` shard can gate locally before a push.

Default scope: the installed ``langstream_tpu`` package tree for the two
AST passes, plus the engine config matrix for the HLO and retrace
passes. ``--skip hlo`` keeps the fast passes for tight edit loops and
pre-commit hooks (the HLO matrix jit-compiles ~30 tiny dispatches and
takes a couple of minutes on CPU; the retrace pass only builds two tiny
engines and checks builder-memo identity — seconds, never a compile).
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List, Optional

from langstream_tpu.analysis.common import Finding

PASSES = ("lock", "jit", "retrace", "hlo")


def _package_root() -> str:
    import langstream_tpu

    return os.path.dirname(os.path.abspath(langstream_tpu.__file__))


def build_parser(parser: Optional[argparse.ArgumentParser] = None):
    parser = parser or argparse.ArgumentParser(prog="langstream-tpu check")
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories for the AST passes "
             "(default: the langstream_tpu package)",
    )
    parser.add_argument(
        "--skip", action="append", default=[], choices=list(PASSES),
        help="skip a pass (repeatable); e.g. --skip hlo for the "
             "sub-second AST-only gate",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed findings with their reasons "
             "(the audit view)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable output (findings + collective census)",
    )
    parser.add_argument(
        "--platform", default="cpu",
        help="jax platform for the HLO pass (default cpu — the "
             "deterministic gate CI runs; empty string = jax default)",
    )
    return parser


def run_check(args: argparse.Namespace) -> int:
    paths = args.paths or [_package_root()]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        # a typo'd path must not gate CLEAN over zero analyzed files
        print(f"langstream-tpu check: no such path(s): {missing}")
        return 2
    skip = set(args.skip)
    if {"lock", "jit"} - skip:
        from langstream_tpu.analysis.common import iter_py_files

        if not iter_py_files(paths):
            # an existing-but-Python-free scope is the same trap: the
            # gate would pass without having analyzed anything
            print(
                f"langstream-tpu check: no Python files under {paths}"
            )
            return 2
    report: Dict[str, List[Finding]] = {}
    census: Dict[str, Dict[str, int]] = {}

    if "lock" not in skip:
        from langstream_tpu.analysis.lock_discipline import run_lock_pass

        report["lock-discipline"] = run_lock_pass(paths)
    if "jit" not in skip:
        from langstream_tpu.analysis.jit_hazards import run_jit_pass

        report["jit-hazards"] = run_jit_pass(paths)
    if {"retrace", "hlo"} - skip:
        # the virtual multi-device mesh must be configured BEFORE jax
        # initializes its backend (same dance as tests/conftest.py) so
        # the tp=2 matrix legs exist off-TPU — and the retrace pass
        # builds engines (importing jax) too, so this must run before
        # EITHER engine-building pass touches jax
        xla_flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in xla_flags:
            os.environ["XLA_FLAGS"] = (
                xla_flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax

        if args.platform:
            jax.config.update("jax_platforms", args.platform)
    if "retrace" not in skip:
        # builder-memo identity over tiny never-started engines: cheap
        # enough for the pre-commit gate (no lowering, no compile), but
        # it does import jax — keep it after the pure-AST passes so
        # their findings print even when the import environment is sick
        from langstream_tpu.analysis.retrace import run_retrace_pass

        progress = None if args.as_json else (
            lambda message: print(f"  {message}", flush=True)
        )
        report["retrace-budget"] = run_retrace_pass(progress=progress)
    if "hlo" not in skip:
        from langstream_tpu.analysis.hlo_lint import run_hlo_pass

        progress = None if args.as_json else (
            lambda message: print(f"  {message}", flush=True)
        )
        hlo_findings, census = run_hlo_pass(progress=progress)
        report["hlo-invariants"] = hlo_findings

    failures = 0
    if args.as_json:
        payload = {
            "passes": {
                name: [vars(f) for f in findings]
                for name, findings in report.items()
            },
            "census": census,
        }
        print(json.dumps(payload, indent=2))
        failures = sum(
            1
            for findings in report.values()
            for f in findings
            if not f.suppressed
        )
        return 1 if failures else 0

    for name, findings in report.items():
        open_findings = [f for f in findings if not f.suppressed]
        suppressed = [f for f in findings if f.suppressed]
        print(
            f"{name}: {len(open_findings)} finding(s)"
            f" ({len(suppressed)} suppressed)"
        )
        for finding in open_findings:
            print(f"  {finding.format()}")
        if args.show_suppressed:
            for finding in suppressed:
                print(f"  {finding.format()}")
        failures += len(open_findings)
    if census:
        collectives = {
            dispatch: c for dispatch, c in census.items() if c
        }
        if collectives:
            print("collective census (tp>1 dispatches):")
            for dispatch, counts in sorted(collectives.items()):
                detail = " ".join(
                    f"{op}x{n}" for op, n in sorted(counts.items())
                )
                print(f"  {dispatch}: {detail}")
    print(
        "langstream-tpu check: "
        + ("CLEAN" if not failures else f"{failures} FINDING(S)")
    )
    return 1 if failures else 0


def main(argv: Optional[List[str]] = None) -> int:
    return run_check(build_parser().parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
