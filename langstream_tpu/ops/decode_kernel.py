"""Length-aware Pallas TPU decode attention (flash-decode).

The XLA decode attention (``ops/attention.py::decode_attention``) is an
einsum over the cache's FULL static buffer ``[S, T, KVH, D]``: masking
keeps invalid positions out of the softmax, but every decode step still
streams all ``T`` allocated rows per slot from HBM. At serving contexts
(T = 4-8k) with typical live lengths far below T, most of that traffic
is dead rows — and decode is the HBM-bound hot loop, so dead traffic is
lost tokens/sec.

This kernel makes decode-attention HBM traffic proportional to the
LIVE context instead of the allocated buffer:

- grid = (slot, T/block_k); the kv-block axis is innermost/sequential,
  so VMEM scratch carries the online-softmax state across a slot's
  blocks (same recurrence as ``ops/flash_attention.py``).
- per-slot lengths ride as a scalar-prefetch operand: they are
  available to the BlockSpec index maps BEFORE the pipeline issues
  each block's DMA. Blocks past a slot's last live block clamp their
  index to that last block — Pallas elides the copy when the mapped
  block indices repeat, so skipped blocks cost neither HBM reads nor
  MXU time (their compute is ``pl.when``-gated off).
- GQA runs as one small MXU matmul per kv head against the block's
  ``[block_k, D]`` slab (a static python loop — KVH is a config
  constant); q is tiny ([H, D]) and loaded once per slot.
- the int8-cache twin streams int8 k/v tiles (half the bytes — the
  kv-quant win compounds with block skipping) and folds the
  per-(position, head) scales exactly like the XLA quant path:
  k_scale AFTER q·kᵀ, v_scale into the probs BEFORE p·v.

Reference parity: none to port — the reference's decode loop lives
server-side behind provider HTTPS (SURVEY §2.4, `OpenAICompletionService
.java:52`); this is the TPU-native interior of the `jax-local` engine's
continuous-batching decode step (`providers/jax_local/engine.py`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# candidate kv-block sizes, largest first; the allocated cache length
# must divide evenly (no padding — padding would copy the cache)
_BLOCK_CANDIDATES = (512, 256, 128, 64, 32)


def pick_block_k(max_len: int) -> Optional[int]:
    for cand in _BLOCK_CANDIDATES:
        if max_len % cand == 0 and max_len >= cand:
            return cand
    return None


def _num_valid_blocks(length, block_k: int):
    """Blocks holding live rows (≥1 so empty slots still touch block 0 —
    their scores are fully masked and finalize emits zeros)."""
    return jnp.maximum(1, (length + block_k - 1) // block_k)


def _first_valid_block(length, window, block_k: int):
    """First block inside the sliding window (0 when window is off or
    wider than the live context): valid positions are
    ``length - window .. length - 1``."""
    return jnp.where(
        window > 0,
        jnp.maximum(0, (length - window) // block_k),
        0,
    )


def _decode_kernel_body(
    lens_ref,   # SMEM scalar-prefetch [S] int32
    win_ref,    # SMEM scalar-prefetch [1] int32 (0 = full attention)
    q_ref,      # VMEM [1, H, D]
    k_ref,      # VMEM [1, block_k, KVH, D] (cache dtype, or int8)
    v_ref,      # VMEM [1, block_k, KVH, D]
    ks_ref,     # VMEM [1, block_k, KVH] f32, or None (bf16 cache)
    vs_ref,     # VMEM [1, block_k, KVH] f32, or None
    out_ref,    # VMEM [1, H, D]
    m_scratch,  # VMEM [H, 128] f32 — running row max
    l_scratch,  # VMEM [H, 128] f32 — running row sum
    acc_scratch,  # VMEM [H, D] f32
    *,
    scale: float,
    block_k: int,
    kv_heads: int,
    group: int,
    softcap: Optional[float],
):
    """One online-softmax recurrence for both cache dtypes. The int8
    mode (``ks_ref``/``vs_ref`` present) streams int8 k/v from HBM (the
    bandwidth halving is the whole point) and folds the scales exactly
    like ``ops/attention.py::decode_attention_quant``: k_scale
    multiplies the scores after q·kᵀ, v_scale folds into the probs
    before p·v, and — matching the XLA quant path, which contracts
    f32 probs against f32 values — the p·v dot runs in f32 (no bf16
    round-trip on the scale-folded probs). The bf16 mode contracts
    bf16 probs with the bf16 cache, matching ``decode_attention``'s
    ``weights.astype(v_cache.dtype)``.

    A sliding window (Gemma-2) tightens the live block range from BOTH
    ends — blocks below the window skip compute exactly like dead
    blocks past the length (and their DMAs are clamp-elided by the
    index maps); ``softcap`` caps the scores before masking."""
    quantized = ks_ref is not None
    s_i = pl.program_id(0)
    j = pl.program_id(1)
    num_blocks = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    length = lens_ref[s_i]
    window = win_ref[0]
    first = _first_valid_block(length, window, block_k)

    @pl.when((j >= first) & (j < _num_valid_blocks(length, block_k)))
    def _compute():
        q = q_ref[0]  # [H, D]
        # int8 values are exactly representable in bf16, so the MXU
        # sees the same numbers the XLA quant path computes
        k = k_ref[0].astype(q.dtype) if quantized else k_ref[0]
        ks = ks_ref[0] if quantized else None  # [block_k, KVH] f32
        parts = []
        for h in range(kv_heads):
            q_h = q[h * group:(h + 1) * group]  # [G, D]
            k_h = k[:, h, :]                    # [block_k, D]
            s_h = jax.lax.dot_general(
                q_h, k_h, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if quantized:
                s_h = s_h * ks[:, h][None, :]
            parts.append(s_h)
        s = jnp.concatenate(parts, axis=0)  # [H, block_k]
        if softcap is not None:
            s = softcap * jnp.tanh(s / softcap)

        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        mask = cols < length
        mask = jnp.logical_and(
            mask, (window <= 0) | (cols > (length - 1) - window)
        )
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scratch[:, :1]
        row_max = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, row_max)
        p = jnp.exp(s - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)
        l_scratch[:] = jnp.broadcast_to(
            l_scratch[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_scratch.shape,
        )

        if quantized:
            v = v_ref[0].astype(jnp.float32)  # f32 contraction, as XLA
            vs = vs_ref[0]                    # [block_k, KVH] f32
        else:
            v = v_ref[0]
        pv_parts = []
        for h in range(kv_heads):
            p_h = p[h * group:(h + 1) * group]  # [G, block_k] f32
            if quantized:
                p_h = p_h * vs[:, h][None, :]
            else:
                p_h = p_h.astype(v.dtype)
            v_h = v[:, h, :]                    # [block_k, D]
            pv_parts.append(
                jax.lax.dot_general(
                    p_h, v_h, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
        pv = jnp.concatenate(pv_parts, axis=0)  # [H, D]
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out_ref[0] = (acc_scratch[:] / l_safe).astype(out_ref.dtype)


def _decode_kernel(lens_ref, win_ref, q_ref, k_ref, v_ref, out_ref,
                   m_scratch, l_scratch, acc_scratch, **kw):
    _decode_kernel_body(
        lens_ref, win_ref, q_ref, k_ref, v_ref, None, None, out_ref,
        m_scratch, l_scratch, acc_scratch, **kw,
    )


def _decode_kernel_quant(lens_ref, win_ref, q_ref, k_ref, v_ref, ks_ref,
                         vs_ref, out_ref, m_scratch, l_scratch,
                         acc_scratch, **kw):
    _decode_kernel_body(
        lens_ref, win_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref,
        m_scratch, l_scratch, acc_scratch, **kw,
    )


def flash_decode_attention(
    q: jnp.ndarray,        # [S, H, D] — one new token per slot
    k_cache: jnp.ndarray,  # [S, T, KVH, D] (bf16; int8 with scales)
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,  # [S] valid rows incl. the new token
    *,
    k_scale: Optional[jnp.ndarray] = None,  # [S, T, KVH] — int8 mode
    v_scale: Optional[jnp.ndarray] = None,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,  # scalar; None/0 = full attn
    scale: Optional[float] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in for :func:`langstream_tpu.ops.attention.decode_attention`
    (or ``decode_attention_quant`` when scales are given) with HBM
    traffic ∝ live context. Caller gates via :func:`use_flash_decode`;
    shapes must satisfy D % 128 == 0, H % KVH == 0, and ``block_k`` must
    divide T (``pick_block_k``). A sliding ``window`` (Gemma-2) bounds
    the traffic by the window instead — blocks below it clamp-elide
    their DMA just like dead blocks past the length."""
    slots, heads, dim = q.shape
    max_len, kv_heads = k_cache.shape[1], k_cache.shape[2]
    group = heads // kv_heads
    scale = dim ** -0.5 if scale is None else scale
    block_k = block_k or pick_block_k(max_len)
    if block_k is None:
        raise ValueError(f"no kv block size divides max_len={max_len}")
    num_blocks = max_len // block_k
    quantized = k_scale is not None
    lengths = lengths.astype(jnp.int32)
    window_arr = jnp.reshape(
        jnp.asarray(0 if window is None else window, dtype=jnp.int32), (1,)
    )

    def block_index(s, j, lens, win):
        # clamp dead blocks (past the length OR below the sliding
        # window) into the live range: the mapped indices repeat, so
        # the pipeline skips their DMA entirely
        first = _first_valid_block(lens[s], win[0], block_k)
        last = _num_valid_blocks(lens[s], block_k) - 1
        return jnp.clip(j, first, last)

    def kv_index(s, j, lens, win):
        return (s, block_index(s, j, lens, win), 0, 0)

    def scale_index(s, j, lens, win):
        return (s, block_index(s, j, lens, win), 0)

    in_specs = [
        pl.BlockSpec((1, heads, dim), lambda s, j, lens, win: (s, 0, 0)),
        pl.BlockSpec((1, block_k, kv_heads, dim), kv_index),
        pl.BlockSpec((1, block_k, kv_heads, dim), kv_index),
    ]
    operands = [q, k_cache, v_cache]
    if quantized:
        kernel = functools.partial(
            _decode_kernel_quant, scale=scale, block_k=block_k,
            kv_heads=kv_heads, group=group, softcap=softcap,
        )
        in_specs += [
            pl.BlockSpec((1, block_k, kv_heads), scale_index),
            pl.BlockSpec((1, block_k, kv_heads), scale_index),
        ]
        operands += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
        kv_bytes = k_cache.size + v_cache.size + (k_scale.size + v_scale.size) * 4
    else:
        kernel = functools.partial(
            _decode_kernel, scale=scale, block_k=block_k,
            kv_heads=kv_heads, group=group, softcap=softcap,
        )
        kv_bytes = (k_cache.size + v_cache.size) * k_cache.dtype.itemsize

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, num_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, heads, dim), lambda s, j, lens, win: (s, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((heads, 128), jnp.float32),
            pltpu.VMEM((heads, 128), jnp.float32),
            pltpu.VMEM((heads, dim), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, heads, dim), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * slots * heads * max_len * dim,
            # the whole point: the scheduler should expect live-context
            # traffic, not the full buffer (estimate at half occupancy)
            bytes_accessed=q.size * q.dtype.itemsize * 2 + kv_bytes // 2,
            transcendentals=slots * heads * max_len,
        ),
        interpret=interpret,
    )(lengths, window_arr, *operands)


def flash_decode_attention_quant(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,   # int8
    k_scale: jnp.ndarray,   # [S, T, KVH]
    v_cache: jnp.ndarray,
    v_scale: jnp.ndarray,
    lengths: jnp.ndarray,
    **kwargs,
) -> jnp.ndarray:
    """Argument-ordering twin of
    :func:`langstream_tpu.ops.attention.decode_attention_quant`."""
    return flash_decode_attention(
        q, k_cache, v_cache, lengths,
        k_scale=k_scale, v_scale=v_scale, **kwargs,
    )


def flash_decode_attention_sharded(
    q: jnp.ndarray,        # [S, H, D] — H sharded over ``axis_name``
    k_cache: jnp.ndarray,  # [S, T, KVH, D] — KVH sharded
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,
    mesh,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    axis_name: str = "tp",
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash decode under tensor parallelism: one independent kernel per
    head shard through ``shard_map`` (a Mosaic call has no SPMD
    partitioning rule). Attention never mixes heads, so no collective;
    query and kv heads shard by the same tp factor (``validate_mesh``
    enforces divisibility). The (traced) ``window`` scalar rides as a
    replicated operand."""
    from jax.sharding import PartitionSpec as P

    head_spec = P(None, axis_name, None)
    cache_spec = P(None, None, axis_name, None)
    scale_spec = P(None, None, axis_name)
    quantized = k_scale is not None
    window_arr = jnp.asarray(
        0 if window is None else window, dtype=jnp.int32
    )

    def local(q_l, k_l, v_l, lengths_l, window_l, *scales):
        return flash_decode_attention(
            q_l, k_l, v_l, lengths_l, interpret=interpret,
            softcap=softcap, window=window_l, scale=scale,
            **(
                {"k_scale": scales[0], "v_scale": scales[1]}
                if scales else {}
            ),
        )

    in_specs = [head_spec, cache_spec, cache_spec, P(None), P()]
    operands = [q, k_cache, v_cache, lengths, window_arr]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    from langstream_tpu.ops.flash_attention import compat_shard_map

    return compat_shard_map(
        local, mesh, tuple(in_specs), head_spec
    )(*operands)


def decode_shapes_ok(max_len: int, dim: int, heads: int, kv_heads: int) -> bool:
    """Hard shape requirements of the kernel (hold on ANY backend)."""
    return (
        dim % 128 == 0
        and heads % kv_heads == 0
        and pick_block_k(max_len) is not None
    )


def use_flash_decode(max_len: int, dim: int, heads: int, kv_heads: int) -> bool:
    """The kernel pays once dead-block skipping can actually drop HBM
    traffic: a long allocated cache, MXU-aligned head_dim, a block size
    that divides it, and a real TPU backend. ``LS_DECODE_FLASH=1/0``
    overrides the auto policy (on-chip A/B knob) — shape requirements
    still bind."""
    import os

    from langstream_tpu.ops.flash_attention import on_tpu

    if not decode_shapes_ok(max_len, dim, heads, kv_heads):
        return False
    override = os.environ.get("LS_DECODE_FLASH", "")
    if override == "1":
        return on_tpu()
    if override == "0":
        return False
    return on_tpu() and max_len >= 1024
