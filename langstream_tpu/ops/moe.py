"""Mixture-of-experts MLP with grouped, capacity-based top-k routing.

TPU-first formulation (GShard/Switch style): instead of gathering each
expert's tokens with dynamic shapes — which XLA cannot tile onto the MXU —
tokens are routed through *static* dispatch/combine einsums against a
fixed per-expert capacity. Routing happens within fixed-size token groups
so the dispatch tensors stay [G, S, E, C] with constant S and C — memory
and FLOPs scale linearly in sequence length, not quadratically.

The expert axis of the weights carries the logical ``expert`` name, which
the mesh rules map to the ``ep`` axis
(``langstream_tpu.parallel.mesh.DEFAULT_RULES``); XLA then inserts the
all-to-alls between token-sharded activations and expert-sharded weights
automatically.

Two regimes:

- **training** (``capacity_factor`` set): tokens overflowing an expert's
  capacity are dropped (zero MLP delta) — the standard Switch trade that
  keeps compute balanced; the aux loss pushes the router toward balance.
- **exact / serving** (``capacity_factor=None``): every expert runs
  densely on every token and outputs combine with the renormalized top-k
  gates (zero weight for unselected experts). This matches a
  dropless-trained checkpoint (e.g. Mixtral) bit-for-bit in routing
  semantics, and is *strictly cheaper* than capacity-based dropless
  routing: dense costs E rows/token vs the dropless capacity bound's
  E·k rows/token, with no dispatch/combine einsums at all.

A ``valid`` mask keeps padding tokens from consuming capacity or skewing
the aux loss.

Reference parity: the reference has no local models at all (it proxies to
OpenAI et al. — see SURVEY.md §2.4, langstream-agents/langstream-ai-agents/
src/main/java/com/datastax/oss/streaming/ai/services/ServiceProvider.java:24).
MoE model support is net-new capability for the jax-local provider
(Mixtral-family), mirroring what the external providers offer.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def moe_capacity(
    group_tokens: int,
    num_experts: int,
    num_selected: int,
    capacity_factor: Optional[float],
) -> int:
    """Per-expert capacity within one routing group:
    ``ceil(factor * S * k / E)`` clamped to the all-fits bound ``S * k``
    (``None`` factor → that bound; note the exact regime in
    :func:`moe_mlp` uses the dense path instead, which is cheaper).
    """
    dropless = group_tokens * num_selected
    if capacity_factor is None:
        return dropless
    return max(
        1,
        min(
            dropless,
            int(
                math.ceil(
                    capacity_factor * group_tokens * num_selected / num_experts
                )
            ),
        ),
    )


def moe_routing(
    logits: jnp.ndarray,  # [S, E] float32 router logits for one group
    num_selected: int,
    capacity: int,
    valid: Optional[jnp.ndarray] = None,  # [S] bool; False = padding
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k routing with per-expert capacity inside one group.

    Returns:
      dispatch  [S, E, C] float  — 0/1 routing of tokens into expert rows
      combine   [S, E, C] float  — dispatch weighted by normalized gates
      aux_loss  scalar           — Switch-style load-balancing loss
                                   (over valid tokens only)
    """
    num_tokens, num_experts = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)  # [S, E]
    gate_vals, gate_idx = jax.lax.top_k(probs, num_selected)  # [S, k]
    # renormalize the selected gates so the expert mix sums to 1 per token
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(axis=-1, keepdims=True), 1e-9
    )

    onehot = jax.nn.one_hot(gate_idx, num_experts, dtype=jnp.float32)  # [S,k,E]
    if valid is not None:
        onehot = onehot * valid[:, None, None].astype(jnp.float32)
    # Position of each (token, choice) within its expert: priority is
    # choice-major (all first choices before any second choice), so a
    # token's primary expert wins capacity over others' secondaries.
    flat = onehot.transpose(1, 0, 2).reshape(num_selected * num_tokens, num_experts)
    pos_flat = jnp.cumsum(flat, axis=0) - flat  # [k*S, E]
    pos = pos_flat.reshape(num_selected, num_tokens, num_experts).transpose(1, 0, 2)
    pos_in_expert = (pos * onehot).sum(-1).astype(jnp.int32)  # [S, k]
    # masked-out choices (padding tokens) have all-zero onehot rows
    fits = (pos_in_expert < capacity) & (onehot.sum(-1) > 0)  # [S, k]

    pos_onehot = jax.nn.one_hot(pos_in_expert, capacity, dtype=jnp.float32)
    pos_onehot = pos_onehot * fits[..., None].astype(jnp.float32)
    dispatch = jnp.einsum("ske,skc->sec", onehot, pos_onehot)
    combine = jnp.einsum("sk,ske,skc->sec", gate_vals, onehot, pos_onehot)

    # load-balance loss: E * sum_e mean(frac routed to e) * mean(prob e),
    # means taken over valid tokens only
    if valid is None:
        denom = jnp.float32(num_tokens)
        probs_masked = probs
    else:
        denom = jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
        probs_masked = probs * valid[:, None].astype(jnp.float32)
    # fraction over ALL top-k selections, normalized by k (Switch/Mixtral
    # formulation): second-choice load gets balancing pressure too
    frac_routed = onehot.sum(axis=(0, 1)) / (num_selected * denom)
    mean_prob = probs_masked.sum(axis=0) / denom
    aux_loss = num_experts * jnp.sum(frac_routed * mean_prob)
    return dispatch, combine, aux_loss


def moe_mlp(
    x: jnp.ndarray,        # [..., H]
    router_w: jnp.ndarray,  # [H, E]
    w_gate: jnp.ndarray,   # [E, H, F]
    w_up: jnp.ndarray,     # [E, H, F]
    w_down: jnp.ndarray,   # [E, F, H]
    *,
    num_selected: int = 2,
    capacity_factor: Optional[float] = 2.0,
    group_size: int = 64,
    valid: Optional[jnp.ndarray] = None,  # [...] bool, x's leading shape
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """SwiGLU expert MLP over grouped capacity-routed tokens.

    Returns (output with x's shape, load-balancing aux loss). All shapes
    static: dispatch/combine are [G, S, E, C] einsum operands, so under an
    ``ep``-sharded mesh the per-expert matmuls stay dense MXU work and the
    routing einsums become all-to-alls. ``capacity_factor=None`` = the
    dropless serving regime.
    """
    orig_shape = x.shape
    hidden = x.shape[-1]
    x2 = x.reshape(-1, hidden)
    num_tokens = x2.shape[0]
    num_experts = router_w.shape[-1]
    num_selected = min(num_selected, num_experts)

    if capacity_factor is None:
        valid2 = None if valid is None else valid.reshape(-1)
        y, aux = _moe_mlp_dense(
            x2, router_w, w_gate, w_up, w_down,
            num_selected=num_selected, valid=valid2,
        )
        return y.reshape(orig_shape), aux

    group = min(group_size, num_tokens)
    pad = (-num_tokens) % group
    valid2 = (
        jnp.ones((num_tokens,), dtype=bool)
        if valid is None
        else valid.reshape(-1)
    )
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
        valid2 = jnp.pad(valid2, (0, pad))
    num_groups = x2.shape[0] // group
    xg = x2.reshape(num_groups, group, hidden)
    vg = valid2.reshape(num_groups, group)
    capacity = moe_capacity(group, num_experts, num_selected, capacity_factor)

    logits = jnp.einsum(
        "gsh,he->gse", xg.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    dispatch, combine, aux = jax.vmap(
        lambda l, v: moe_routing(l, num_selected, capacity, v)
    )(logits, vg)
    aux_loss = aux.mean()

    dtype = x2.dtype
    expert_in = jnp.einsum("gsec,gsh->egch", dispatch.astype(dtype), xg)
    gate = jnp.einsum("egch,ehf->egcf", expert_in, w_gate)
    up = jnp.einsum("egch,ehf->egcf", expert_in, w_up)
    expert_out = jnp.einsum("egcf,efh->egch", jax.nn.silu(gate) * up, w_down)
    y = jnp.einsum("gsec,egch->gsh", combine.astype(dtype), expert_out)
    y = y.reshape(-1, hidden)[:num_tokens]
    return y.reshape(orig_shape), aux_loss


def _moe_mlp_dense(
    x2: jnp.ndarray,        # [T, H]
    router_w: jnp.ndarray,
    w_gate: jnp.ndarray,
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,
    *,
    num_selected: int,
    valid: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact MoE: every expert runs on every token; outputs combine with
    renormalized top-k gate weights (zero for unselected experts). No
    token is ever dropped and no dispatch tensors exist. Under an
    ep-sharded mesh the [E, T, F] activations shard over ep, and XLA
    reduces the final combine over the expert axis with one psum."""
    num_experts = router_w.shape[-1]
    logits = jnp.einsum(
        "th,he->te", x2.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, num_selected)
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(axis=-1, keepdims=True), 1e-9
    )
    onehot = jax.nn.one_hot(gate_idx, num_experts, dtype=jnp.float32)
    gates = jnp.einsum("tk,tke->te", gate_vals, onehot)  # [T, E]

    dtype = x2.dtype
    gate_proj = jnp.einsum("th,ehf->etf", x2, w_gate)
    up_proj = jnp.einsum("th,ehf->etf", x2, w_up)
    out = jnp.einsum("etf,efh->eth", jax.nn.silu(gate_proj) * up_proj, w_down)
    y = jnp.einsum("te,eth->th", gates.astype(dtype), out)

    if valid is None:
        denom = jnp.float32(x2.shape[0])
        probs_masked = probs
        first_choice = onehot[:, 0]
    else:
        vf = valid.astype(jnp.float32)
        denom = jnp.maximum(vf.sum(), 1.0)
        probs_masked = probs * vf[:, None]
        first_choice = onehot[:, 0] * vf[:, None]
    aux_loss = num_experts * jnp.sum(
        (first_choice.sum(0) / denom) * (probs_masked.sum(0) / denom)
    )
    return y, aux_loss
