"""Attention ops for prefill and decode (GQA), XLA-first.

Decode attention over a static-length KV cache and causal prefill
attention. Plain einsum formulations — on TPU, XLA fuses the
softmax chain into the two matmuls and keeps them on the MXU; the Pallas
flash kernel (``ops/flash_attention.py``) takes over for long-sequence
prefill where the O(T²) materialization would spill HBM.

Conventions: q/k/v are [batch, seq, heads, head_dim]; the KV cache is
[batch, max_len, kv_heads, head_dim]; GQA repeats kv heads on the fly
(a gather XLA folds into the matmul, not a materialized repeat).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def _group_query(q: jnp.ndarray, kv_heads: int) -> jnp.ndarray:
    """Reshape [B, T, H, D] → [B, T, KVH, G, D] grouping queries by their
    kv head (G = H // KVH)."""
    batch, seq, heads, dim = q.shape
    groups = heads // kv_heads
    return q.reshape(batch, seq, kv_heads, groups, dim)


def _cap_scores(scores: jnp.ndarray, softcap: Optional[float]) -> jnp.ndarray:
    """Logit softcapping (Gemma-2): cap·tanh(s/cap), applied BEFORE
    masking — matches the HF formulation."""
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    return scores


def prefill_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mask: Optional[jnp.ndarray] = None,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Causal self-attention over a full (padded) prompt.

    q: [B, T, H, D], k/v: [B, T, KVH, D] → [B, T, H, D].
    ``mask`` [B, T] marks valid tokens (padding excluded). ``softcap``
    applies Gemma-style logit capping, ``window`` (traced scalar; 0 =
    full) restricts each query to the last ``window`` positions, and
    ``scale`` overrides the default head_dim**-0.5 (Gemma's
    query_pre_attn_scalar).
    """
    batch, seq, heads, dim = q.shape
    kv_heads = k.shape[2]
    scale = dim ** -0.5 if scale is None else scale
    qg = _group_query(q, kv_heads)  # [B, T, KVH, G, D]
    scores = jnp.einsum(
        "bqkgd,bskd->bkgqs", qg.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale  # [B, KVH, G, Tq, Ts]
    scores = _cap_scores(scores, softcap)
    causal = jnp.tril(jnp.ones((seq, seq), dtype=bool))
    if window is not None:
        rows = jnp.arange(seq)[:, None]
        cols = jnp.arange(seq)[None, :]
        in_window = (window <= 0) | (cols > rows - window)
        causal = jnp.logical_and(causal, in_window)
    allowed = causal[None, None, None]
    if mask is not None:
        allowed = jnp.logical_and(allowed, mask[:, None, None, None, :])
    scores = jnp.where(allowed, scores, -1e30)
    weights = _softmax(scores)
    out = jnp.einsum("bkgqs,bskd->bqkgd", weights.astype(v.dtype), v)
    return out.reshape(batch, seq, heads, dim)


def _decode_valid(
    max_len: int,
    lengths: jnp.ndarray,
    window: Optional[jnp.ndarray],
) -> jnp.ndarray:
    """[B, T] validity for one-token decode: live rows, optionally
    restricted to the query's sliding window (query pos = lengths-1)."""
    pos = jnp.arange(max_len)[None, :]
    valid = pos < lengths[:, None]
    if window is not None:
        in_window = (window <= 0) | (
            pos > (lengths[:, None] - 1) - window
        )
        valid = jnp.logical_and(valid, in_window)
    return valid


def decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """One-token decode attention against the cache.

    q: [B, H, D] (the new token's queries), k/v_cache: [B, T, KVH, D],
    lengths: [B] number of valid cache entries (including the new token,
    already written at position lengths-1). Returns [B, H, D].
    """
    batch, heads, dim = q.shape
    max_len = k_cache.shape[1]
    kv_heads = k_cache.shape[2]
    groups = heads // kv_heads
    scale = dim ** -0.5 if scale is None else scale
    qg = q.reshape(batch, kv_heads, groups, dim)
    scores = jnp.einsum(
        "bkgd,bskd->bkgs", qg.astype(jnp.float32), k_cache.astype(jnp.float32)
    ) * scale  # [B, KVH, G, T]
    scores = _cap_scores(scores, softcap)
    valid = _decode_valid(max_len, lengths, window)
    scores = jnp.where(valid[:, None, None, :], scores, -1e30)
    weights = _softmax(scores)
    out = jnp.einsum("bkgs,bskd->bkgd", weights.astype(v_cache.dtype), v_cache)
    return out.reshape(batch, heads, dim)


def chunk_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    starts: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Chunked prefill-at-offset attention against the cache.

    q: [B, T, H, D] — T new tokens per row whose global positions are
    ``starts[b] + t``; k/v_cache: [B, S, KVH, D] with the new tokens' KV
    already written at ``starts[b]..starts[b]+n-1``; lengths: [B] total
    valid cache entries (starts + suffix length). Query t attends
    causally to cache positions ``<= starts[b] + t``. Returns
    [B, T, H, D]. This is what makes a warm-session follow-up one
    bucketed dispatch instead of one decode dispatch per suffix token.
    """
    batch, seq, heads, dim = q.shape
    max_len = k_cache.shape[1]
    kv_heads = k_cache.shape[2]
    scale = dim ** -0.5 if scale is None else scale
    qg = _group_query(q, kv_heads)  # [B, Tq, KVH, G, D]
    scores = jnp.einsum(
        "bqkgd,bskd->bkgqs", qg.astype(jnp.float32), k_cache.astype(jnp.float32)
    ) * scale  # [B, KVH, G, Tq, S]
    scores = _cap_scores(scores, softcap)
    pos_q = starts[:, None] + jnp.arange(seq)[None, :]       # [B, Tq]
    pos_s = jnp.arange(max_len)[None, None, :]               # [1, 1, S]
    allowed = (pos_s <= pos_q[:, :, None]) & (
        pos_s < lengths[:, None, None]
    )  # [B, Tq, S]
    if window is not None:
        allowed = allowed & (
            (window <= 0) | (pos_s > pos_q[:, :, None] - window)
        )
    scores = jnp.where(allowed[:, None, None, :, :], scores, -1e30)
    weights = _softmax(scores)
    out = jnp.einsum("bkgqs,bskd->bqkgd", weights.astype(v_cache.dtype), v_cache)
    return out.reshape(batch, seq, heads, dim)


def _softmax(scores: jnp.ndarray) -> jnp.ndarray:
    scores = scores - jnp.max(scores, axis=-1, keepdims=True)
    exp = jnp.exp(scores)
    return exp / jnp.sum(exp, axis=-1, keepdims=True)


# ---------------------------------------------------------------------- #
# paged KV cache (kv_layout: paged)
# ---------------------------------------------------------------------- #
# The cache is a global block pool [num_blocks, block_size, kv_heads,
# head_dim] addressed through per-slot block tables [B, M] (M =
# max_seq // block_size): token position p of row b lives in pool block
# ``table[b, p // block_size]`` at offset ``p % block_size``. Block 0 is
# the null block — tables route padding and masked writes there, and no
# live length mask ever lets attention read it. The paths below GATHER a
# row-contiguous view via the table and reuse the dense attention math,
# so dense and paged layouts share one set of masking/softcap/window
# formulas. They are the REFERENCE ORACLE (``paged_kernel: reference``)
# for the fused Pallas kernel in ``ops/paged_attention.py``, which reads
# the tables inside its index maps and streams pool blocks HBM→VMEM
# directly — same masking formulas, no materialized gather copy.


def gather_blocks(pool: jnp.ndarray, block_tables: jnp.ndarray) -> jnp.ndarray:
    """[N, Bs, ...] pool + [B, M] tables → [B, M*Bs, ...] contiguous
    per-row view (a copy — the read side of the paged layout)."""
    view = pool[block_tables]  # [B, M, Bs, ...]
    return view.reshape(
        view.shape[0], view.shape[1] * view.shape[2], *view.shape[3:]
    )


def paged_write_rows(
    pool: jnp.ndarray,          # [N, Bs, ...]
    new: jnp.ndarray,           # [B, T, ...]
    block_tables: jnp.ndarray,  # [B, M]
    offsets: jnp.ndarray,       # [B] global position of each row's token 0
    valid: jnp.ndarray,         # [B, T] bool; False routes to the null block
) -> jnp.ndarray:
    """Scatter per-token rows into their table-addressed pool blocks.
    Works for any trailing shape (bf16/int8 values AND their scale
    leaves). Invalid rows — padding, masked decode slots — land in the
    null block, whose content is never read. Positions past the table's
    capacity (``pos // block_size >= M``) are routed through the null
    block the same way: relying on the take_along_axis index clamp
    would silently land them in the row's LAST real block, overwriting
    live rows another chain may still reference."""
    seq = new.shape[1]
    block_size = pool.shape[1]
    capacity = block_tables.shape[1]                           # M
    pos = offsets[:, None] + jnp.arange(seq)[None, :]          # [B, T]
    seq_block = (pos // block_size).astype(jnp.int32)
    blocks = jnp.take_along_axis(
        block_tables, jnp.clip(seq_block, 0, capacity - 1), axis=1
    )
    in_table = (seq_block >= 0) & (seq_block < capacity)
    blocks = jnp.where(valid & in_table, blocks, 0)
    return pool.at[blocks, pos % block_size].set(new.astype(pool.dtype))


def paged_decode_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """:func:`decode_attention` over a block pool: gather each row's
    blocks into a contiguous [B, M*Bs, KVH, D] view, then the dense
    formula (lengths mask out the tail, incl. any null-block rows)."""
    k_cache = gather_blocks(k_pool, block_tables)
    v_cache = gather_blocks(v_pool, block_tables)
    return decode_attention(
        q, k_cache, v_cache, lengths,
        softcap=softcap, window=window, scale=scale,
    )


def paged_chunk_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    starts: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """:func:`chunk_attention` over a block pool (prefill-at-offset for
    paged slots — the path that reads a SHARED cached prefix written by
    some other request's prefill)."""
    k_cache = gather_blocks(k_pool, block_tables)
    v_cache = gather_blocks(v_pool, block_tables)
    return chunk_attention(
        q, k_cache, v_cache, starts, lengths,
        softcap=softcap, window=window, scale=scale,
    )


def paged_decode_attention_quant(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,     # [N, Bs, KVH, D] int8
    k_scale: jnp.ndarray,    # [N, Bs, KVH] f32
    v_pool: jnp.ndarray,
    v_scale: jnp.ndarray,
    block_tables: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Int8-pool twin of :func:`paged_decode_attention` (scale leaves
    gather through the same tables)."""
    return decode_attention_quant(
        q,
        gather_blocks(k_pool, block_tables),
        gather_blocks(k_scale, block_tables),
        gather_blocks(v_pool, block_tables),
        gather_blocks(v_scale, block_tables),
        lengths,
        softcap=softcap, window=window, scale=scale,
    )


def paged_chunk_attention_quant(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_pool: jnp.ndarray,
    v_scale: jnp.ndarray,
    block_tables: jnp.ndarray,
    starts: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Int8-pool twin of :func:`paged_chunk_attention`."""
    return chunk_attention_quant(
        q,
        gather_blocks(k_pool, block_tables),
        gather_blocks(k_scale, block_tables),
        gather_blocks(v_pool, block_tables),
        gather_blocks(v_scale, block_tables),
        starts, lengths,
        softcap=softcap, window=window, scale=scale,
    )


# ---------------------------------------------------------------------- #
# int8 KV-cache variants
# ---------------------------------------------------------------------- #
# The cache stores int8 values with a per-(position, kv-head) scale.
# Per-row scales COMMUTE with both attention contractions, so the MXU
# streams the bare int8 cache and the scales touch only
# activation-sized arrays — the same algebra that fixed the weight
# dequant in round 3 (quant.qeinsum):
#   QK: q · (K_q * s)ᵀ  = (q · K_qᵀ) * s      (s indexes [pos, head] —
#                                              the score layout)
#   PV: p · (V_q * s)   = (p * s) · V_q       (s folds into the probs)


def quantize_kv(x: jnp.ndarray):
    """Per-row symmetric int8: x [..., D] → (int8 values, f32 scales
    [...]) with scale = amax/127 over the head dim."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    values = jnp.round(
        x.astype(jnp.float32) / scale[..., None]
    ).astype(jnp.int8)
    return values, scale


def decode_attention_quant(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,    # [B, S, KVH, D] int8
    k_scale: jnp.ndarray,    # [B, S, KVH] f32
    v_cache: jnp.ndarray,
    v_scale: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """:func:`decode_attention` over an int8 cache (see algebra above)."""
    batch, heads, dim = q.shape
    max_len = k_cache.shape[1]
    kv_heads = k_cache.shape[2]
    groups = heads // kv_heads
    scale = dim ** -0.5 if scale is None else scale
    qg = q.reshape(batch, kv_heads, groups, dim)
    scores = jnp.einsum(
        "bkgd,bskd->bkgs", qg.astype(jnp.float32),
        k_cache.astype(jnp.float32),
    )
    scores = scores * k_scale.transpose(0, 2, 1)[:, :, None, :] * scale
    scores = _cap_scores(scores, softcap)
    valid = _decode_valid(max_len, lengths, window)
    scores = jnp.where(valid[:, None, None, :], scores, -1e30)
    weights = _softmax(scores)
    weights = weights * v_scale.transpose(0, 2, 1)[:, :, None, :]
    out = jnp.einsum(
        "bkgs,bskd->bkgd", weights, v_cache.astype(jnp.float32)
    )
    return out.reshape(batch, heads, dim).astype(q.dtype)


def chunk_attention_quant(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,    # [B, S, KVH, D] int8
    k_scale: jnp.ndarray,    # [B, S, KVH] f32
    v_cache: jnp.ndarray,
    v_scale: jnp.ndarray,
    starts: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """:func:`chunk_attention` over an int8 cache."""
    batch, seq, heads, dim = q.shape
    max_len = k_cache.shape[1]
    kv_heads = k_cache.shape[2]
    scale = dim ** -0.5 if scale is None else scale
    qg = _group_query(q, kv_heads)
    scores = jnp.einsum(
        "bqkgd,bskd->bkgqs", qg.astype(jnp.float32),
        k_cache.astype(jnp.float32),
    )
    scores = scores * k_scale.transpose(0, 2, 1)[:, :, None, None, :] * scale
    scores = _cap_scores(scores, softcap)
    pos_q = starts[:, None] + jnp.arange(seq)[None, :]
    pos_s = jnp.arange(max_len)[None, None, :]
    allowed = (pos_s <= pos_q[:, :, None]) & (
        pos_s < lengths[:, None, None]
    )
    if window is not None:
        allowed = allowed & (
            (window <= 0) | (pos_s > pos_q[:, :, None] - window)
        )
    scores = jnp.where(allowed[:, None, None, :, :], scores, -1e30)
    weights = _softmax(scores)
    weights = weights * v_scale.transpose(0, 2, 1)[:, :, None, None, :]
    out = jnp.einsum(
        "bkgqs,bskd->bqkgd", weights, v_cache.astype(jnp.float32)
    )
    return out.reshape(batch, seq, heads, dim).astype(q.dtype)
