"""Rotary position embeddings (RoPE), Llama-3 style."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int,
    max_positions: int,
    theta: float = 500000.0,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """Precomputed [max_positions, head_dim//2] complex angles as (cos, sin)
    stacked on a leading axis of size 2."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    positions = jnp.arange(max_positions, dtype=jnp.float32)
    angles = jnp.outer(positions, inv_freq)
    return jnp.stack([jnp.cos(angles), jnp.sin(angles)]).astype(dtype)


def apply_rope(
    x: jnp.ndarray,
    freqs: jnp.ndarray,
    positions: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate ``x`` of shape [..., seq, heads, head_dim] by the angles at
    ``positions`` [..., seq]. Interleaved-pair convention (HF Llama's
    rotate_half layout: first half / second half)."""
    cos = freqs[0][positions]  # [..., seq, head_dim//2]
    sin = freqs[1][positions]
    cos = jnp.expand_dims(cos, axis=-2)  # broadcast over heads
    sin = jnp.expand_dims(sin, axis=-2)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)
