"""Rotary position embeddings (RoPE), Llama-3 style."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def _llama3_scale_inv_freq(
    inv_freq: jnp.ndarray,
    factor: float,
    low_freq_factor: float,
    high_freq_factor: float,
    original_max_positions: float,
) -> jnp.ndarray:
    """Llama-3.1 NTK-by-parts frequency scaling (HF
    ``_compute_llama3_parameters``): high-frequency components keep
    their wavelength, low-frequency ones stretch by ``factor``, and the
    band between interpolates smoothly."""
    import math

    low_wavelen = original_max_positions / low_freq_factor
    high_wavelen = original_max_positions / high_freq_factor
    wavelen = 2.0 * math.pi / inv_freq
    scaled = inv_freq / factor
    smooth = (
        original_max_positions / wavelen - low_freq_factor
    ) / (high_freq_factor - low_freq_factor)
    smoothed = (1.0 - smooth) * scaled + smooth * inv_freq
    return jnp.where(
        wavelen < high_wavelen,
        inv_freq,
        jnp.where(wavelen > low_wavelen, scaled, smoothed),
    )


def rope_frequencies(
    head_dim: int,
    max_positions: int,
    theta: float = 500000.0,
    dtype=jnp.float32,
    scaling: Optional[tuple] = None,
) -> jnp.ndarray:
    """Precomputed [max_positions, head_dim//2] complex angles as (cos, sin)
    stacked on a leading axis of size 2.

    ``scaling`` is the config's hashable rope-scaling tuple
    ``("llama3", factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings)`` — the Llama-3.1/3.2 long-context
    recipe. None = plain RoPE."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if scaling is not None:
        kind = scaling[0]
        if kind != "llama3":
            raise ValueError(f"unsupported rope scaling type: {kind!r}")
        inv_freq = _llama3_scale_inv_freq(inv_freq, *scaling[1:])
    positions = jnp.arange(max_positions, dtype=jnp.float32)
    angles = jnp.outer(positions, inv_freq)
    return jnp.stack([jnp.cos(angles), jnp.sin(angles)]).astype(dtype)


def apply_rope(
    x: jnp.ndarray,
    freqs: jnp.ndarray,
    positions: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate ``x`` of shape [..., seq, heads, head_dim] by the angles at
    ``positions`` [..., seq]. Interleaved-pair convention (HF Llama's
    rotate_half layout: first half / second half)."""
    cos = freqs[0][positions]  # [..., seq, head_dim//2]
    sin = freqs[1][positions]
    cos = jnp.expand_dims(cos, axis=-2)  # broadcast over heads
    sin = jnp.expand_dims(sin, axis=-2)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)
