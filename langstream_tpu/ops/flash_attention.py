"""Pallas TPU flash attention for causal prefill.

The plain-XLA prefill attention (``ops/attention.py``) materializes the
O(Tq·Ts) score matrix in HBM. For long prompts that dominates HBM traffic,
so this kernel computes attention blockwise in VMEM with the online-softmax
recurrence: the score tile, the softmax statistics (running max / running
sum), and the output accumulator all live in VMEM scratch; HBM sees only
q/k/v tile reads and one output tile write per q block.

Kernel layout (the canonical TPU flash schedule):

- grid = (batch, q_heads, Tq/block_q, Tk/block_k); the last grid axis is
  innermost and sequential on TPU, so VMEM scratch carries the online
  softmax state across k blocks of the same q block.
- q/k/v tiles are MXU-shaped ([block, head_dim], 128-aligned); the two
  matmuls (q·kᵀ and p·v) run on the MXU in the input dtype with f32
  accumulation; masking and the softmax recurrence run on the VPU in f32.
- GQA is folded into the k/v BlockSpec index maps (query head h reads kv
  head h // group) — no materialized head repetition.
- causal blocks strictly above the diagonal skip their compute entirely
  via ``pl.when`` (they still prefetch, which the pipeline overlaps).
- per-batch valid lengths ride in SMEM (right-padding mask).

Reference parity: this replaces the HBM-bound attention inside what the
reference would run as a remote model call (it has no kernels of its own —
`langstream-agents/langstream-ai-agents/.../OpenAICompletionService.java:52`
delegates to a provider); the kernel is the TPU-native interior of the
`jax-local` completions service.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def compat_shard_map(f, mesh, in_specs, out_specs):
    """``shard_map`` across jax versions: ≥0.8 exposes ``jax.shard_map``
    (replication checking via ``check_vma``); older releases only ship
    ``jax.experimental.shard_map.shard_map`` (``check_rep``). Every
    sharded kernel wrapper in ``ops/`` routes through here so a jax
    downgrade can't silently strand the tp paths behind an
    AttributeError."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def _flash_kernel(
    lengths_ref,  # SMEM [1, 1] — valid length for this batch row
    window_ref,   # SMEM [1, 1] — sliding window (0 = full attention)
    q_ref,        # VMEM [1, 1, block_q, d]
    k_ref,        # VMEM [1, 1, block_k, d]
    v_ref,        # VMEM [1, 1, block_k, d]
    out_ref,      # VMEM [1, 1, block_q, d]
    m_scratch,    # VMEM [block_q, 128] f32 — running row max
    l_scratch,    # VMEM [block_q, 128] f32 — running row sum
    acc_scratch,  # VMEM [block_q, d] f32 — unnormalized output
    *,
    scale: float,
    block_q: int,
    block_k: int,
    softcap: Optional[float],
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q_start = qi * block_q
    k_start = kj * block_k
    window = window_ref[0, 0]
    # Causal: skip blocks entirely in the future of the q block; with a
    # sliding window (Gemma-2) also skip blocks entirely BEFORE every
    # row's window (earliest window start in the block is
    # q_start - window + 1)
    relevant = k_start <= q_start + block_q - 1
    relevant = jnp.logical_and(
        relevant,
        (window <= 0) | (k_start + block_k - 1 >= q_start - window + 1),
    )

    @pl.when(relevant)
    def _compute():
        length = lengths_ref[0, 0]
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if softcap is not None:
            s = softcap * jnp.tanh(s / softcap)

        rows = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = jnp.logical_and(cols <= rows, cols < length)
        mask = jnp.logical_and(
            mask, (window <= 0) | (cols > rows - window)
        )
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scratch[:, :1]                      # [block_q, 1]
        row_max = jnp.max(s, axis=-1, keepdims=True)   # [block_q, 1]
        m_new = jnp.maximum(m_prev, row_max)
        # p is zeroed (not just -inf shifted) so fully-masked rows stay 0.
        p = jnp.exp(s - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)                # [block_q, 1]

        l_prev = l_scratch[:, :1]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[0, 0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, d]
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(kj == num_k - 1)
    def _finalize():
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc_scratch[:] / l_safe).astype(out_ref.dtype)


def _flash_kernel_quant(
    lengths_ref,  # SMEM [1, 1]
    window_ref,   # SMEM [1, 1] — sliding window (0 = full attention)
    q_ref,        # VMEM [1, 1, block_q, d]
    k_ref,        # VMEM [1, 1, block_k, d] int8
    v_ref,        # VMEM [1, 1, block_k, d] int8
    ks_ref,       # VMEM [1, 1, block_k] f32 — per-row k scales
    vs_ref,       # VMEM [1, 1, block_k] f32 — per-row v scales
    out_ref,      # VMEM [1, 1, block_q, d]
    m_scratch,
    l_scratch,
    acc_scratch,
    *,
    scale: float,
    block_q: int,
    block_k: int,
    softcap: Optional[float],
):
    """Int8-cache flash: k/v tiles stream from HBM as int8 (half the
    bandwidth of bf16 — the whole point), upcast in VMEM (int8 values
    are EXACTLY representable in bf16, so the MXU sees the same values
    the XLA quant path does), and the per-(position, head) scales fold
    the way ``ops/attention.py`` folds them: k_scale multiplies the
    score AFTER the q·kᵀ contraction, v_scale folds into the probs
    BEFORE p·v — neither contraction ever touches a dequantized
    cache-sized tensor."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q_start = qi * block_q
    k_start = kj * block_k
    window = window_ref[0, 0]
    relevant = k_start <= q_start + block_q - 1
    relevant = jnp.logical_and(
        relevant,
        (window <= 0) | (k_start + block_k - 1 >= q_start - window + 1),
    )

    @pl.when(relevant)
    def _compute():
        length = lengths_ref[0, 0]
        q = q_ref[0, 0]
        k = k_ref[0, 0].astype(q.dtype)   # int8 → exact in bf16
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        s = s * (ks_ref[0, 0][None, :] * scale)  # fold k scales per row
        if softcap is not None:
            s = softcap * jnp.tanh(s / softcap)

        rows = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = jnp.logical_and(cols <= rows, cols < length)
        mask = jnp.logical_and(
            mask, (window <= 0) | (cols > rows - window)
        )
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scratch[:, :1]
        row_max = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, row_max)
        p = jnp.exp(s - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)

        l_prev = l_scratch[:, :1]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[0, 0].astype(q.dtype)
        p_scaled = p * vs_ref[0, 0][None, :]  # fold v scales into probs
        pv = jax.lax.dot_general(
            p_scaled.astype(q.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(kj == num_k - 1)
    def _finalize():
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc_scratch[:] / l_safe).astype(out_ref.dtype)


def _pallas_flash(
    q: jnp.ndarray,        # [B, H, T, D]
    k: jnp.ndarray,        # [B, KVH, T, D] (bf16, or int8 with scales)
    v: jnp.ndarray,
    lengths: jnp.ndarray,  # [B] int32
    *,
    block_q: int,
    block_k: int,
    interpret: bool,
    k_scale: Optional[jnp.ndarray] = None,  # [B, KVH, T] f32
    v_scale: Optional[jnp.ndarray] = None,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,   # scalar; None/0 = full
    scale: Optional[float] = None,
) -> jnp.ndarray:
    batch, heads, seq, dim = q.shape
    kv_heads = k.shape[1]
    group = heads // kv_heads
    scale = dim ** -0.5 if scale is None else scale
    grid = (batch, heads, seq // block_q, seq // block_k)
    quantized = k_scale is not None

    lengths_2d = lengths.reshape(batch, 1).astype(jnp.int32)
    window_2d = jnp.reshape(
        jnp.asarray(0 if window is None else window, dtype=jnp.int32), (1, 1)
    )
    scalar_spec = pl.BlockSpec(
        (1, 1), lambda b, h, i, j: (b, 0), memory_space=pltpu.SMEM,
    )
    in_specs = [
        scalar_spec,
        pl.BlockSpec(
            (1, 1), lambda b, h, i, j: (0, 0),
            memory_space=pltpu.SMEM,
        ),
        pl.BlockSpec(
            (1, 1, block_q, dim), lambda b, h, i, j: (b, h, i, 0),
        ),
        pl.BlockSpec(
            (1, 1, block_k, dim), lambda b, h, i, j: (b, h // group, j, 0),
        ),
        pl.BlockSpec(
            (1, 1, block_k, dim), lambda b, h, i, j: (b, h // group, j, 0),
        ),
    ]
    operands = [lengths_2d, window_2d, q, k, v]
    if quantized:
        kernel = functools.partial(
            _flash_kernel_quant, scale=scale,
            block_q=block_q, block_k=block_k, softcap=softcap,
        )
        scale_spec = pl.BlockSpec(
            (1, 1, block_k), lambda b, h, i, j: (b, h // group, j),
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
        kv_bytes = k.size + v.size + k_scale.size * 4 + v_scale.size * 4
    else:
        kernel = functools.partial(
            _flash_kernel, scale=scale, block_q=block_q, block_k=block_k,
            softcap=softcap,
        )
        kv_bytes = (k.size + v.size) * k.dtype.itemsize

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, block_q, dim), lambda b, h, i, j: (b, h, i, 0),
        ),
        out_shape=jax.ShapeDtypeStruct((batch, heads, seq, dim), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, dim), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * batch * heads * seq * seq * dim,
            bytes_accessed=(
                (q.size + q.size) * q.dtype.itemsize + kv_bytes
            ),
            transcendentals=batch * heads * seq * seq,
        ),
        interpret=interpret,
    )(*operands)


def flash_prefill_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, T, KVH, D] (bf16; int8 when scales given)
    v: jnp.ndarray,
    *,
    mask: Optional[jnp.ndarray] = None,   # [B, T] right-padded valid mask
    lengths: Optional[jnp.ndarray] = None,  # [B] (alternative to mask)
    k_scale: Optional[jnp.ndarray] = None,  # [B, T, KVH] — int8-cache mode
    v_scale: Optional[jnp.ndarray] = None,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,  # scalar; None/0 = full attn
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal flash attention over right-padded prompts ([B, T, H, D] in
    and out). ``mask`` must be CONTIGUOUS right-padding (True for the
    first ``lengths[b]`` positions, False after) — it is collapsed to
    per-row lengths for the kernel's SMEM masking, so a non-contiguous
    (packed / loss-style) mask would be silently misapplied; use
    :func:`langstream_tpu.ops.attention.prefill_attention` for those.

    With ``k_scale``/``v_scale`` the kernel runs the int8-cache variant
    (k/v int8, per-(position, kv-head) scales — see
    :func:`_flash_kernel_quant`). ``softcap``/``window``/``scale`` carry
    the Gemma-2 mechanisms: logit capping, a (traced, per-layer) sliding
    window — blocks fully outside a row's window skip their compute —
    and the query_pre_attn_scalar score scale."""
    batch, seq, heads, dim = q.shape
    if lengths is None:
        lengths = (
            jnp.sum(mask.astype(jnp.int32), axis=-1)
            if mask is not None
            else jnp.full((batch,), seq, dtype=jnp.int32)
        )

    block_q = min(block_q, _round_up(seq, 128))
    block_k = min(block_k, _round_up(seq, 128))
    padded = _round_up(seq, max(block_q, block_k))

    # [B, T, H, D] → [B, H, T, D]; pad T to a block multiple (the length
    # mask keeps padded keys out of the softmax).
    def to_kernel_layout(x):
        x = jnp.swapaxes(x, 1, 2)
        if padded != seq:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, padded - seq), (0, 0)))
        return x

    def scales_layout(s):
        if s is None:
            return None
        s = jnp.swapaxes(s, 1, 2)  # [B, KVH, T]
        if padded != seq:
            s = jnp.pad(s, ((0, 0), (0, 0), (0, padded - seq)))
        return s.astype(jnp.float32)

    out = _pallas_flash(
        to_kernel_layout(q), to_kernel_layout(k), to_kernel_layout(v),
        lengths,
        block_q=block_q, block_k=block_k, interpret=interpret,
        k_scale=scales_layout(k_scale), v_scale=scales_layout(v_scale),
        softcap=softcap, window=window, scale=scale,
    )
    out = jnp.swapaxes(out, 1, 2)
    return out[:, :seq] if padded != seq else out


def flash_prefill_attention_quant(
    q: jnp.ndarray,        # [B, T, H, D]
    k: jnp.ndarray,        # [B, T, KVH, D] int8
    k_scale: jnp.ndarray,  # [B, T, KVH] f32
    v: jnp.ndarray,        # [B, T, KVH, D] int8
    v_scale: jnp.ndarray,  # [B, T, KVH] f32
    **kwargs,
) -> jnp.ndarray:
    """Causal flash prefill over an int8-quantized window (the cold half
    of `engine: {kv-quant: int8}`): same scale-folded algebra as
    :func:`langstream_tpu.ops.attention.chunk_attention_quant` with
    ``starts=0``, but the k/v tiles stream from HBM as int8 — quantized
    cold prefill keeps the flash HBM profile instead of falling back to
    the O(T²)-score XLA path (docs/perf.md round-3 follow-up). Thin
    argument-ordering wrapper over :func:`flash_prefill_attention` —
    its mask caveat (contiguous right-padding only) applies."""
    return flash_prefill_attention(
        q, k, v, k_scale=k_scale, v_scale=v_scale, **kwargs
    )


def flash_prefill_attention_sharded(
    q: jnp.ndarray,  # [B, T, H, D] — H sharded over ``axis_name``
    k: jnp.ndarray,  # [B, T, KVH, D] — KVH sharded over ``axis_name``
    v: jnp.ndarray,
    mesh,
    *,
    mask: Optional[jnp.ndarray] = None,
    lengths: Optional[jnp.ndarray] = None,
    k_scale: Optional[jnp.ndarray] = None,  # [B, T, KVH] — int8 mode
    v_scale: Optional[jnp.ndarray] = None,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    axis_name: str = "tp",
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash prefill under tensor parallelism.

    A Mosaic ``pallas_call`` has no SPMD partitioning rule, so it cannot
    sit inside a tp-sharded jit directly; ``shard_map`` over the head
    axis runs one independent kernel per shard — attention never mixes
    heads, so no collective is needed (the same per-shard layout the tp
    attention einsums produce). GQA stays consistent because query and
    kv heads shard by the same factor (``validate_mesh`` enforces
    divisibility). With ``k_scale``/``v_scale`` the int8-cache kernel
    runs per shard, the scales sharded over their kv-head axis. The
    (traced) ``window`` scalar rides as a replicated operand.
    """
    from jax.sharding import PartitionSpec as P

    batch = q.shape[0]
    if lengths is None:
        lengths = (
            jnp.sum(mask.astype(jnp.int32), axis=-1)
            if mask is not None
            else jnp.full((batch,), q.shape[1], dtype=jnp.int32)
        )
    head_spec = P(None, None, axis_name, None)
    scale_spec = P(None, None, axis_name)
    quantized = k_scale is not None
    window_arr = jnp.asarray(
        0 if window is None else window, dtype=jnp.int32
    )

    def local(q_l, k_l, v_l, lengths_l, window_l, *scales):
        return flash_prefill_attention(
            q_l, k_l, v_l, lengths=lengths_l, interpret=interpret,
            softcap=softcap, window=window_l, scale=scale,
            **(
                {"k_scale": scales[0], "v_scale": scales[1]}
                if scales else {}
            ),
        )

    in_specs = [head_spec, head_spec, head_spec, P(None), P()]
    operands = [q, k, v, lengths, window_arr]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    return compat_shard_map(
        local, mesh, tuple(in_specs), head_spec
    )(*operands)


def flash_prefill_attention_quant_sharded(
    q: jnp.ndarray,        # [B, T, H, D] — H sharded over ``axis_name``
    k: jnp.ndarray,        # [B, T, KVH, D] int8
    k_scale: jnp.ndarray,  # [B, T, KVH]
    v: jnp.ndarray,
    v_scale: jnp.ndarray,
    mesh,
    **kwargs,
) -> jnp.ndarray:
    """Int8 flash prefill under tensor parallelism — thin argument-
    ordering wrapper over :func:`flash_prefill_attention_sharded`."""
    return flash_prefill_attention_sharded(
        q, k, v, mesh, k_scale=k_scale, v_scale=v_scale, **kwargs
    )


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def on_tpu() -> bool:
    """True on any real TPU backend — including plugins whose platform
    name is not literally "tpu" (the tunneled v5e registers as "axon";
    ``default_backend()`` alone would silently disable the kernel)."""
    try:
        devices = jax.devices()
    except RuntimeError:  # pragma: no cover — backend init failed
        return False
    return any("TPU" in (d.device_kind or "") for d in devices)


def use_flash(seq: int, dim: int) -> bool:
    """Flash pays off once the score matrix dwarfs the tiles: long enough
    sequence, MXU-aligned head_dim, and a real TPU backend."""
    return on_tpu() and seq >= 1024 and dim % 128 == 0
