"""Fused ragged paged attention (Pallas TPU) over the KV block pool.

The gather/scatter paged paths (``ops/attention.py::paged_*``) round-trip
the ENTIRE per-row KV view through HBM before attending: ``gather_blocks``
reads every live pool block, writes a contiguous ``[B, M*Bs, KVH, D]``
copy, and the XLA attention then re-reads that copy — 3× the KV traffic
of the dense layout on the path the PR-4 roofline says is MBU-bound.
This kernel is the Ragged Paged Attention shape (PAPERS.md, arxiv
2604.15464): the block table rides into the kernel as a scalar-prefetch
operand and the BlockSpec index maps address the pool DIRECTLY, so the
pipeline DMAs each table-addressed block HBM→VMEM exactly once and the
online-softmax recurrence consumes it in place — no materialized gather,
KV traffic ∝ live (block-padded) context.

One grid covers every ragged case the engine dispatches:

- grid = (row, Tq/block_q, M); the kv-block axis is innermost and
  sequential, so VMEM scratch carries the online-softmax state across a
  row's blocks (the ``flash_attention.py`` / ``decode_kernel.py``
  recurrence).
- each row carries ``start`` (global position of its first query token)
  and ``length`` (TOTAL live context = prefix + new tokens): decode is
  ``Tq=1, start=length-1``; warm prefill-at-offset is ``start=offset``;
  cold paged prefill is ``start=0``. Query token t of row b sits at
  global position ``starts[b] + t`` and attends causally at that
  position — the same masking formulas the XLA paged paths share.
- block tables / starts / lengths / window are scalar-prefetch operands:
  available to the index maps BEFORE each block's DMA is issued. Blocks
  outside a (row, q-block)'s live range — past the causal frontier, past
  the row's length, or below its sliding window — clamp their mapped
  pool index into the live range; Pallas elides the copy when mapped
  indices repeat, so skipped blocks cost neither HBM reads nor MXU time
  (their compute is ``pl.when``-gated off).
- GQA runs as one small MXU matmul per kv head (static python loop —
  KVH is a config constant) against the block's ``[Bs, D]`` slab, with
  the q tile flattened to ``[block_q·G, D]`` per kv head.
- the int8-pool twin streams bare int8 k/v blocks through the MXU (half
  the bytes) and folds the per-(position, kv-head) scales exactly as the
  ``ops/attention.py`` quant algebra prescribes: k_scale multiplies the
  scores AFTER q·kᵀ (the score layout), v_scale folds into the probs
  BEFORE p·v, and the p·v contraction runs in f32 like the XLA quant
  path.

The gather/scatter composition stays in ``ops/attention.py`` as the
reference oracle (``paged_kernel: reference``); ``interpret=True`` runs
this kernel on CPU so tier-1 parity stays CPU-verifiable. Under tensor
parallelism the kernel dispatches through
:func:`ragged_paged_attention_sharded` — one independent launch per
kv-head shard via ``shard_map`` (a bare Mosaic call has no SPMD
partitioning rule), tables/starts/lengths replicated, the pool split on
its kv-head axis — the same twin pattern ``flash_attention.py`` /
``decode_kernel.py`` use.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _last_live_block(total, block_size: int):
    """Index of the last block holding live rows (≥0 so empty rows still
    map block 0 — fully masked, finalize emits zeros)."""
    return jnp.maximum(1, (total + block_size - 1) // block_size) - 1


def _block_bounds(start, total, window, qi, *, block_q: int, block_size: int):
    """[first, last] table-block range a q tile actually needs: causal
    frontier of the tile's LAST query caps the top, the row's length
    caps it again, and a sliding window (of the tile's FIRST query)
    floors the bottom. Everything outside clamps into this range, which
    elides the DMA and skips the compute."""
    last = jnp.minimum(
        _last_live_block(total, block_size),
        (start + (qi + 1) * block_q - 1) // block_size,
    )
    last = jnp.maximum(last, 0)
    first = jnp.where(
        window > 0,
        jnp.maximum(0, (start + qi * block_q - window + 1) // block_size),
        0,
    )
    return jnp.minimum(first, last), last


def _ragged_kernel_body(
    tables_ref,  # SMEM scalar-prefetch [B, M] int32
    starts_ref,  # SMEM scalar-prefetch [B] int32
    totals_ref,  # SMEM scalar-prefetch [B] int32
    win_ref,     # SMEM scalar-prefetch [1] int32 (0 = full attention)
    q_ref,       # VMEM [1, block_q, H, D]
    k_ref,       # VMEM [1, Bs, KVH, D] (pool dtype, or int8)
    v_ref,       # VMEM [1, Bs, KVH, D]
    ks_ref,      # VMEM [1, Bs, KVH] f32, or None (bf16 pool)
    vs_ref,      # VMEM [1, Bs, KVH] f32, or None
    out_ref,     # VMEM [1, block_q, H, D]
    m_scratch,   # VMEM [block_q*H, 128] f32 — running row max
    l_scratch,   # VMEM [block_q*H, 128] f32 — running row sum
    acc_scratch,  # VMEM [block_q*H, D] f32
    *,
    scale: float,
    block_q: int,
    block_size: int,
    kv_heads: int,
    group: int,
    softcap: Optional[float],
    ragged_q: bool = False,
):
    """One online-softmax recurrence for both pool dtypes. Rows of the
    score/accumulator tiles are kv-head-major: row ``h·(block_q·G) +
    t·G + g`` is query token ``t`` of query head ``h·G + g`` — the
    per-head q·kᵀ matmuls concatenate along axis 0 and the finalize
    un-permutes back to ``[block_q, H, D]``.

    ``ragged_q`` is the token-ragged q formulation (mixed prefill+decode
    dispatch): each row's live query count is ``total - start`` and may
    differ per row, so q tiles past a row's live count gate off their
    compute AND their finalize — their (clamped) output block belongs to
    the row's last live tile, which already wrote it."""
    quantized = ks_ref is not None
    b = pl.program_id(0)
    qi = pl.program_id(1)
    j = pl.program_id(2)
    num_j = pl.num_programs(2)
    rows_per_head = block_q * group

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    start = starts_ref[b]
    total = totals_ref[b]
    window = win_ref[0]
    # live-tile gate for the ragged-q grid: a tile whose first query
    # index is past the row's live count is dead (its q/KV index maps
    # clamp into the live range, so its DMAs are elided; compute and
    # the live finalize are gated off, and the tile's OWN output block
    # — out tiles never clamp — is zeroed instead, so masked positions
    # are deterministic zeros rather than uninitialized VMEM: the
    # mixed dispatch's null-block writes derive from them, and the
    # mirror replays must be bitwise)
    live = (qi * block_q < total - start) if ragged_q else True
    if ragged_q:

        @pl.when((j == num_j - 1) & jnp.logical_not(live))
        def _zero_dead():
            out_ref[0] = jnp.zeros_like(out_ref[0])
    first, last = _block_bounds(
        start, total, window, qi, block_q=block_q, block_size=block_size
    )

    @pl.when((j >= first) & (j <= last) & live)
    def _compute():
        q = q_ref[0]  # [block_q, H, D]
        # int8 pool values are exactly representable in bf16/f32, so the
        # MXU sees the same numbers the XLA quant path computes
        k = k_ref[0].astype(q.dtype) if quantized else k_ref[0]
        ks = ks_ref[0] if quantized else None  # [Bs, KVH] f32
        parts = []
        for h in range(kv_heads):
            q_h = q[:, h * group:(h + 1) * group, :].reshape(
                rows_per_head, q.shape[-1]
            )
            k_h = k[:, h, :]  # [Bs, D]
            s_h = jax.lax.dot_general(
                q_h, k_h, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if quantized:
                s_h = s_h * ks[:, h][None, :]
            parts.append(s_h)
        s = jnp.concatenate(parts, axis=0)  # [block_q*H, Bs]
        if softcap is not None:
            s = softcap * jnp.tanh(s / softcap)

        # global position of each score row's query: rows are kv-head-
        # major, so token index = (row % rows_per_head) // group
        row_ids = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        q_pos = start + qi * block_q + (row_ids % rows_per_head) // group
        cols = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        mask = (cols <= q_pos) & (cols < total)
        mask = jnp.logical_and(
            mask, (window <= 0) | (cols > q_pos - window)
        )
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scratch[:, :1]
        row_max = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, row_max)
        # p is zeroed (not just -inf shifted) so fully-masked rows stay 0
        p = jnp.exp(s - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)
        l_scratch[:] = jnp.broadcast_to(
            l_scratch[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_scratch.shape,
        )

        if quantized:
            v = v_ref[0].astype(jnp.float32)  # f32 contraction, as XLA
            vs = vs_ref[0]                    # [Bs, KVH] f32
        else:
            v = v_ref[0]
        pv_parts = []
        for h in range(kv_heads):
            p_h = p[h * rows_per_head:(h + 1) * rows_per_head]
            if quantized:
                p_h = p_h * vs[:, h][None, :]
            else:
                p_h = p_h.astype(v.dtype)
            v_h = v[:, h, :]  # [Bs, D]
            pv_parts.append(
                jax.lax.dot_general(
                    p_h, v_h, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
        pv = jnp.concatenate(pv_parts, axis=0)  # [block_q*H, D]
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)

    @pl.when((j == num_j - 1) & live)
    def _finalize():
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc_scratch[:] / l_safe  # [block_q*H, D] kv-head-major
        dim = out.shape[-1]
        out = out.reshape(kv_heads, block_q, group, dim)
        out = out.transpose(1, 0, 2, 3).reshape(
            block_q, kv_heads * group, dim
        )
        out_ref[0] = out.astype(out_ref.dtype)


def _ragged_kernel(tables_ref, starts_ref, totals_ref, win_ref, q_ref,
                   k_ref, v_ref, out_ref, m_scratch, l_scratch,
                   acc_scratch, **kw):
    _ragged_kernel_body(
        tables_ref, starts_ref, totals_ref, win_ref, q_ref, k_ref, v_ref,
        None, None, out_ref, m_scratch, l_scratch, acc_scratch, **kw,
    )


def _ragged_kernel_quant(tables_ref, starts_ref, totals_ref, win_ref,
                         q_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref,
                         m_scratch, l_scratch, acc_scratch, **kw):
    _ragged_kernel_body(
        tables_ref, starts_ref, totals_ref, win_ref, q_ref, k_ref, v_ref,
        ks_ref, vs_ref, out_ref, m_scratch, l_scratch, acc_scratch, **kw,
    )


def _ragged_q_kernel(tables_ref, starts_ref, totals_ref, qoff_ref, win_ref,
                     q_ref, k_ref, v_ref, out_ref, m_scratch, l_scratch,
                     acc_scratch, **kw):
    # qoff_ref is consumed by the index maps only (it addresses the
    # flattened q tile); the recurrence itself needs just starts/totals
    del qoff_ref
    _ragged_kernel_body(
        tables_ref, starts_ref, totals_ref, win_ref, q_ref, k_ref, v_ref,
        None, None, out_ref, m_scratch, l_scratch, acc_scratch,
        ragged_q=True, **kw,
    )


def _ragged_q_kernel_quant(tables_ref, starts_ref, totals_ref, qoff_ref,
                           win_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                           out_ref, m_scratch, l_scratch, acc_scratch, **kw):
    del qoff_ref
    _ragged_kernel_body(
        tables_ref, starts_ref, totals_ref, win_ref, q_ref, k_ref, v_ref,
        ks_ref, vs_ref, out_ref, m_scratch, l_scratch, acc_scratch,
        ragged_q=True, **kw,
    )


def ragged_paged_attention(
    q: jnp.ndarray,             # [B, Tq, H, D] (right-padded new tokens)
    k_pool: jnp.ndarray,        # [N, Bs, KVH, D] (bf16/f32; int8 w/ scales)
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, M] pool block per sequence block
    starts: jnp.ndarray,        # [B] global position of each row's query 0
    lengths: jnp.ndarray,       # [B] TOTAL live context (prefix + new)
    *,
    k_scale: Optional[jnp.ndarray] = None,  # [N, Bs, KVH] — int8 pools
    v_scale: Optional[jnp.ndarray] = None,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,   # scalar; None/0 = full attn
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """One fused launch over the block pool for decode (Tq=1,
    start=length-1), warm prefill-at-offset (start=offset), and cold
    paged prefill (start=0) — drop-in for the per-path
    :func:`langstream_tpu.ops.attention.paged_decode_attention` /
    ``paged_chunk_attention`` gathers (or their ``_quant`` twins when
    scales are given). Returns [B, Tq, H, D]; rows past a row's new-token
    count compute garbage exactly like the XLA paths (callers index by
    length). Caller gates via :func:`use_fused_paged`."""
    batch, seq, heads, dim = q.shape
    num_blocks_table = block_tables.shape[1]
    block_size, kv_heads = k_pool.shape[1], k_pool.shape[2]
    group = heads // kv_heads
    scale = dim ** -0.5 if scale is None else scale
    quantized = k_scale is not None
    block_q = min(block_q or 128, seq)
    padded = -(-seq // block_q) * block_q
    if padded != seq:
        q = jnp.pad(q, ((0, 0), (0, padded - seq), (0, 0), (0, 0)))
    num_q_blocks = padded // block_q

    tables = block_tables.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    totals = lengths.astype(jnp.int32)
    window_arr = jnp.reshape(
        jnp.asarray(0 if window is None else window, dtype=jnp.int32), (1,)
    )

    def kv_block(b, qi, j, tables, starts, totals, win):
        first, last = _block_bounds(
            starts[b], totals[b], win[0], qi,
            block_q=block_q, block_size=block_size,
        )
        # dead blocks clamp into the live range: the mapped pool indices
        # repeat, so the pipeline skips their DMA entirely
        return tables[b, jnp.clip(j, first, last)]

    def kv_index(b, qi, j, tables, starts, totals, win):
        return (kv_block(b, qi, j, tables, starts, totals, win), 0, 0, 0)

    def scale_index(b, qi, j, tables, starts, totals, win):
        return (kv_block(b, qi, j, tables, starts, totals, win), 0, 0)

    def q_index(b, qi, j, tables, starts, totals, win):
        return (b, qi, 0, 0)

    in_specs = [
        pl.BlockSpec((1, block_q, heads, dim), q_index),
        pl.BlockSpec((1, block_size, kv_heads, dim), kv_index),
        pl.BlockSpec((1, block_size, kv_heads, dim), kv_index),
    ]
    operands = [q, k_pool, v_pool]
    kernel_kw = dict(
        scale=scale, block_q=block_q, block_size=block_size,
        kv_heads=kv_heads, group=group, softcap=softcap,
    )
    if quantized:
        kernel = functools.partial(_ragged_kernel_quant, **kernel_kw)
        in_specs += [
            pl.BlockSpec((1, block_size, kv_heads), scale_index),
            pl.BlockSpec((1, block_size, kv_heads), scale_index),
        ]
        operands += [
            k_scale.astype(jnp.float32), v_scale.astype(jnp.float32),
        ]
        kv_bytes = k_pool.size + v_pool.size + (
            k_scale.size + v_scale.size
        ) * 4
    else:
        kernel = functools.partial(_ragged_kernel, **kernel_kw)
        kv_bytes = (k_pool.size + v_pool.size) * k_pool.dtype.itemsize

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(batch, num_q_blocks, num_blocks_table),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, heads, dim), q_index),
        scratch_shapes=[
            pltpu.VMEM((block_q * heads, 128), jnp.float32),
            pltpu.VMEM((block_q * heads, 128), jnp.float32),
            pltpu.VMEM((block_q * heads, dim), jnp.float32),
        ],
    )
    ctx = num_blocks_table * block_size
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, padded, heads, dim), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * batch * padded * heads * ctx * dim,
            # the whole point: the scheduler should expect table-
            # addressed block traffic, not a gathered copy (estimate at
            # half occupancy, like the flash-decode kernel)
            bytes_accessed=q.size * q.dtype.itemsize * 2 + kv_bytes // 2,
            transcendentals=batch * padded * heads * ctx,
        ),
        interpret=interpret,
    )(tables, starts, totals, window_arr, *operands)
    return out[:, :seq] if padded != seq else out


def ragged_paged_attention_quant(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,     # [N, Bs, KVH, D] int8
    k_scale: jnp.ndarray,    # [N, Bs, KVH] f32
    v_pool: jnp.ndarray,
    v_scale: jnp.ndarray,
    block_tables: jnp.ndarray,
    starts: jnp.ndarray,
    lengths: jnp.ndarray,
    **kwargs,
) -> jnp.ndarray:
    """Argument-ordering twin of
    :func:`langstream_tpu.ops.attention.paged_chunk_attention_quant`."""
    return ragged_paged_attention(
        q, k_pool, v_pool, block_tables, starts, lengths,
        k_scale=k_scale, v_scale=v_scale, **kwargs,
    )


def ragged_q_paged_attention(
    q: jnp.ndarray,             # [Q, H, D] flattened new-token tile
    k_pool: jnp.ndarray,        # [N, Bs, KVH, D] (bf16/f32; int8 w/ scales)
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, M] pool block per sequence block
    starts: jnp.ndarray,        # [B] global position of each row's query 0
    lengths: jnp.ndarray,       # [B] TOTAL live context (prefix + new)
    q_offsets: jnp.ndarray,     # [B] row offsets into the flat q tile
    *,
    max_q_len: int,             # static per-row span capacity in q
    k_scale: Optional[jnp.ndarray] = None,  # [N, Bs, KVH] — int8 pools
    v_scale: Optional[jnp.ndarray] = None,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,   # scalar; None/0 = full attn
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Token-ragged q formulation: ONE grid serves rows with
    Tq ∈ {0..max_q_len} — the mixed prefill+decode dispatch shape
    (Sarathi/DeepServe chunked-prefill batching on the RPA schedule).

    Row ``b``'s live queries are ``lengths[b] - starts[b]`` tokens
    (decode rows carry 1, admitting rows carry a prefill window, idle
    rows 0) living at ``q_offsets[b] .. q_offsets[b]+live-1`` of the
    flattened ``q`` tile — cu_q_lens-style row offsets, carried as a
    scalar-prefetch operand next to the existing starts/lengths. Each
    row's span must be ``block_q``-aligned (``q_offsets`` multiples of
    ``block_q``, spans padded up to it); q tiles past a row's live
    count clamp their index maps into the row's LAST live tile — the
    repeated mapped indices elide the q/KV DMAs — and gate off both
    compute and finalize, so attention work is ∝ live tokens per row,
    not ∝ the padded span. Returns the flat [Q, H, D] outputs; padding
    positions within a live tile compute garbage exactly like the XLA
    paths (callers index by the row's live count)."""
    total_q, heads, dim = q.shape
    batch, num_blocks_table = block_tables.shape
    block_size, kv_heads = k_pool.shape[1], k_pool.shape[2]
    group = heads // kv_heads
    scale = dim ** -0.5 if scale is None else scale
    quantized = k_scale is not None
    block_q = min(block_q or 8, max_q_len)
    if max_q_len % block_q or total_q % block_q:
        raise ValueError(
            f"ragged-q spans must tile by block_q={block_q} "
            f"(max_q_len={max_q_len}, flat q={total_q})"
        )
    num_q_tiles = max_q_len // block_q
    # 4-d view so the shared kernel body's [1, block_q, H, D] ref shape
    # (and the scratch layout) match the fixed-Tq kernel exactly
    q_tiles = q.reshape(total_q // block_q, block_q, heads, dim)

    tables = block_tables.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    totals = lengths.astype(jnp.int32)
    qoffs = q_offsets.astype(jnp.int32)
    window_arr = jnp.reshape(
        jnp.asarray(0 if window is None else window, dtype=jnp.int32), (1,)
    )

    def live_tile(b, qi, starts, totals):
        # last live q tile of row b (>=0 so fully-dead rows clamp to
        # tile 0 — gated off in the kernel)
        q_len = totals[b] - starts[b]
        tiles = jnp.maximum(1, (q_len + block_q - 1) // block_q)
        return jnp.minimum(qi, tiles - 1)

    def q_index(b, qi, j, tables, starts, totals, qoffs, win):
        return (
            qoffs[b] // block_q + live_tile(b, qi, starts, totals),
            0, 0, 0,
        )

    def out_index(b, qi, j, tables, starts, totals, qoffs, win):
        # out tiles do NOT clamp: a dead tile owns its span position and
        # writes zeros there (see the kernel's _zero_dead), so padding
        # positions are deterministic instead of uninitialized
        return (qoffs[b] // block_q + qi, 0, 0, 0)

    def kv_block(b, qi, j, tables, starts, totals, qoffs, win):
        qi_live = live_tile(b, qi, starts, totals)
        first, last = _block_bounds(
            starts[b], totals[b], win[0], qi_live,
            block_q=block_q, block_size=block_size,
        )
        # dead q tiles AND dead kv blocks clamp into the live range:
        # repeated mapped indices elide the DMA entirely
        return tables[b, jnp.clip(j, first, last)]

    def kv_index(b, qi, j, tables, starts, totals, qoffs, win):
        return (kv_block(b, qi, j, tables, starts, totals, qoffs, win),
                0, 0, 0)

    def scale_index(b, qi, j, tables, starts, totals, qoffs, win):
        return (kv_block(b, qi, j, tables, starts, totals, qoffs, win),
                0, 0)

    in_specs = [
        pl.BlockSpec((1, block_q, heads, dim), q_index),
        pl.BlockSpec((1, block_size, kv_heads, dim), kv_index),
        pl.BlockSpec((1, block_size, kv_heads, dim), kv_index),
    ]
    operands = [q_tiles, k_pool, v_pool]
    kernel_kw = dict(
        scale=scale, block_q=block_q, block_size=block_size,
        kv_heads=kv_heads, group=group, softcap=softcap,
    )
    if quantized:
        kernel = functools.partial(_ragged_q_kernel_quant, **kernel_kw)
        in_specs += [
            pl.BlockSpec((1, block_size, kv_heads), scale_index),
            pl.BlockSpec((1, block_size, kv_heads), scale_index),
        ]
        operands += [
            k_scale.astype(jnp.float32), v_scale.astype(jnp.float32),
        ]
        kv_bytes = k_pool.size + v_pool.size + (
            k_scale.size + v_scale.size
        ) * 4
    else:
        kernel = functools.partial(_ragged_q_kernel, **kernel_kw)
        kv_bytes = (k_pool.size + v_pool.size) * k_pool.dtype.itemsize

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(batch, num_q_tiles, num_blocks_table),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, heads, dim), out_index),
        scratch_shapes=[
            pltpu.VMEM((block_q * heads, 128), jnp.float32),
            pltpu.VMEM((block_q * heads, 128), jnp.float32),
            pltpu.VMEM((block_q * heads, dim), jnp.float32),
        ],
    )
    ctx = num_blocks_table * block_size
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (total_q // block_q, block_q, heads, dim), q.dtype
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * batch * max_q_len * heads * ctx * dim,
            bytes_accessed=q.size * q.dtype.itemsize * 2 + kv_bytes // 2,
            transcendentals=batch * max_q_len * heads * ctx,
        ),
        interpret=interpret,
    )(tables, starts, totals, qoffs, window_arr, *operands)
    return out.reshape(total_q, heads, dim)


def ragged_q_paged_attention_quant(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,     # [N, Bs, KVH, D] int8
    k_scale: jnp.ndarray,    # [N, Bs, KVH] f32
    v_pool: jnp.ndarray,
    v_scale: jnp.ndarray,
    block_tables: jnp.ndarray,
    starts: jnp.ndarray,
    lengths: jnp.ndarray,
    q_offsets: jnp.ndarray,
    **kwargs,
) -> jnp.ndarray:
    """Int8-pool twin of :func:`ragged_q_paged_attention` (argument
    ordering matches the other ``*_quant`` wrappers)."""
    return ragged_q_paged_attention(
        q, k_pool, v_pool, block_tables, starts, lengths, q_offsets,
        k_scale=k_scale, v_scale=v_scale, **kwargs,
    )


def ragged_q_paged_attention_sharded(
    q: jnp.ndarray,             # [Q, H, D] — H sharded over ``axis_name``
    k_pool: jnp.ndarray,        # [N, Bs, KVH, D] — KVH sharded
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, M] (replicated host metadata)
    starts: jnp.ndarray,        # [B]
    lengths: jnp.ndarray,       # [B]
    q_offsets: jnp.ndarray,     # [B] (replicated)
    mesh,
    *,
    max_q_len: int,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    axis_name: str = "tp",
    interpret: bool = False,
) -> jnp.ndarray:
    """Token-ragged q kernel under tensor parallelism — the shard_map
    twin, exactly like :func:`ragged_paged_attention_sharded`: one
    independent launch per kv-head shard, tables/starts/lengths/
    q_offsets replicated scalar-prefetch, pool/q/out split on their
    kv-head/head axes (attention never mixes kv heads, so no
    collective)."""
    from jax.sharding import PartitionSpec as P

    head_spec = P(None, axis_name, None)         # flat q / out [Q, H, D]
    pool_spec = P(None, None, axis_name, None)   # [N, Bs, KVH, D]
    scale_spec = P(None, None, axis_name)        # [N, Bs, KVH]
    quantized = k_scale is not None
    window_arr = jnp.asarray(
        0 if window is None else window, dtype=jnp.int32
    )

    def local(q_l, k_l, v_l, tables_l, starts_l, totals_l, qoffs_l,
              window_l, *scales):
        return ragged_q_paged_attention(
            q_l, k_l, v_l, tables_l, starts_l, totals_l, qoffs_l,
            max_q_len=max_q_len, interpret=interpret, softcap=softcap,
            window=window_l, scale=scale, block_q=block_q,
            **(
                {"k_scale": scales[0], "v_scale": scales[1]}
                if scales else {}
            ),
        )

    in_specs = [
        head_spec, pool_spec, pool_spec,
        P(None, None), P(None), P(None), P(None), P(),
    ]
    operands = [
        q, k_pool, v_pool, block_tables, starts, lengths, q_offsets,
        window_arr,
    ]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    from langstream_tpu.ops.flash_attention import compat_shard_map

    return compat_shard_map(
        local, mesh, tuple(in_specs), head_spec
    )(*operands)


def ragged_paged_attention_sharded(
    q: jnp.ndarray,             # [B, Tq, H, D] — H sharded over ``axis_name``
    k_pool: jnp.ndarray,        # [N, Bs, KVH, D] — KVH sharded
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, M] (replicated host metadata)
    starts: jnp.ndarray,        # [B]
    lengths: jnp.ndarray,       # [B]
    mesh,
    *,
    k_scale: Optional[jnp.ndarray] = None,  # [N, Bs, KVH] — int8 pools
    v_scale: Optional[jnp.ndarray] = None,
    softcap: Optional[float] = None,
    window: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    axis_name: str = "tp",
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused ragged paged attention under tensor parallelism — the paged
    twin of ``flash_prefill_attention_sharded`` /
    ``flash_decode_attention_sharded``.

    A Mosaic ``pallas_call`` has no SPMD partitioning rule, so the kernel
    cannot sit inside a tp-sharded jit directly; ``shard_map`` over the
    kv-head axis runs one independent launch per shard. Attention never
    mixes kv heads, so no collective is needed: each shard's kernel sees
    a contiguous local head slab of the pool (the layout
    ``model.paged_cache_logical_axes`` pins — kv_heads shard, pool blocks
    never do), the q/output head axis splits by the same tp factor
    (``validate_mesh`` enforces divisibility, so the GQA group size is
    shard-invariant and the per-kv-head MXU loop runs over the local
    shard only). Block tables, starts, lengths, and the (traced)
    ``window`` scalar are replicated operands — the same host metadata
    every shard prefetches in full. With ``k_scale``/``v_scale`` the
    int8-pool kernel runs per shard, scales sharded over their kv-head
    axis."""
    from jax.sharding import PartitionSpec as P

    head_spec = P(None, None, axis_name, None)   # q / out [B, Tq, H, D]
    pool_spec = P(None, None, axis_name, None)   # [N, Bs, KVH, D]
    scale_spec = P(None, None, axis_name)        # [N, Bs, KVH]
    quantized = k_scale is not None
    window_arr = jnp.asarray(
        0 if window is None else window, dtype=jnp.int32
    )

    def local(q_l, k_l, v_l, tables_l, starts_l, totals_l, window_l,
              *scales):
        return ragged_paged_attention(
            q_l, k_l, v_l, tables_l, starts_l, totals_l,
            interpret=interpret, softcap=softcap, window=window_l,
            scale=scale, block_q=block_q,
            **(
                {"k_scale": scales[0], "v_scale": scales[1]}
                if scales else {}
            ),
        )

    in_specs = [
        head_spec, pool_spec, pool_spec,
        P(None, None), P(None), P(None), P(),
    ]
    operands = [q, k_pool, v_pool, block_tables, starts, lengths, window_arr]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    from langstream_tpu.ops.flash_attention import compat_shard_map

    return compat_shard_map(
        local, mesh, tuple(in_specs), head_spec
    )(*operands)


def ragged_paged_attention_quant_sharded(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,     # [N, Bs, KVH, D] int8
    k_scale: jnp.ndarray,    # [N, Bs, KVH] f32
    v_pool: jnp.ndarray,
    v_scale: jnp.ndarray,
    block_tables: jnp.ndarray,
    starts: jnp.ndarray,
    lengths: jnp.ndarray,
    mesh,
    **kwargs,
) -> jnp.ndarray:
    """Int8-pool twin of :func:`ragged_paged_attention_sharded` — thin
    argument-ordering wrapper."""
    return ragged_paged_attention_sharded(
        q, k_pool, v_pool, block_tables, starts, lengths, mesh,
        k_scale=k_scale, v_scale=v_scale, **kwargs,
    )


def fused_shapes_ok(heads: int, kv_heads: int) -> bool:
    """Structural requirement (holds on ANY backend): GQA folds into the
    per-kv-head matmul loop, so query heads must group evenly."""
    return kv_heads > 0 and heads % kv_heads == 0


def use_fused_paged(
    dim: int, heads: int, kv_heads: int, interpret: bool = False
) -> bool:
    """Kernel gate: structurally-valid GQA always; beyond that, a real
    TPU backend with an MXU-aligned head_dim — or interpret mode (the
    CPU test hook), where Mosaic's tiling constraints don't apply, so
    tiny test shapes exercise the exact kernel schedule tier-1 can
    verify."""
    if not fused_shapes_ok(heads, kv_heads):
        return False
    if interpret:
        return True
    from langstream_tpu.ops.flash_attention import on_tpu

    return on_tpu() and dim % 128 == 0
