"""Core JAX/Pallas ops: attention, norms, rotary embeddings.

These are the framework's "kernels". XLA already fuses elementwise chains
into the surrounding matmuls; Pallas kernels are reserved for the ops XLA
cannot schedule optimally (flash attention over long prefill, ring
attention over the sp axis).
"""

from langstream_tpu.ops.norms import rms_norm
from langstream_tpu.ops.rope import apply_rope, rope_frequencies
from langstream_tpu.ops.attention import (
    decode_attention,
    prefill_attention,
)

__all__ = [
    "apply_rope",
    "decode_attention",
    "prefill_attention",
    "rms_norm",
    "rope_frequencies",
]
