"""Shared training/scoring losses (single source of truth for the plain
and pipeline-parallel train paths)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def causal_ce_loss(
    logits: jnp.ndarray,  # [B, T, V]
    tokens: jnp.ndarray,  # [B, T] int
    mask: jnp.ndarray,    # [B, T] valid-token mask
) -> jnp.ndarray:
    """Next-token cross-entropy, mean over valid target positions."""
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    valid = mask[:, 1:].astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    token_ll = jnp.take_along_axis(
        log_probs, targets[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    total = jnp.maximum(valid.sum(), 1.0)
    return -(token_ll * valid).sum() / total
