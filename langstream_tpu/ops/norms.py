"""Normalization ops."""

from __future__ import annotations

import jax.lax
import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    plus_one: bool = False,
) -> jnp.ndarray:
    """RMSNorm in f32 accumulation regardless of input dtype (the TPU
    recipe: keep reductions in f32, matmuls in bf16). ``plus_one``
    selects the zero-centered weight convention (Gemma: the checkpoint
    stores w and the norm applies 1 + w)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    variance = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(variance + eps)
    w32 = weight.astype(jnp.float32)
    if plus_one:
        w32 = 1.0 + w32
    return (normed * w32).astype(dtype)
