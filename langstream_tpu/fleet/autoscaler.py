"""SLO-driven autoscaler: burn rates + queue depth → replica count.

The control loop consumes the signals every replica already exports
through ``engines_snapshot`` and gossips in its heartbeat (see
``fleet/heartbeat.py``):

- **SLO burn rates** (``jax_engine_slo_{ttft,tpot}_burn_rate_5m`` from
  PR 4's multi-window :class:`~langstream_tpu.runtime.accounting.SLOTracker`):
  burn > 1 means the fleet is consuming error budget faster than the
  SLO allows — the canonical "scale up" signal (DeepServe, arxiv
  2501.14417, scales on exactly this).
- **Queue depth** per replica (``jax_engine_queue_depth``): backlog
  that will become TTFT violations one admission later.
- **Shed counts** (``requests_shed_total{reason="queue_timeout"}``):
  a nonzero delta means the admission deadline is already failing
  callers — pressure regardless of what the burn windows say yet.

Decisions are **hysteretic** so the fleet never flaps: scale-up needs
the up-cooldown elapsed, scale-down additionally needs
``idle_evals`` consecutive calm evaluations AND the down-cooldown —
and a scale-down never kills sessions: the victim (highest ordinal,
matching StatefulSet semantics) is first marked **draining** in the
router (no new sessions; resident prefix chains age out with the last
ones), and the StatefulSet is only shrunk once the victim reports an
empty queue and zero active sessions.

The actuator is ``Operator.scale(namespace, name, replicas)`` patching
the StatefulSet through the kube API — :class:`MockKubeApi` in tests,
so the whole loop is CPU-verifiable end to end.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Dict, List, Optional, Sequence

from langstream_tpu.fleet.router import FleetRouter, ReplicaState

logger = logging.getLogger(__name__)

_BURN_KEYS = (
    "jax_engine_slo_ttft_burn_rate_5m",
    "jax_engine_slo_tpot_burn_rate_5m",
)
_SHED_KEY = 'requests_shed_total{reason="queue_timeout"}'


@dataclasses.dataclass
class AutoscalePolicy:
    """Thresholds + hysteresis knobs. Defaults suit a small fleet; the
    important invariants are threshold GAPS (burn_up > burn_down,
    queue_up > queue_down) — equal thresholds would flap on noise."""

    min_replicas: int = 1
    max_replicas: int = 8
    burn_up: float = 1.0        # any replica burning budget ≥ as fast as allowed
    burn_down: float = 0.25     # all replicas comfortably inside budget
    queue_up: float = 4.0       # mean backlog per replica
    queue_down: float = 0.5
    up_cooldown_s: float = 30.0
    down_cooldown_s: float = 120.0
    idle_evals: int = 3         # consecutive calm evaluations before down
    step: int = 1               # replicas added per scale-up decision

    def __post_init__(self) -> None:
        if self.burn_down >= self.burn_up:
            raise ValueError("burn_down must be < burn_up (hysteresis gap)")
        if self.queue_down >= self.queue_up:
            raise ValueError("queue_down must be < queue_up (hysteresis gap)")
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")


@dataclasses.dataclass
class AutoscaleDecision:
    current: int
    target: int
    reason: str
    draining: List[str] = dataclasses.field(default_factory=list)


class SLOAutoscaler:
    """One fleet's scaling brain. ``scale`` is the actuator callback
    ``(replicas: int) -> None`` — typically
    ``lambda n: operator.scale(namespace, name, n)``. All clock inputs
    take an explicit ``now`` so simulated fleets run on simulated
    time."""

    def __init__(
        self,
        policy: Optional[AutoscalePolicy] = None,
        *,
        scale: Optional[Callable[[int], None]] = None,
        role: Optional[str] = None,
        burn_keys: Optional[Sequence[str]] = None,
    ) -> None:
        """``role`` scopes this instance to one disaggregation pool: it
        evaluates (and drains) only replicas gossiping that role, so a
        disaggregated fleet runs one autoscaler per pool, each on its
        own StatefulSet and its own signals. ``burn_keys`` overrides
        the burn-rate gauges that count as pressure — queue/TTFT burn
        for a prefill pool (cold prompts stack up as admission
        backlog), TPOT burn for a decode pool (its SLO is the
        inter-token gap, and TTFT there is the prefill pool's problem).
        Defaults preserve the unified behavior exactly."""
        self.policy = policy or AutoscalePolicy()
        self._scale = scale
        self.role = role
        self._burn_keys = tuple(burn_keys) if burn_keys else _BURN_KEYS
        self._last_up_at = float("-inf")
        self._last_down_at = float("-inf")
        self._calm_evals = 0
        # per-replica shed baselines: a replica blinking out of one
        # eval's fresh set and back must not re-count its lifetime
        # counter as new pressure (entries persist across absences;
        # max(0, …) absorbs a restarted replica's counter reset)
        self._last_shed: Dict[str, float] = {}
        self._draining: List[str] = []
        self.last_eval_hot = False
        self.target = 0  # last decided target (0 = no evaluation yet)
        self.events: Dict[str, int] = {"up": 0, "down": 0}
        self.decisions: List[AutoscaleDecision] = []

    # ------------------------------------------------------------------ #
    # signal extraction
    # ------------------------------------------------------------------ #
    def _pressure(self, replicas: Sequence[ReplicaState]) -> Dict[str, float]:
        max_burn, queue_sum, shed_delta = 0.0, 0.0, 0.0
        for state in replicas:
            for key in self._burn_keys:
                max_burn = max(max_burn, state.gauges.get(key, 0.0))
            queue_sum += state.queue_depth
            if _SHED_KEY in state.gauges:
                shed = state.gauges[_SHED_KEY]
                baseline = self._last_shed.get(state.replica_id)
                if baseline is not None:
                    shed_delta += max(0.0, shed - baseline)
                # first sighting establishes the baseline only — a
                # joining replica's lifetime counter is not a spike
                self._last_shed[state.replica_id] = shed
        mean_queue = queue_sum / len(replicas) if replicas else 0.0
        return {
            "max_burn": max_burn,
            "mean_queue": mean_queue,
            "shed_delta": shed_delta,
        }

    # ------------------------------------------------------------------ #
    # decision
    # ------------------------------------------------------------------ #
    def evaluate(
        self,
        replicas: Sequence[ReplicaState],
        current: int,
        now: Optional[float] = None,
    ) -> AutoscaleDecision:
        """Pure-ish decision: computes the target count from the fleet
        view without actuating. Records the decision for flap audits
        (tests assert the sequence is monotone per direction)."""
        now = time.monotonic() if now is None else now
        policy = self.policy
        signals = self._pressure(replicas)
        shed_delta = signals["shed_delta"]

        hot = (
            signals["max_burn"] >= policy.burn_up
            or signals["mean_queue"] >= policy.queue_up
            or shed_delta > 0
        )
        calm = (
            signals["max_burn"] <= policy.burn_down
            and signals["mean_queue"] <= policy.queue_down
            and shed_delta == 0
        )

        target, reason = current, "steady"
        self.last_eval_hot = hot
        if hot:
            self._calm_evals = 0
            if now - self._last_up_at >= policy.up_cooldown_s:
                target = min(policy.max_replicas, current + policy.step)
                if target > current:
                    reason = (
                        f"scale-up: burn {signals['max_burn']:.2f} / "
                        f"queue {signals['mean_queue']:.1f} / "
                        f"shed +{shed_delta:.0f}"
                    )
                else:
                    reason = "pressure at max_replicas"
            else:
                reason = "pressure inside up-cooldown"
        elif calm:
            self._calm_evals += 1
            if (
                self._calm_evals >= policy.idle_evals
                and now - self._last_down_at >= policy.down_cooldown_s
                # never shrink while budget was recently burning: the
                # up-cooldown doubles as a post-spike refractory period
                and now - self._last_up_at >= policy.up_cooldown_s
            ):
                target = max(policy.min_replicas, current - 1)
                if target < current:
                    reason = (
                        f"scale-down: calm x{self._calm_evals} "
                        f"(burn {signals['max_burn']:.2f}, "
                        f"queue {signals['mean_queue']:.1f})"
                    )
        else:
            # the hysteresis band between thresholds: hold position
            self._calm_evals = 0

        decision = AutoscaleDecision(
            current=current, target=target, reason=reason,
            draining=list(self._draining),
        )
        self.decisions.append(decision)
        self.target = target
        return decision

    # ------------------------------------------------------------------ #
    # actuation with drain
    # ------------------------------------------------------------------ #
    def step(
        self,
        router: FleetRouter,
        current: int,
        now: Optional[float] = None,
    ) -> AutoscaleDecision:
        """Evaluate against the router's live view and actuate:
        scale-up immediately; scale-down via drain-then-shrink."""
        now = time.monotonic() if now is None else now
        view = router.snapshot_states()
        if self.role is not None:
            # pool-scoped: this instance owns ONE role's StatefulSet —
            # the other pool's replicas are neither pressure nor
            # scale-down victims here
            view = [s for s in view if s.role == self.role]
        fresh = [
            s for s in view if s.fresh(now, router.heartbeat_timeout_s)
        ]
        decision = self.evaluate(fresh or view, current, now)

        if self._draining and self.last_eval_hot:
            # demand is back — even at max_replicas, where no actuated
            # scale-up will fire: letting the drain complete would
            # shrink a HOT fleet below max and flap straight back up
            for replica_id in self._draining:
                router.mark_draining(replica_id, False)
            self._draining = []
            decision.draining = []

        if decision.target > current:
            self._last_up_at = now
            self.events["up"] += 1
            if self._scale is not None:
                self._scale(decision.target)
            return decision

        if decision.target < current and not self._draining:
            # victim = highest ordinal (StatefulSets shrink from the
            # top); drain first, shrink when it reports idle
            victims = [s.replica_id for s in view if not s.draining]
            if victims:
                # length-then-lex = numeric order for `name-<ordinal>`
                # ids ("runner-10" drains before "runner-2" would)
                victim = sorted(victims, key=lambda r: (len(r), r))[-1]
                self._draining = [victim]
                router.mark_draining(victim, True)
                decision.draining = [victim]
                logger.info("fleet scale-down: draining %s", victim)

        if self._draining:
            drained = []
            for replica_id in self._draining:
                state = router.state_of(replica_id)
                # drained when idle — or gone: a victim that crashed
                # mid-drain stops heartbeating, and its frozen
                # last-gossiped queue depth must not wedge the drain
                # (and with it every future scale-down) forever
                if state is None or not state.fresh(
                    now, router.heartbeat_timeout_s
                ) or (
                    state.queue_depth <= 0 and state.active_sessions <= 0
                ):
                    drained.append(replica_id)
            if drained:
                self._last_down_at = now
                self._calm_evals = 0
                self.events["down"] += 1
                new_target = max(
                    self.policy.min_replicas, current - len(drained)
                )
                for replica_id in drained:
                    self._draining.remove(replica_id)
                    # do NOT forget the victim yet: the pod keeps
                    # heartbeating until kube actually terminates it,
                    # and a forgotten entry would re-register fresh and
                    # serving — routing new sessions onto a dying pod.
                    # The draining mark stays (observe never clears
                    # it); the reaper below removes the entry once its
                    # gossip goes stale, and a future re-grown ordinal
                    # re-enters via its new epoch.
                decision.target = new_target
                decision.reason = (
                    f"scale-down applied: drained {','.join(drained)}"
                )
                decision.draining = list(self._draining)
                self.target = new_target
                if self._scale is not None:
                    self._scale(new_target)
        # reap terminated victims: draining, no longer ours to watch,
        # and silent past the timeout = the pod is actually gone
        for state in router.snapshot_states():
            if (
                state.draining
                and state.replica_id not in self._draining
                and not state.fresh(now, router.heartbeat_timeout_s)
            ):
                router.forget(state.replica_id)
        return decision

    # ------------------------------------------------------------------ #
    # metrics
    # ------------------------------------------------------------------ #
    def gauges(self) -> Dict[str, float]:
        # role-scoped instances label their series so a disaggregated
        # fleet's two autoscalers merge into one scrape without
        # colliding; un-roled instances keep the PR 10 names exactly
        suffix = f'{{role="{self.role}"}}' if self.role else ""
        out = {
            f"fleet_replicas_draining{suffix}": float(len(self._draining)),
        }
        if self.target > 0:
            # absent until the first evaluation: a scrape must read
            # "no target yet" (top renders n/a), not a target of 0
            out[f"fleet_replicas_target{suffix}"] = float(self.target)
        for direction, count in sorted(self.events.items()):
            label = (
                f'{{direction="{direction}",role="{self.role}"}}'
                if self.role else f'{{direction="{direction}"}}'
            )
            out[f"fleet_autoscale_events_total{label}"] = float(count)
        return out
