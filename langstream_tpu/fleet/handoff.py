"""Paged-KV handoff over the topic fabric (prefill/decode disaggregation).

DeepServe/AIBrix-style disaggregation moves a session's prompt KV from
the prefill replica that computed it to the decode replica that will
stream the continuation. The seam is the same topic fabric the fleet
already gossips heartbeats over: a handoff is a short-lived stream of
``kv_handoff`` records keyed by ``handoff_id``, each carrying a bounded
slice of the session's block chain, so one fat handoff can never
head-of-line-block the topic behind it (records interleave with other
handoffs' chunks and with anything else sharing the fabric).

Wire schema (one record per chunk; every field JSON-able so the records
survive Kafka/Pulsar exactly like heartbeats do):

    {
      "kind":       "kv_handoff",
      "handoff_id": "h-9f3a…",        # one export = one id
      "chunk":      0,                # 0..chunks-1, any arrival order
      "chunks":     4,
      "block_start": 0,               # first chain block in this chunk
      "block_size": 16,
      "kv_quant":   false,            # int8 pools ship values + scales
      "tokens":     […],              # the chunk's blocks' token ids
      "arrays":     {leaf: {"dtype", "shape", "data": b64}},
      "manifest":   {…}               # chunk 0 only: the warm-admission
                                      #   envelope (prompt, sampled
                                      #   tokens, sampling params, seed)
    }

``arrays`` holds the per-layer pool rows for this chunk's blocks —
``[layers, blocks, block_size, kv_heads, head_dim]`` per value leaf
(bf16 shipped as float32 bytes; int8 pools additionally ship their f32
scale leaves). The simulated fleet omits ``arrays`` (its pools are
accounting-only) and carries ``sim_bytes`` instead, so one schema and
one assembler serve both the CPU sim and a real engine pair.

:class:`HandoffAssembler` reassembles chunks on the decode side and
GC's orphans: a prefill replica dying mid-handoff leaves an incomplete
chunk set that would otherwise pin memory forever — after
``orphan_timeout_s`` without progress the partial handoff is dropped
(counted, never raised), and the session simply re-routes as a cold
prefill. The importer's block-level unwind lives with the pool
accounting (:meth:`PagedKVManager.abort_import`): nothing is ever
published from a torn handoff before its block ids recycle.
"""

from __future__ import annotations

import base64
import dataclasses
import threading
import uuid
from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

# the handoff stream shares the heartbeat fabric, not the heartbeat
# topic: a fat KV transfer must never delay the gossip the router's
# liveness view depends on
HANDOFF_TOPIC = "fleet-kv-handoff"
RECORD_KIND = "kv_handoff"

# bounded chunk size: one chunk's array payload never exceeds this, so
# a single handoff record cannot head-of-line-block the topic (Kafka's
# default max.message.bytes is 1 MiB; stay comfortably under it)
DEFAULT_MAX_CHUNK_BYTES = 256 * 1024


def _encode_array(array: np.ndarray) -> Dict[str, Any]:
    array = np.ascontiguousarray(array)
    return {
        "dtype": str(array.dtype),
        "shape": list(array.shape),
        "data": base64.b64encode(array.tobytes()).decode("ascii"),
    }


def _decode_array(spec: Mapping[str, Any]) -> np.ndarray:
    raw = base64.b64decode(spec["data"])
    return np.frombuffer(raw, dtype=np.dtype(spec["dtype"])).reshape(
        spec["shape"]
    ).copy()


def payload_nbytes(payload: Mapping[str, Any]) -> int:
    """Device bytes behind an engine export payload (the accounting the
    ``kv_handoff_*_bytes_total`` gauges bill — pre-base64 array bytes,
    i.e. what actually crossed HBM/host, not wire framing)."""
    arrays = payload.get("arrays") or {}
    return int(sum(np.asarray(a).nbytes for a in arrays.values()))


def new_handoff_id() -> str:
    return f"h-{uuid.uuid4().hex[:16]}"


def handoff_records(
    payload: Mapping[str, Any],
    manifest: Mapping[str, Any],
    *,
    handoff_id: Optional[str] = None,
    max_chunk_bytes: int = DEFAULT_MAX_CHUNK_BYTES,
) -> List[Dict[str, Any]]:
    """Split one engine export payload (``DecodeEngine`` handoff shape:
    ``tokens`` + per-leaf ``arrays`` + ``block_size``/``kv_quant``) into
    bounded ``kv_handoff`` records. ``manifest`` rides chunk 0 — the
    warm-admission envelope the decode side replays from."""
    block_size = int(payload["block_size"])
    tokens = list(payload["tokens"])
    arrays: Dict[str, np.ndarray] = {
        leaf: np.asarray(array)
        for leaf, array in (payload.get("arrays") or {}).items()
    }
    n_blocks = len(tokens) // block_size
    if arrays:
        per_block = sum(
            a.nbytes // max(1, a.shape[1]) for a in arrays.values()
        )
        blocks_per_chunk = max(1, max_chunk_bytes // max(1, per_block))
    else:
        # sim payloads: no arrays; chunk on a nominal per-block budget
        per_block = int(payload.get("sim_block_bytes", 0) or 0)
        blocks_per_chunk = (
            max(1, max_chunk_bytes // per_block) if per_block else n_blocks
        ) or 1
    chunks = max(1, -(-n_blocks // blocks_per_chunk))
    handoff_id = handoff_id or new_handoff_id()
    records: List[Dict[str, Any]] = []
    for index in range(chunks):
        start = index * blocks_per_chunk
        stop = min(n_blocks, start + blocks_per_chunk)
        record: Dict[str, Any] = {
            "kind": RECORD_KIND,
            "handoff_id": handoff_id,
            "chunk": index,
            "chunks": chunks,
            "block_start": start,
            "block_size": block_size,
            "kv_quant": bool(payload.get("kv_quant", False)),
            "tokens": tokens[start * block_size: stop * block_size],
        }
        if arrays:
            record["arrays"] = {
                leaf: _encode_array(array[:, start:stop])
                for leaf, array in arrays.items()
            }
        elif per_block:
            record["sim_bytes"] = per_block * (stop - start)
        if index == 0:
            record["manifest"] = dict(manifest)
        records.append(record)
    return records


@dataclasses.dataclass
class _Pending:
    chunks: int
    received: Dict[int, Mapping[str, Any]]
    last_progress: float
    nbytes: int = 0


class HandoffAssembler:
    """Decode-side chunk reassembly with orphan GC.

    Thread-safe: the fabric consumer task offers records while a serve
    path (or the sim loop) drives :meth:`gc` — every read and write of
    the pending table holds the lock. Assembly is pure dict/array
    splicing; nothing here touches a KV pool (the engine imports the
    assembled payload on its own thread at admission)."""

    def __init__(self, *, orphan_timeout_s: float = 30.0) -> None:
        self.orphan_timeout_s = float(orphan_timeout_s)
        self._pending: Dict[str, _Pending] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.stats: Dict[str, int] = {  # guarded-by: _lock
            "handoffs_assembled": 0,
            "handoffs_orphaned": 0,
            "chunks_received": 0,
            "bytes_received": 0,
        }

    def offer(
        self, value: Mapping[str, Any], now: float
    ) -> Optional[Dict[str, Any]]:
        """Apply one fabric record; returns the assembled handoff
        (``{"manifest": …, "payload": …}``) when its final chunk lands,
        else None. Malformed records are dropped — a bad gossip record
        must never take the consumer loop down."""
        if not isinstance(value, Mapping) or value.get("kind") != RECORD_KIND:
            return None
        handoff_id = value.get("handoff_id")
        chunk = value.get("chunk")
        chunks = value.get("chunks")
        if not isinstance(handoff_id, str) or not isinstance(chunk, int) \
                or not isinstance(chunks, int) or not 0 <= chunk < chunks:
            return None
        with self._lock:
            pending = self._pending.get(handoff_id)
            if pending is None:
                pending = _Pending(chunks=chunks, received={},
                                   last_progress=now)
                self._pending[handoff_id] = pending
            if pending.chunks != chunks:
                return None  # torn: mismatched chunk counts
            duplicate = chunk in pending.received
            pending.received[chunk] = value
            pending.last_progress = now
            if not duplicate:
                # an at-least-once fabric redelivers: the replacement
                # is fine (same content), but its bytes must not count
                # twice — handoff_bytes is the transfer-price evidence
                # the disagg A/B reads
                self.stats["chunks_received"] += 1
                nbytes = value.get("sim_bytes")
                if not isinstance(nbytes, int):
                    nbytes = sum(
                        len(spec.get("data", "")) * 3 // 4
                        for spec in (value.get("arrays") or {}).values()
                        if isinstance(spec, Mapping)
                    )
                pending.nbytes += int(nbytes)
                self.stats["bytes_received"] += int(nbytes)
            if len(pending.received) < pending.chunks:
                return None
            self._pending.pop(handoff_id)
        try:
            assembled = self._assemble(handoff_id, pending)
        except Exception:  # noqa: BLE001 — a torn/mixed-schema chunk
            # set (leaf missing from a later chunk, shape mismatch,
            # bad b64) must drop like any malformed record, never take
            # the fabric consumer loop down; the session re-routes
            # cold via the caller's timeout path
            with self._lock:
                self.stats["handoffs_orphaned"] += 1
            return None
        with self._lock:
            self.stats["handoffs_assembled"] += 1
        return assembled

    @staticmethod
    def _assemble(
        handoff_id: str, pending: _Pending
    ) -> Dict[str, Any]:
        ordered = [pending.received[i] for i in range(pending.chunks)]
        first = ordered[0]
        tokens: List[int] = []
        for record in ordered:
            tokens.extend(int(t) for t in record.get("tokens", ()))
        payload: Dict[str, Any] = {
            "tokens": tokens,
            "block_size": int(first.get("block_size", 0) or 0),
            "kv_quant": bool(first.get("kv_quant", False)),
            "nbytes": pending.nbytes,
        }
        if first.get("arrays"):
            payload["arrays"] = {
                leaf: np.concatenate(
                    [_decode_array(rec["arrays"][leaf]) for rec in ordered],
                    axis=1,
                )
                for leaf in first["arrays"]
            }
        return {
            "handoff_id": handoff_id,
            "manifest": dict(first.get("manifest") or {}),
            "payload": payload,
        }

    def gc(self, now: float) -> List[str]:
        """Drop incomplete handoffs with no progress inside the orphan
        timeout — the mid-handoff-crash path: the chunks are garbage
        the moment their prefill replica dies, and the session they
        belonged to re-routes as a cold prefill elsewhere."""
        with self._lock:
            orphans = [
                handoff_id
                for handoff_id, pending in self._pending.items()
                if now - pending.last_progress >= self.orphan_timeout_s
            ]
            for handoff_id in orphans:
                self._pending.pop(handoff_id)
                self.stats["handoffs_orphaned"] += 1
        return orphans

    def pending_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._pending)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return {
                "fleet_handoffs_assembled_total": float(
                    self.stats["handoffs_assembled"]
                ),
                "fleet_handoffs_orphaned_total": float(
                    self.stats["handoffs_orphaned"]
                ),
                "fleet_handoff_bytes_total": float(
                    self.stats["bytes_received"]
                ),
                "fleet_handoffs_pending": float(len(self._pending)),
            }


def manifest_for_request(
    prompt_tokens: Sequence[int],
    generated: Sequence[int],
    sampling: Mapping[str, Any],
    *,
    session_id: Optional[str] = None,
    trace_id: Optional[str] = None,
    replica: Optional[str] = None,
    export_ts: Optional[float] = None,
) -> Dict[str, Any]:
    """The warm-admission envelope: everything the decode side needs to
    rebuild the PR 9 replay request — original prompt, every token the
    prefill leg sampled (the last one is teacher-forced, its KV row
    written by the first decode step), and the sampling params WITH the
    effective seed (an unseeded stochastic session must continue the
    prefill replica's stream, so the auto-seed crosses in the manifest;
    sampling keys derive from ``(seed, position)`` and positions are
    absolute, so the continuation is bitwise wherever it lands).

    ``export_ts`` (wall seconds; stamped now when omitted) rides the
    chunk-0 manifest so the decode side can compute the journey
    ledger's ``handoff_transit`` stage — import-side clock minus the
    export stamp — without any side channel (ISSUE 20)."""
    import time as _time

    return {
        "prompt_tokens": [int(t) for t in prompt_tokens],
        "generated": [int(t) for t in generated],
        "sampling": dict(sampling),
        "session_id": session_id,
        "trace_id": trace_id,
        "replica": replica,
        "export_ts": (
            float(export_ts) if export_ts is not None else _time.time()
        ),
    }
