"""Fleet heartbeat protocol: runners gossip, the router listens.

One heartbeat is one JSON-able dict published to a shared topic
(default ``fleet-heartbeats``) on the existing topic fabric — memory
broker in tests/local runs, Kafka/Pulsar in clusters; nothing here
knows the difference (both ends speak the
``TopicProducer``/``TopicReader`` SPI).

Schema (all fields optional except ``replica``; unknown fields are
ignored so the schema can grow without a fleet-wide flag day):

    {
      "replica":         "runner-0",      # stable pod identity
      "seq":             42,              # per-replica monotonic counter
      "epoch":           "9f3a…",         # per-PROCESS identity: a new
                                          #   epoch = a restarted pod
                                          #   (fresh seq counter)
      "state":           "serving",       # serving|degraded|rebuilding|down
      "role":            "unified",       # prefill|decode|unified —
                                          #   disaggregation pool this
                                          #   replica serves (absent =
                                          #   unified, the pre-disagg
                                          #   behavior)
      "queue_depth":     3,               # admission queue + pending
      "active_sessions": 5,               # sessions holding slots
      "block_size":      16,              # paged block size (0 = dense)
      "chain_digests":   ["ab12…", …],    # resident prefix chains
                                          #   (router.digests_from_keys)
      "host_chain_digests": ["cd34…", …], # chains demoted to the
                                          #   host-DRAM tier, promotable
                                          #   without recompute (absent =
                                          #   un-tiered pool)
      "gauges":          {…}              # engines_snapshot subset:
                                          #   SLO burn rates, sheds,
                                          #   prefix hit tokens
    }

The router drops out-of-order ``seq`` (a delayed heartbeat must never
resurrect a condemned replica) and times out replicas that stop
gossiping — so a crashed runner falls out of rotation within one
``heartbeat_timeout_s`` even if nothing condemns it explicitly.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, Dict, Mapping, Optional

logger = logging.getLogger(__name__)

HEARTBEAT_TOPIC = "fleet-heartbeats"

# process identity stamped on every heartbeat: the router tells a pod
# RESTART (new epoch, fresh seq counter — accept immediately) from an
# at-least-once transport REPLAYING a dead process's records (old
# epoch — drop), which bare seq numbers cannot distinguish
PROCESS_EPOCH = uuid.uuid4().hex

# gauges worth gossiping: the autoscaler's pressure signals plus the
# affinity instrument, NOT the whole snapshot (heartbeats are frequent)
_GOSSIP_GAUGES = (
    "jax_engine_slo_ttft_burn_rate_5m",
    "jax_engine_slo_ttft_burn_rate_1h",
    "jax_engine_slo_tpot_burn_rate_5m",
    "jax_engine_slo_tpot_burn_rate_1h",
    "jax_engine_queue_depth",
    'requests_shed_total{reason="queue_timeout"}',
    "prefix_cache_hit_tokens_total",
)


def build_heartbeat(
    replica_id: str,
    seq: int,
    *,
    engine: Optional[Any] = None,
    supervisor: Optional[Any] = None,
    snapshot: Optional[Mapping[str, float]] = None,
    digest_limit: int = 4096,
    role: str = "unified",
) -> Dict[str, Any]:
    """Assemble a heartbeat from a live engine (+ optional supervisor).

    ``engine`` is a ``DecodeEngine`` (or anything exposing
    ``queue_depth``/``kv_manager``/``block_size``/``slots``);
    ``supervisor`` contributes the degraded/rebuilding state the router
    treats as a drain signal. ``snapshot`` overrides the gauge source
    (defaults to ``engines_snapshot()`` of the live process).
    """
    heartbeat: Dict[str, Any] = {
        "replica": replica_id, "seq": int(seq), "epoch": PROCESS_EPOCH,
        "role": str(role or "unified"),
    }
    state = "serving"
    if supervisor is not None:
        state = {
            "serving": "serving",
            "rebuilding": "rebuilding",
            "failed": "degraded",
            "stopped": "down",
        }.get(getattr(supervisor, "state", "serving"), "serving")
    heartbeat["state"] = state
    if engine is not None:
        heartbeat["queue_depth"] = int(getattr(engine, "queue_depth", 0))
        slots = getattr(engine, "slots", None)
        if slots is not None:
            heartbeat["active_sessions"] = sum(
                1 for s in slots if getattr(s, "active", False)
            )
        manager = getattr(engine, "kv_manager", None)
        if manager is not None:
            from langstream_tpu.fleet.router import digests_from_keys

            heartbeat["block_size"] = int(manager.block_size)
            # PagedKVManager is engine-thread-owned (documented not
            # thread-safe), and this builder usually runs on the
            # gossip task: retry the snapshot+digest a few times if a
            # concurrent publish/evict resizes a dict mid-iteration,
            # and on a persistently hot pool OMIT the field — observe()
            # keeps the router's previous digest set when absent, so a
            # busy beat degrades to slightly stale affinity, never a
            # crashed gossip loop (stale digests cost a cache miss at
            # worst). The memo's chain-key validation makes any racy
            # write-back value-safe.
            for _ in range(4):
                try:
                    heartbeat["chain_digests"] = sorted(
                        digests_from_keys(
                            manager.published_keys(limit=digest_limit),
                            memo=getattr(manager, "digest_memo", None),
                        )
                    )
                    break
                except RuntimeError:  # dict resized under iteration
                    continue
            arena = getattr(manager, "host", None)
            if arena is not None:
                # the arena is thread-safe (own lock), so no retry
                # loop; digests() is a point-in-time snapshot set
                heartbeat["host_chain_digests"] = sorted(arena.digests())
        else:
            heartbeat["block_size"] = 0
    if snapshot is None and engine is not None:
        from langstream_tpu.providers.jax_local.engine import engines_snapshot

        snapshot = engines_snapshot()
    if snapshot:
        heartbeat["gauges"] = {
            key: float(snapshot[key]) for key in _GOSSIP_GAUGES
            if key in snapshot
        }
    return heartbeat


async def publish_loop(
    producer: Any,
    beat: Any,
    *,
    interval_s: float = 2.0,
    stop: Optional[asyncio.Event] = None,
) -> None:
    """Gossip pump: call ``beat()`` (a zero-arg heartbeat builder, e.g.
    a ``build_heartbeat`` closure with its own seq counter) and publish
    the dict every ``interval_s``. A failed publish is logged and
    retried next beat — heartbeating must never kill a runner."""
    from langstream_tpu.api.records import Record

    stop = stop or asyncio.Event()
    while not stop.is_set():
        try:
            heartbeat = beat()
            await producer.write(
                Record(value=heartbeat, key=heartbeat.get("replica"))
            )
        except Exception:  # noqa: BLE001 — gossip is best-effort
            logger.exception("fleet heartbeat publish failed")
        try:
            await asyncio.wait_for(stop.wait(), timeout=interval_s)
        except asyncio.TimeoutError:
            pass


async def consume_loop(
    reader: Any,
    router: Any,
    *,
    stop: Optional[asyncio.Event] = None,
    poll_timeout_s: float = 0.2,
) -> None:
    """Router-side pump: tail the heartbeat topic and feed
    ``router.observe``. Records whose value is not a dict are skipped
    (``observe`` additionally rejects malformed dicts)."""
    stop = stop or asyncio.Event()
    while not stop.is_set():
        batch = await reader.read(timeout=poll_timeout_s)
        for record in batch:
            value = record.value
            if isinstance(value, Mapping):
                router.observe(value)
