"""Simulated fleet: M fake engines with REAL paged prefix caches.

The fleet layer's acceptance instrument (``tests/test_fleet.py``): the
router, heartbeat protocol, and autoscaler are the production classes;
only the engine is fake — a :class:`SimReplica` replaces the device
with a step-counting slot model but keeps a real
:class:`~langstream_tpu.providers.jax_local.paged.PagedKVManager`, so
prefix matching, block-granular admission, publish-at-admission/finish,
refcounts, and LRU eviction behave exactly like a runner pod's pool.
Heartbeats flow through a real in-process memory topic
(``topics/memory.py``) and scaling actuates a real
``Operator.scale`` against a ``MockKubeApi`` StatefulSet, so the whole
loop — gossip → routing → pressure → patch → reconcile — runs on CPU
with no JAX and no cluster.

Time is simulated (``fleet.now`` advances ``step_time`` per tick), so
SLO windows, heartbeat timeouts, and autoscaler cooldowns run in
microseconds of wall clock.

Cost model (deliberately minimal): admission occupies a slot for
``ceil(missed_prefill_tokens / prefill_rate)`` steps — a prefix hit
skips prefill work, which is WHY affinity routing lifts throughput and
cuts TTFT/sheds, not just a counter. Decode is one token per step per
slot. Generated tokens are a pure function of (prompt, index) so a
session killed mid-stream and re-routed continues its exact stream on
any replica — the fleet-level analogue of PR 9's bitwise resurrection.

``python -m langstream_tpu.fleet.sim`` runs the routed-vs-round-robin
A/B on identical traffic and writes ``bench_fleet_routed.json`` /
``bench_fleet_rr.json`` artifacts for ``tools/ab_analyze.py``.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import math
import os
import random
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from langstream_tpu.api.records import Record
from langstream_tpu.deployer.kube import MockKubeApi
from langstream_tpu.deployer.operator import Operator
from langstream_tpu.fleet.autoscaler import AutoscalePolicy, SLOAutoscaler
from langstream_tpu.fleet.heartbeat import HEARTBEAT_TOPIC
from langstream_tpu.fleet.router import (
    FleetRouter,
    NoRoutableReplica,
    digests_from_keys,
)
from langstream_tpu.providers.jax_local.paged import PagedKVManager
from langstream_tpu.topics.memory import (
    MemoryBroker,
    MemoryTopicProducer,
    MemoryTopicReader,
)
from langstream_tpu.api.topics import OffsetPosition


class ReplicaDown(Exception):
    """Submit raced a crash: the fleet re-routes, the client never sees it."""


def generated_token(prompt: Sequence[int], index: int) -> int:
    """Deterministic continuation token ``index`` for ``prompt`` —
    replica-independent, so a re-routed session's stream is bitwise
    identical to the unkilled oracle."""
    seed = 0
    for t in prompt:
        seed = (seed * 1000003 + int(t)) & 0xFFFFFFFF
    return 2 + (seed * 31 + index * 7919) % 29989


class SimSession:
    """One client stream. ``tokens`` is what the client saw — append
    only, each token exactly once; ``errors`` is what a real client
    would surface as a 500 (503-with-retry paths stay internal)."""

    _ids = 0

    def __init__(self, prompt: Sequence[int], max_new_tokens: int = 8) -> None:
        SimSession._ids += 1
        self.id = f"sess-{SimSession._ids}"
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.tokens: List[int] = []
        self.errors: List[str] = []
        self.done = False
        self.reroutes = 0
        self.replica: Optional[str] = None
        self.submitted_at: Optional[float] = None  # fleet submit (sim s)
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    def admission_tokens(self) -> List[int]:
        """What a (re)admission prefills: original prompt plus every
        token already delivered (PR 9 replay shape)."""
        return self.prompt + self.tokens

    def expected_tokens(self) -> List[int]:
        return [
            generated_token(self.prompt, i)
            for i in range(self.max_new_tokens)
        ]


class _Slot:
    __slots__ = ("session", "table", "prefill_remaining", "adm_tokens")

    def __init__(self, session, table, prefill_steps, adm_tokens) -> None:
        self.session = session
        self.table = table
        self.prefill_remaining = prefill_steps
        self.adm_tokens = adm_tokens


class SimReplica:
    """Fake engine, real pool. The step model: admission pops the
    queue into free slots (worst-case block reservation — allocation
    failure is backpressure, exactly like ``_admit_paged``), prefill
    holds the slot ``ceil(miss/prefill_rate)`` steps, decode emits one
    token per step, finish publishes the full-block chain and releases
    the table."""

    def __init__(
        self,
        name: str,
        *,
        num_blocks: int = 256,
        block_size: int = 8,
        slots: int = 4,
        prefill_rate: int = 64,
        queue_timeout_s: Optional[float] = None,
        ttft_target_s: float = 2.0,
        digest_limit: int = 4096,
    ) -> None:
        self.name = name
        self.block_size = block_size
        self.num_slots = slots
        self.prefill_rate = prefill_rate
        self.queue_timeout_s = queue_timeout_s
        self.ttft_target_s = ttft_target_s
        self.digest_limit = digest_limit
        self.kv = PagedKVManager(num_blocks, block_size)
        self.queue: Deque[Tuple[SimSession, float]] = deque()
        self.active: List[_Slot] = []
        self.state = "serving"
        self.seq = 0
        self.boot = 0  # bumped per rebuild: the heartbeat epoch
        self.shed_total = 0
        self._ttft_samples: Deque[Tuple[float, float]] = deque()

    # -------------------------------------------------------------- #
    # serving
    # -------------------------------------------------------------- #
    def submit(self, session: SimSession, now: float) -> None:
        if self.state != "serving":
            raise ReplicaDown(f"{self.name} is {self.state}")
        session.replica = self.name
        if session.submitted_at is None:
            session.submitted_at = now
        self.queue.append((session, now))

    def _admit(self, now: float) -> None:
        while self.queue and len(self.active) < self.num_slots:
            session, queued_at = self.queue[0]
            adm = session.admission_tokens()
            chain, matched = self.kv.match(adm)
            need = max(
                0,
                math.ceil(
                    (len(adm) + session.remaining) / self.block_size
                ) - len(chain),
            )
            fresh = self.kv.allocate(need)
            if fresh is None:
                return  # pool backpressure: admission waits
            self.queue.popleft()
            self.kv.ref(chain)
            self.kv.stats["hit_tokens"] += matched
            table = chain + fresh
            # publish-cold-at-admission: concurrent same-prefix
            # sessions hit these blocks before this one finishes
            self.kv.publish(adm, table)
            prefill_steps = math.ceil(
                max(0, len(adm) - matched) / self.prefill_rate
            )
            self.active.append(_Slot(session, table, prefill_steps, adm))

    def _shed_expired(self, now: float) -> List[SimSession]:
        if not self.queue_timeout_s:
            return []
        shed: List[SimSession] = []
        keep: Deque[Tuple[SimSession, float]] = deque()
        for session, queued_at in self.queue:
            if now - queued_at >= self.queue_timeout_s:
                self.shed_total += 1
                shed.append(session)
            else:
                keep.append((session, queued_at))
        self.queue = keep
        return shed

    def step(self, now: float) -> Dict[str, List[SimSession]]:
        """One engine step: shed expired, admit, prefill/decode.
        Returns sessions that finished and sessions shed at the
        admission deadline (the fleet re-routes sheds — a 503 with
        Retry-After, never a client 500)."""
        if self.state != "serving":
            return {"finished": [], "shed": []}
        shed = self._shed_expired(now)
        self._admit(now)
        finished: List[SimSession] = []
        for slot in list(self.active):
            if slot.prefill_remaining > 0:
                slot.prefill_remaining -= 1
                continue
            session = slot.session
            session.tokens.append(
                generated_token(session.prompt, len(session.tokens))
            )
            if session.first_token_at is None:
                session.first_token_at = now
                assert session.submitted_at is not None
                self._ttft_samples.append(
                    (now, now - session.submitted_at)
                )
                while (
                    self._ttft_samples
                    and now - self._ttft_samples[0][0] > 3600.0
                ):
                    self._ttft_samples.popleft()
            if session.remaining <= 0:
                session.done = True
                session.finished_at = now
                full = session.prompt + session.tokens
                self.kv.publish(full, slot.table)
                self.kv.release(slot.table)
                self.active.remove(slot)
                finished.append(session)
        return {"finished": finished, "shed": shed}

    # -------------------------------------------------------------- #
    # failure / recovery (the PR 9 arc at fleet granularity)
    # -------------------------------------------------------------- #
    def kill(self) -> List[SimSession]:
        """Crash: every queued and active session is handed back for
        fleet-level resurrection (prompt + delivered tokens); the pool
        dies with the process."""
        self.state = "down"
        orphans = [s for s, _ in self.queue] + [
            slot.session for slot in self.active
        ]
        self.queue.clear()
        self.active.clear()
        return orphans

    def rebuild(self) -> None:
        """Supervisor finished: fresh pool (prefix cache lost), same
        identity, heartbeat seq continues so the router's condemnation
        clears on the next serving gossip."""
        self.kv = PagedKVManager(self.kv.num_blocks, self.block_size)
        self.state = "serving"
        self.boot += 1  # new process: new heartbeat epoch

    # -------------------------------------------------------------- #
    # gossip
    # -------------------------------------------------------------- #
    def _burn_rates(self, now: float) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for label, window in (("5m", 300.0), ("1h", 3600.0)):
            samples = [
                ttft for ts, ttft in self._ttft_samples
                if now - ts <= window
            ]
            if not samples:
                continue
            violations = sum(
                1 for ttft in samples if ttft > self.ttft_target_s
            )
            out[f"jax_engine_slo_ttft_burn_rate_{label}"] = round(
                (violations / len(samples)) / 0.05, 4
            )
        return out

    def heartbeat(self, now: float) -> Dict[str, Any]:
        self.seq += 1
        gauges = self._burn_rates(now)
        gauges['requests_shed_total{reason="queue_timeout"}'] = float(
            self.shed_total
        )
        gauges["prefix_cache_hit_tokens_total"] = float(
            self.kv.stats["hit_tokens"]
        )
        return {
            "replica": self.name,
            "seq": self.seq,
            "epoch": f"{self.name}/boot-{self.boot}",
            "state": self.state,
            "queue_depth": len(self.queue),
            "active_sessions": len(self.active),
            "block_size": self.block_size,
            "chain_digests": sorted(
                digests_from_keys(
                    self.kv.published_keys(limit=self.digest_limit),
                    memo=self.kv.digest_memo,
                )
            ),
            "gauges": gauges,
        }


class SimFleet:
    """M :class:`SimReplica`s behind a memory-topic heartbeat fabric,
    the production router, and (optionally) the production autoscaler
    actuating a MockKubeApi StatefulSet."""

    def __init__(
        self,
        replicas: int = 3,
        *,
        policy: str = "affinity",
        step_time: float = 0.25,
        heartbeat_interval_s: float = 1.0,
        heartbeat_timeout_s: float = 5.0,
        autoscale: Optional[AutoscalePolicy] = None,
        autoscale_interval_s: float = 5.0,
        namespace: str = "fleet",
        statefulset: str = "runner",
        unrouted_patience_ticks: int = 200,
        **replica_kwargs: Any,
    ) -> None:
        self.now = 0.0
        self.step_time = step_time
        self.policy = policy
        self.heartbeat_interval_s = heartbeat_interval_s
        self._next_heartbeat = 0.0
        self.replica_kwargs = replica_kwargs
        self.router = FleetRouter(
            policy=policy, heartbeat_timeout_s=heartbeat_timeout_s
        )
        self.broker = MemoryBroker()
        self._producer = MemoryTopicProducer(self.broker, HEARTBEAT_TOPIC)
        self._reader = MemoryTopicReader(
            self.broker, HEARTBEAT_TOPIC, OffsetPosition.EARLIEST
        )
        self.replicas: Dict[str, SimReplica] = {}
        self.namespace, self.statefulset = namespace, statefulset
        self.kube = MockKubeApi()
        self.operator = Operator(self.kube)
        self.kube.apply({
            "kind": "StatefulSet",
            "metadata": {"name": statefulset, "namespace": namespace},
            "spec": {"replicas": replicas},
        })
        self.autoscaler: Optional[SLOAutoscaler] = None
        self.autoscale_interval_s = autoscale_interval_s
        self._next_autoscale = 0.0
        if autoscale is not None:
            self.autoscaler = SLOAutoscaler(
                autoscale,
                scale=lambda n: self.operator.scale(
                    namespace, statefulset, n
                ),
            )
        for ordinal in range(replicas):
            self._spawn(ordinal)
        # fleet books
        self.sessions: List[SimSession] = []
        self._unrouted: Deque[SimSession] = deque()
        # retry budget for a session no replica will take: past it the
        # client REALLY sees the failure (this is what keeps the
        # zero-client-errors assertions falsifiable — a fleet that
        # cannot place a session does produce an error)
        self.unrouted_patience_ticks = int(unrouted_patience_ticks)
        self.reroutes = 0
        self.fleet_sheds = 0
        self.retired_hit_tokens = 0  # killed replicas' counters survive

    # -------------------------------------------------------------- #
    # replica lifecycle
    # -------------------------------------------------------------- #
    def _spawn(self, ordinal: int) -> SimReplica:
        name = f"{self.statefulset}-{ordinal}"
        replica = SimReplica(name, **self.replica_kwargs)
        self.replicas[name] = replica
        return replica

    def kill(self, name: str) -> None:
        """Crash one runner mid-stream: condemn it in the router (the
        gateway's 503 signal) and resurrect its sessions elsewhere."""
        replica = self.replicas[name]
        self.retired_hit_tokens += replica.kv.stats["hit_tokens"]
        orphans = replica.kill()
        self.router.mark_unroutable(name, reason="crashed")
        for session in orphans:
            session.reroutes += 1
            self.reroutes += 1
            self._route_submit(session)

    def revive(self, name: str) -> None:
        self.replicas[name].rebuild()

    # -------------------------------------------------------------- #
    # traffic
    # -------------------------------------------------------------- #
    def submit(
        self, prompt: Sequence[int], max_new_tokens: int = 8
    ) -> SimSession:
        session = SimSession(prompt, max_new_tokens)
        session.submitted_at = self.now
        self.sessions.append(session)
        self._route_submit(session)
        return session

    def _route_submit(self, session: SimSession) -> None:
        """Route (or re-route) a session; a submit that races a crash
        condemns the replica and retries — only a fleet with zero
        routable replicas parks the session for the next tick (the
        client's 503-with-Retry-After, not a 500)."""
        for _ in range(len(self.replicas) + 1):
            try:
                decision = self.router.route(
                    session.admission_tokens(), now=self.now
                )
            except NoRoutableReplica:
                break
            replica = self.replicas.get(decision.replica_id)
            if replica is None:
                self.router.forget(decision.replica_id)
                continue
            try:
                replica.submit(session, self.now)
                session._unrouted_ticks = 0
                return
            except ReplicaDown:
                self.router.mark_unroutable(
                    decision.replica_id, reason="connection refused"
                )
        self._unrouted.append(session)

    # -------------------------------------------------------------- #
    # the loop
    # -------------------------------------------------------------- #
    async def _pump_heartbeats(self) -> None:
        for replica in self.replicas.values():
            if replica.state != "down":
                heartbeat = replica.heartbeat(self.now)
                await self._producer.write(
                    Record(value=heartbeat, key=replica.name)
                )
        for record in await self._reader.read(
            max_records=10_000, timeout=0.0
        ):
            if isinstance(record.value, dict):
                self.router.observe(record.value, now=self.now)

    def _reconcile_replicas(self) -> None:
        """StatefulSet semantics: ordinals ``0..replicas-1`` exist.
        Scale-up spawns the missing ordinals; scale-down removes
        ordinals past ``desired`` once drained (a down-but-in-range
        replica is the supervisor's problem, not the reconciler's)."""
        sts = self.kube.get(
            "StatefulSet", self.namespace, self.statefulset
        )
        desired = int(sts["spec"]["replicas"]) if sts else len(self.replicas)
        for ordinal in range(desired):
            if f"{self.statefulset}-{ordinal}" not in self.replicas:
                self._spawn(ordinal)
        for name in sorted(
            self.replicas, key=lambda n: int(n.rsplit("-", 1)[1])
        )[desired:]:
            replica = self.replicas[name]
            if not replica.queue and not replica.active:
                self.retired_hit_tokens += replica.kv.stats["hit_tokens"]
                self.replicas.pop(name)
                self.router.forget(name)

    async def tick(self) -> None:
        self.now += self.step_time
        retry, self._unrouted = self._unrouted, deque()
        for session in retry:
            waited = getattr(session, "_unrouted_ticks", 0) + 1
            session._unrouted_ticks = waited
            if waited > self.unrouted_patience_ticks:
                # retries exhausted: the client's 503s harden into a
                # real failure (counted by client_errors())
                session.errors.append(
                    f"503: no routable replica after {waited} retries"
                )
                continue
            self._route_submit(session)
        for replica in list(self.replicas.values()):
            result = replica.step(self.now)
            for session in result["shed"]:
                self.fleet_sheds += 1
                session.reroutes += 1
                self._route_submit(session)
        if self.now >= self._next_heartbeat:
            self._next_heartbeat = self.now + self.heartbeat_interval_s
            await self._pump_heartbeats()
        if self.autoscaler is not None and self.now >= self._next_autoscale:
            self._next_autoscale = self.now + self.autoscale_interval_s
            sts = self.kube.get(
                "StatefulSet", self.namespace, self.statefulset
            )
            current = int(sts["spec"]["replicas"])
            self.autoscaler.step(self.router, current, now=self.now)
            self._reconcile_replicas()

    async def run(self, ticks: int) -> None:
        for _ in range(ticks):
            await self.tick()

    async def run_until_idle(self, max_ticks: int = 10_000) -> None:
        for _ in range(max_ticks):
            await self.tick()
            if self._unrouted:
                continue
            if all(
                not r.queue and not r.active
                for r in self.replicas.values()
            ) and all(s.done or s.errors for s in self.sessions):
                return
        raise TimeoutError(
            f"fleet not idle after {max_ticks} ticks "
            f"(unrouted={len(self._unrouted)})"
        )

    # -------------------------------------------------------------- #
    # books
    # -------------------------------------------------------------- #
    def fleet_hit_tokens(self) -> int:
        return self.retired_hit_tokens + sum(
            r.kv.stats["hit_tokens"] for r in self.replicas.values()
        )

    def fleet_shed_total(self) -> int:
        return self.fleet_sheds

    def client_errors(self) -> int:
        return sum(len(s.errors) for s in self.sessions)

    def gauges(self) -> Dict[str, float]:
        out = self.router.gauges(now=self.now)
        out["fleet_replicas_current"] = float(
            sum(1 for r in self.replicas.values() if r.state != "down")
        )
        if self.autoscaler is not None:
            out.update(self.autoscaler.gauges())
        return out


# ------------------------------------------------------------------ #
# shared-prefix traffic + the routed-vs-round-robin A/B artifact
# ------------------------------------------------------------------ #
@dataclasses.dataclass
class TrafficSpec:
    groups: int = 4
    sessions_per_group: int = 16
    prefix_blocks: int = 4       # shared prefix length, in blocks
    suffix_tokens: int = 8       # unique per-session tail
    max_new_tokens: int = 8
    wave_size: int = 8           # sessions submitted per wave
    ticks_between_waves: int = 4
    seed: int = 1234


def make_prompts(
    spec: TrafficSpec, block_size: int
) -> List[List[int]]:
    rng = random.Random(spec.seed)
    prefixes = [
        [rng.randrange(2, 30000)
         for _ in range(spec.prefix_blocks * block_size)]
        for _ in range(spec.groups)
    ]
    prompts = []
    for group, prefix in enumerate(prefixes):
        for _ in range(spec.sessions_per_group):
            prompts.append(
                prefix + [rng.randrange(2, 30000)
                          for _ in range(spec.suffix_tokens)]
            )
    # interleave groups the way real traffic arrives (round-robin over
    # groups, NOT group-sorted — affinity has to earn its hits)
    order = list(range(len(prompts)))
    rng.shuffle(order)
    return [prompts[i] for i in order]


async def run_leg(
    policy: str,
    spec: TrafficSpec,
    *,
    replicas: int = 4,
    block_size: int = 8,
    queue_timeout_s: Optional[float] = 8.0,
    **fleet_kwargs: Any,
) -> Dict[str, Any]:
    fleet = SimFleet(
        replicas,
        policy=policy,
        block_size=block_size,
        queue_timeout_s=queue_timeout_s,
        **fleet_kwargs,
    )
    # prime the router's view before the first routing decision
    await fleet._pump_heartbeats()
    prompts = make_prompts(spec, block_size)
    waves = [
        prompts[i:i + spec.wave_size]
        for i in range(0, len(prompts), spec.wave_size)
    ]
    for wave in waves:
        for prompt in wave:
            fleet.submit(prompt, max_new_tokens=spec.max_new_tokens)
        await fleet.run(spec.ticks_between_waves)
    await fleet.run_until_idle()
    ttfts = sorted(
        s.first_token_at - s.submitted_at
        for s in fleet.sessions
        if s.first_token_at is not None and s.submitted_at is not None
    )
    record = {
        "metric": "fleet_sim",
        "policy": policy,
        "value": float(fleet.fleet_hit_tokens()),
        "prefix_hit_tokens": fleet.fleet_hit_tokens(),
        "requests_shed": fleet.fleet_shed_total(),
        "reroutes": fleet.reroutes,
        "client_errors": fleet.client_errors(),
        "sessions": len(fleet.sessions),
        "replicas": replicas,
        "sim_seconds": round(fleet.now, 3),
        "ttft_p50_s": round(ttfts[len(ttfts) // 2], 3) if ttfts else None,
    }
    return record


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(
        description="routed-vs-round-robin fleet A/B on simulated traffic"
    )
    parser.add_argument("--replicas", type=int, default=4)
    parser.add_argument("--groups", type=int, default=4)
    parser.add_argument("--sessions-per-group", type=int, default=16)
    parser.add_argument("--seed", type=int, default=1234)
    parser.add_argument(
        "--out", default="bench_artifacts",
        help="directory for bench_fleet_routed.json / bench_fleet_rr.json",
    )
    args = parser.parse_args(argv)
    spec = TrafficSpec(
        groups=args.groups,
        sessions_per_group=args.sessions_per_group,
        seed=args.seed,
    )
    os.makedirs(args.out, exist_ok=True)
    legs = {
        "bench_fleet_routed.json": "affinity",
        "bench_fleet_rr.json": "round_robin",
    }
    for filename, policy in legs.items():
        record = asyncio.run(
            run_leg(policy, spec, replicas=args.replicas)
        )
        path = os.path.join(args.out, filename)
        with open(path, "w") as handle:
            handle.write(json.dumps(record) + "\n")
        print(json.dumps(record))


if __name__ == "__main__":
    main()
