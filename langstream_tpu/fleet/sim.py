"""Simulated fleet: M fake engines with REAL paged prefix caches.

The fleet layer's acceptance instrument (``tests/test_fleet.py``): the
router, heartbeat protocol, and autoscaler are the production classes;
only the engine is fake — a :class:`SimReplica` replaces the device
with a step-counting slot model but keeps a real
:class:`~langstream_tpu.providers.jax_local.paged.PagedKVManager`, so
prefix matching, block-granular admission, publish-at-admission/finish,
refcounts, and LRU eviction behave exactly like a runner pod's pool.
Heartbeats flow through a real in-process memory topic
(``topics/memory.py``) and scaling actuates a real
``Operator.scale`` against a ``MockKubeApi`` StatefulSet, so the whole
loop — gossip → routing → pressure → patch → reconcile — runs on CPU
with no JAX and no cluster.

Time is simulated (``fleet.now`` advances ``step_time`` per tick), so
SLO windows, heartbeat timeouts, and autoscaler cooldowns run in
microseconds of wall clock.

Cost model (deliberately minimal): admission occupies a slot for
``ceil(missed_prefill_tokens / prefill_rate)`` steps — a prefix hit
skips prefill work, which is WHY affinity routing lifts throughput and
cuts TTFT/sheds, not just a counter. Decode is one token per step per
slot. Generated tokens are a pure function of (prompt, index) so a
session killed mid-stream and re-routed continues its exact stream on
any replica — the fleet-level analogue of PR 9's bitwise resurrection.

``python -m langstream_tpu.fleet.sim`` runs the routed-vs-round-robin
A/B on identical traffic and writes ``bench_fleet_routed.json`` /
``bench_fleet_rr.json`` artifacts for ``tools/ab_analyze.py``;
``--disagg`` runs the disaggregated-vs-unified pair
(``bench_fleet_disagg.json`` / ``bench_fleet_unified.json``).

Prefill/decode disaggregation (ISSUE 15): with ``prefill_blocking``
the step model serializes monolithic prefill dispatches against decode
(the real split-mode engine's behavior — one device, one dispatch
stream), which is exactly the interference the unified leg suffers: a
cold prompt landing on a replica stalls every decoding stream on it
for the whole prefill. Role-aware fleets route cold prompts to a
prefill pool; each prefill replica emits the FIRST token, exports the
session's block chain as bounded ``kv_handoff`` chunks over the topic
fabric (``fleet/handoff.py``), and the fleet imports them — worst-case
block reservation at import-admission, publish-at-commit only — into
an affinity-chosen decode replica, then pins the decode leg there (the
routed ``langstream-replica`` header). Decode replicas never run a
monolithic prefill, so their max TPOT excursion is structurally
bounded — the number the disagg A/B is judged on, at equal tok/s and
bitwise-identical client streams.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import math
import os
import random
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from langstream_tpu.api.records import Record
from langstream_tpu.deployer.kube import MockKubeApi
from langstream_tpu.deployer.operator import Operator
from langstream_tpu.fleet.autoscaler import AutoscalePolicy, SLOAutoscaler
from langstream_tpu.fleet.handoff import (
    HANDOFF_TOPIC,
    HandoffAssembler,
    handoff_records,
    new_handoff_id,
)
from langstream_tpu.fleet.heartbeat import HEARTBEAT_TOPIC
from langstream_tpu.fleet.router import (
    FleetRouter,
    NoRoutableReplica,
    digests_from_keys,
    prompt_digests,
)
from langstream_tpu.providers.jax_local.paged import (
    HostKVArena,
    PagedKVManager,
)
from langstream_tpu.runtime.journey import StageBuilder
from langstream_tpu.topics.memory import (
    MemoryBroker,
    MemoryTopicProducer,
    MemoryTopicReader,
)
from langstream_tpu.api.topics import OffsetPosition


class ReplicaDown(Exception):
    """Submit raced a crash: the fleet re-routes, the client never sees it."""


def generated_token(prompt: Sequence[int], index: int) -> int:
    """Deterministic continuation token ``index`` for ``prompt`` —
    replica-independent, so a re-routed session's stream is bitwise
    identical to the unkilled oracle."""
    seed = 0
    for t in prompt:
        seed = (seed * 1000003 + int(t)) & 0xFFFFFFFF
    return 2 + (seed * 31 + index * 7919) % 29989


class SimSession:
    """One client stream. ``tokens`` is what the client saw — append
    only, each token exactly once; ``errors`` is what a real client
    would surface as a 500 (503-with-retry paths stay internal)."""

    _ids = 0

    def __init__(self, prompt: Sequence[int], max_new_tokens: int = 8) -> None:
        SimSession._ids += 1
        self.id = f"sess-{SimSession._ids}"
        # one trace id for the whole client stream, however many
        # replicas (prefill leg, handoff, decode leg, crash re-routes)
        # it crosses — the journey ledger's join key
        self.trace_id = f"trace-{self.id}"
        # journey cross-leg markers, stamped by the fleet at handoff
        # commit (transit start = the chunk-0 manifest's export_ts) and
        # consumed by the next leg's journey record
        self._jt_transit_start: Optional[float] = None
        self._jt_import: Optional[Tuple[float, float]] = None
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.tokens: List[int] = []
        self.errors: List[str] = []
        self.done = False
        self.reroutes = 0
        self.replica: Optional[str] = None
        self.submitted_at: Optional[float] = None  # fleet submit (sim s)
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # per-token provenance for the tail instrument: the disagg A/B
        # is judged on the max inter-token gap WITHIN one replica's leg
        # (the handoff/reroute boundary is a TTFT-shaped cost, not a
        # decode-interference excursion)
        self.token_times: List[float] = []
        self.token_replicas: List[str] = []

    def max_tpot_excursion(self) -> float:
        """Worst inter-token gap between consecutive tokens emitted by
        the SAME replica — decode interference as the client feels it,
        excluding leg boundaries (handoff / crash re-route), which the
        TTFT columns already price."""
        worst = 0.0
        for i in range(1, len(self.token_times)):
            if self.token_replicas[i] == self.token_replicas[i - 1]:
                worst = max(
                    worst, self.token_times[i] - self.token_times[i - 1]
                )
        return worst

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    def admission_tokens(self) -> List[int]:
        """What a (re)admission prefills: original prompt plus every
        token already delivered (PR 9 replay shape)."""
        return self.prompt + self.tokens

    def expected_tokens(self) -> List[int]:
        return [
            generated_token(self.prompt, i)
            for i in range(self.max_new_tokens)
        ]


class _Slot:
    __slots__ = (
        "session", "table", "prefill_remaining", "adm_tokens",
        # journey anchors for this leg (sim seconds)
        "queued_at", "admitted_at", "admit_class", "first_token_at",
    )

    def __init__(
        self, session, table, prefill_steps, adm_tokens,
        queued_at=0.0, admitted_at=0.0, admit_class="cold",
    ) -> None:
        self.session = session
        self.table = table
        self.prefill_remaining = prefill_steps
        self.adm_tokens = adm_tokens
        self.queued_at = queued_at
        self.admitted_at = admitted_at
        self.admit_class = admit_class
        self.first_token_at: Optional[float] = None


class SimReplica:
    """Fake engine, real pool. The step model: admission pops the
    queue into free slots (worst-case block reservation — allocation
    failure is backpressure, exactly like ``_admit_paged``), prefill
    holds the slot ``ceil(miss/prefill_rate)`` steps, decode emits one
    token per step, finish publishes the full-block chain and releases
    the table."""

    def __init__(
        self,
        name: str,
        *,
        num_blocks: int = 256,
        block_size: int = 8,
        slots: int = 4,
        prefill_rate: int = 64,
        queue_timeout_s: Optional[float] = None,
        ttft_target_s: float = 2.0,
        digest_limit: int = 4096,
        role: str = "unified",
        kv_host_blocks: int = 0,
        prefill_blocking: bool = False,
        handoff_block_bytes: int = 2048,
        handoff_chunk_bytes: int = 8192,
        handoff_chunks_per_tick: int = 4,
    ) -> None:
        self.name = name
        self.block_size = block_size
        self.num_slots = slots
        self.prefill_rate = prefill_rate
        self.queue_timeout_s = queue_timeout_s
        self.ttft_target_s = ttft_target_s
        self.digest_limit = digest_limit
        # disaggregation: pool membership + the interference model.
        # ``prefill_blocking`` serializes prefill dispatches against
        # decode (the real single-device engine), so a unified replica
        # admitting a cold prompt stalls its decoding slots — the
        # excursion the disagg A/B cuts. ``prefill`` replicas emit the
        # first token then hand the session's chain off as bounded
        # chunks; ``decode`` replicas import chains and never prefill
        # more than a warm suffix.
        self.role = role
        self.prefill_blocking = prefill_blocking
        self.handoff_block_bytes = handoff_block_bytes
        self.handoff_chunk_bytes = handoff_chunk_bytes
        self.handoff_chunks_per_tick = handoff_chunks_per_tick
        self.handoff_outbox: Deque[Dict[str, Any]] = deque()
        # in-flight imports: handoff_id -> (tokens, reserved blocks) —
        # refcount-held, UNPUBLISHED until commit (abort releases them
        # before any id can recycle under a live chain key)
        self._imports: Dict[str, Tuple[List[int], List[int]]] = {}
        self.handoff_stats: Dict[str, int] = {
            "exported": 0, "imported": 0, "aborted": 0, "bytes": 0,
        }
        self.kv = PagedKVManager(num_blocks, block_size)
        # host-DRAM demotion tier (ISSUE 18): accounting-only arena —
        # no rows to move in the sim, but matching, LRU, capacity
        # backpressure, and gossip behave exactly like the engine's
        self.kv_host_blocks = int(kv_host_blocks)
        if self.kv_host_blocks > 0:
            self.kv.attach_host(HostKVArena(self.kv_host_blocks))
        self.host_hit_tokens = 0
        # eviction-recompute ledger, the engine's
        # tokens_wasted{evicted_recompute} analogue: full blocks this
        # replica prefills AGAIN after having once published them
        # (digest-keyed, so an id recycled by the pool still counts)
        self._taught: set = set()
        self.recompute_tokens = 0
        self.queue: Deque[Tuple[SimSession, float]] = deque()
        self.active: List[_Slot] = []
        # journey ledger (ISSUE 20): one record per leg served here, the
        # same schema the engine writes to its flight recorder —
        # ``SimFleet.write_flight_artifacts`` lays them out on disk so
        # ``langstream-tpu journey`` joins sim fleets unchanged
        self.journeys: List[Dict[str, Any]] = []
        self.state = "serving"
        self.seq = 0
        self.boot = 0  # bumped per rebuild: the heartbeat epoch
        self.shed_total = 0
        self._ttft_samples: Deque[Tuple[float, float]] = deque()

    # -------------------------------------------------------------- #
    # serving
    # -------------------------------------------------------------- #
    def submit(self, session: SimSession, now: float) -> None:
        if self.state != "serving":
            raise ReplicaDown(f"{self.name} is {self.state}")
        session.replica = self.name
        if session.submitted_at is None:
            session.submitted_at = now
        self.queue.append((session, now))

    def _admit(self, now: float) -> None:
        while self.queue and len(self.active) < self.num_slots:
            session, queued_at = self.queue[0]
            adm = session.admission_tokens()
            chain, matched = self.kv.match(adm)
            need = max(
                0,
                math.ceil(
                    (len(adm) + session.remaining) / self.block_size
                ) - len(chain),
            )
            fresh = self.kv.allocate(need)
            if fresh is None:
                return  # pool backpressure: admission waits
            self.queue.popleft()
            self.kv.ref(chain)
            self.kv.stats["hit_tokens"] += matched
            table = chain + fresh
            # host-tier promotion: digest-matched demoted blocks
            # continue the HBM chain without recompute — prefill only
            # pays for tokens NEITHER tier holds (the engine's H2D
            # scatter costs bytes, not FLOPs; the step model prices
            # FLOPs, so a promoted block is simply not re-prefilled)
            promoted = 0
            if self.kv.host is not None:
                entries = self.kv.host_match(adm, len(chain))
                if entries:
                    promoted = len(entries) * self.block_size
                    self.host_hit_tokens += promoted
                    self.kv.host.note_promoted(len(entries))
            # eviction-recompute: unmatched full blocks this replica
            # once published are re-teach work an un-tiered pool burns
            digests = prompt_digests(
                adm, self.block_size, limit=len(adm) // self.block_size
            )
            start = len(chain) + (promoted // self.block_size)
            self.recompute_tokens += self.block_size * sum(
                1 for d in digests[start:] if d in self._taught
            )
            self._taught.update(digests)
            # publish-cold-at-admission: concurrent same-prefix
            # sessions hit these blocks before this one finishes
            self.kv.publish(adm, table)
            prefill_steps = math.ceil(
                max(0, len(adm) - matched - promoted) / self.prefill_rate
            )
            # journey admission class: a handoff-import leg's prefix
            # hit was manufactured by the fabric, not earned by the pool
            admit_class = (
                "handoff-import" if session._jt_import is not None
                else "host-promote" if promoted
                else "hbm-hit" if matched
                else "cold"
            )
            self.active.append(_Slot(
                session, table, prefill_steps, adm,
                queued_at=queued_at, admitted_at=now,
                admit_class=admit_class,
            ))

    def _shed_expired(self, now: float) -> List[SimSession]:
        if not self.queue_timeout_s:
            return []
        shed: List[SimSession] = []
        keep: Deque[Tuple[SimSession, float]] = deque()
        for session, queued_at in self.queue:
            if now - queued_at >= self.queue_timeout_s:
                self.shed_total += 1
                shed.append(session)
                # the shed wait is still attributable queue time: a
                # partial journey record keeps the re-routed request's
                # e2e wall tiled (the next leg starts its own queue)
                self.journeys.append({
                    "ts": now,
                    "kind": "journey",
                    "trace_id": session.trace_id,
                    "session_id": session.id,
                    "finish_reason": "shed",
                    "tokens": len(session.tokens),
                    "stages": [{
                        "stage": "queue", "start": queued_at,
                        "end": now, "shed": True,
                    }],
                })
            else:
                keep.append((session, queued_at))
        self.queue = keep
        return shed

    def step(self, now: float) -> Dict[str, Any]:
        """One engine step: shed expired, admit, prefill/decode.
        Returns sessions that finished, sessions shed at the admission
        deadline (the fleet re-routes sheds — a 503 with Retry-After,
        never a client 500), sessions handed off (prefill role: first
        token emitted, chain exported), and the handoff records this
        tick may publish (the outbox drains at a bounded rate, so a
        fat handoff never floods the fabric in one tick — and a crash
        can land MID-handoff, which is the failure the orphan GC and
        import-abort paths exist for)."""
        if self.state != "serving":
            return {"finished": [], "shed": [], "handoffs": [],
                    "records": []}
        shed = self._shed_expired(now)
        self._admit(now)
        records = [
            self.handoff_outbox.popleft()
            for _ in range(min(
                len(self.handoff_outbox), self.handoff_chunks_per_tick
            ))
        ]
        finished: List[SimSession] = []
        handoffs: List[Tuple[str, SimSession]] = []
        if self.prefill_blocking and any(
            slot.prefill_remaining > 0 for slot in self.active
        ):
            # the split-mode device serializes dispatches: a monolithic
            # prefill stalls every decoding slot for this step (ONE
            # batched prefill dispatch advances all prefilling slots)
            for slot in self.active:
                if slot.prefill_remaining > 0:
                    slot.prefill_remaining -= 1
            return {"finished": finished, "shed": shed,
                    "handoffs": handoffs, "records": records}
        for slot in list(self.active):
            if slot.prefill_remaining > 0:
                slot.prefill_remaining -= 1
                continue
            session = slot.session
            session.tokens.append(
                generated_token(session.prompt, len(session.tokens))
            )
            session.token_times.append(now)
            session.token_replicas.append(self.name)
            if slot.first_token_at is None:
                slot.first_token_at = now  # this LEG's prefill→decode edge
            if session.first_token_at is None:
                session.first_token_at = now
                assert session.submitted_at is not None
                self._ttft_samples.append(
                    (now, now - session.submitted_at)
                )
                while (
                    self._ttft_samples
                    and now - self._ttft_samples[0][0] > 3600.0
                ):
                    self._ttft_samples.popleft()
            if session.remaining <= 0:
                session.done = True
                session.finished_at = now
                full = session.prompt + session.tokens
                self.kv.publish(full, slot.table)
                self.kv.release(slot.table)
                self.active.remove(slot)
                finished.append(session)
                self._emit_journey(slot, now)
            elif self.role == "prefill":
                # disaggregation prefill leg: first token out, chain
                # out — the decode pool owns the continuation
                handoffs.append((self._export_handoff(slot, now), session))
                self.active.remove(slot)
                self._emit_journey(slot, now, handoff=True)
        return {"finished": finished, "shed": shed,
                "handoffs": handoffs, "records": records}

    def _emit_journey(
        self, slot: _Slot, now: float, *, handoff: bool = False
    ) -> None:
        """One finished (or handed-off) leg's ``journey`` record, on
        the sim clock — the exact shape the engine's ``_emit_journey``
        writes to the flight recorder, so ``runtime/journey.py`` joins
        real and simulated fleets with the same code. StageBuilder
        clamping makes the leg tile by construction; the fleet-stamped
        cross-leg markers (transit start, import window) are consumed
        here so a later leg cannot double-emit them."""
        session = slot.session
        builder = StageBuilder()
        transit_start = session._jt_transit_start
        import_window = session._jt_import
        if transit_start is not None:
            builder.add(
                "handoff_transit",
                transit_start,
                import_window[0] if import_window else slot.queued_at,
            )
        if import_window is not None:
            builder.add(
                "handoff_import", import_window[0], import_window[1]
            )
        builder.add("queue", slot.queued_at, slot.admitted_at)
        builder.add(
            "admit", slot.admitted_at, slot.admitted_at,
            admit_class=slot.admit_class,
        )
        first = (
            slot.first_token_at if slot.first_token_at is not None
            else now
        )
        builder.add("prefill", slot.admitted_at, first)
        builder.add("decode", first, now)
        if handoff:
            builder.add("handoff_export", now, now)
        else:
            builder.add("finish", now, now)
        session._jt_transit_start = None
        session._jt_import = None
        self.journeys.append({
            "ts": now,
            "kind": "journey",
            "trace_id": session.trace_id,
            "session_id": session.id,
            "finish_reason": "handoff" if handoff else "stop",
            "tokens": len(session.tokens),
            "admit_class": slot.admit_class,
            "first_token": session.first_token_at,
            "stages": builder.stages,
        })

    # -------------------------------------------------------------- #
    # KV handoff (disaggregation; fleet/handoff.py schema)
    # -------------------------------------------------------------- #
    def _export_handoff(self, slot: _Slot, now: float) -> str:
        """Serialize the finishing prefill leg's chain into bounded
        ``kv_handoff`` records on the outbox. The exported chain is the
        PUBLISHED full-block prefix (publish-at-admission already made
        it matchable here); the emitted first token rides the manifest
        as the teacher-forced replay token, exactly like the engine's
        export."""
        session = slot.session
        chain, matched = self.kv.export_session(slot.adm_tokens)
        tokens = list(slot.adm_tokens[:matched])
        handoff_id = new_handoff_id()
        payload = {
            "tokens": tokens,
            "block_size": self.block_size,
            "kv_quant": False,
            "sim_block_bytes": self.handoff_block_bytes,
        }
        manifest = {
            "session_id": session.id,
            "trace_id": session.trace_id,
            "prompt_len": len(session.prompt),
            "generated": list(session.tokens),
            "replica": self.name,
            # transit anchor: the decode side's journey subtracts this
            # from its import-start to price the fabric hop
            "export_ts": now,
        }
        for record in handoff_records(
            payload, manifest,
            handoff_id=handoff_id,
            max_chunk_bytes=self.handoff_chunk_bytes,
        ):
            self.handoff_outbox.append(record)
        self.kv.release(chain)      # export ref (chain stays published)
        self.kv.release(slot.table)  # the leg's slot reservation
        self.handoff_stats["exported"] += 1
        self.handoff_stats["bytes"] += (
            len(tokens) // self.block_size * self.handoff_block_bytes
        )
        return handoff_id

    def begin_import(self, handoff_id: str, tokens: List[int]) -> bool:
        """Worst-case reservation at import-admission: blocks for every
        full block of the handed-off prefix not already resident, held
        UNPUBLISHED until :meth:`commit_import` — pool pressure aborts
        the handoff here (the session falls back to a cold prefill
        elsewhere; backpressure, never an error)."""
        if self.state != "serving":
            return False
        reserved = self.kv.import_session(tokens)
        if reserved is None:
            self.handoff_stats["aborted"] += 1
            return False
        chain, fresh = reserved
        self._imports[handoff_id] = (list(tokens), chain + fresh)
        return True

    def feed_import(self, handoff_id: str, nbytes: int) -> None:
        if handoff_id in self._imports:
            self.handoff_stats["bytes"] += int(nbytes)

    def commit_import(
        self, handoff_id: str, tokens: Optional[List[int]] = None
    ) -> bool:
        """Publish a fully-arrived chain under its chunk keys and drop
        the reservation refs. ``tokens`` narrows the publish to what
        the chunks actually carried (a prefix of the worst-case
        reservation); over-reserved tail blocks free on release."""
        entry = self._imports.pop(handoff_id, None)
        if entry is None or self.state != "serving":
            return False
        reserved_tokens, blocks = entry
        use = reserved_tokens if tokens is None else list(tokens)
        if len(use) > len(reserved_tokens):
            use = use[: len(reserved_tokens)]
        self.kv.commit_import(use, blocks)
        self.handoff_stats["imported"] += 1
        return True

    def abort_import(self, handoff_id: str) -> None:
        """Unwind a torn import BEFORE any block id recycles: nothing
        was published, so the reserved blocks free straight back."""
        entry = self._imports.pop(handoff_id, None)
        if entry is None:
            return
        if self.state == "serving":
            self.kv.abort_import(entry[1])
        self.handoff_stats["aborted"] += 1

    # -------------------------------------------------------------- #
    # failure / recovery (the PR 9 arc at fleet granularity)
    # -------------------------------------------------------------- #
    def kill(self) -> List[SimSession]:
        """Crash: every queued and active session is handed back for
        fleet-level resurrection (prompt + delivered tokens); the pool
        dies with the process."""
        self.state = "down"
        orphans = [s for s, _ in self.queue] + [
            slot.session for slot in self.active
        ]
        self.queue.clear()
        self.active.clear()
        # un-flushed handoff chunks die with the process — the decode
        # side's orphan GC (fleet tick) aborts their partial imports
        self.handoff_outbox.clear()
        self._imports.clear()
        return orphans

    def rebuild(self) -> None:
        """Supervisor finished: fresh pool (prefix cache lost), same
        identity, heartbeat seq continues so the router's condemnation
        clears on the next serving gossip."""
        self.kv = PagedKVManager(self.kv.num_blocks, self.block_size)
        if self.kv_host_blocks > 0:
            # pinned host memory dies with the process too
            self.kv.attach_host(HostKVArena(self.kv_host_blocks))
        # a crash-rebuild re-teach is crash recompute, not eviction
        # recompute — reset the ledger so the tiered A/B stays honest
        self._taught = set()
        self.state = "serving"
        self.boot += 1  # new process: new heartbeat epoch

    # -------------------------------------------------------------- #
    # gossip
    # -------------------------------------------------------------- #
    def _burn_rates(self, now: float) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for label, window in (("5m", 300.0), ("1h", 3600.0)):
            samples = [
                ttft for ts, ttft in self._ttft_samples
                if now - ts <= window
            ]
            if not samples:
                continue
            violations = sum(
                1 for ttft in samples if ttft > self.ttft_target_s
            )
            out[f"jax_engine_slo_ttft_burn_rate_{label}"] = round(
                (violations / len(samples)) / 0.05, 4
            )
        return out

    def heartbeat(self, now: float) -> Dict[str, Any]:
        self.seq += 1
        gauges = self._burn_rates(now)
        gauges['requests_shed_total{reason="queue_timeout"}'] = float(
            self.shed_total
        )
        gauges["prefix_cache_hit_tokens_total"] = float(
            self.kv.stats["hit_tokens"]
        )
        heartbeat = {
            "replica": self.name,
            "seq": self.seq,
            "epoch": f"{self.name}/boot-{self.boot}",
            "state": self.state,
            "role": self.role,
            "queue_depth": len(self.queue),
            "active_sessions": len(self.active),
            "block_size": self.block_size,
            "chain_digests": sorted(
                digests_from_keys(
                    self.kv.published_keys(limit=self.digest_limit),
                    memo=self.kv.digest_memo,
                )
            ),
            "gauges": gauges,
        }
        if self.kv.host is not None:
            heartbeat["host_chain_digests"] = sorted(
                self.kv.host.digests()
            )
        return heartbeat


class SimFleet:
    """M :class:`SimReplica`s behind a memory-topic heartbeat fabric,
    the production router, and (optionally) the production autoscaler
    actuating a MockKubeApi StatefulSet."""

    def __init__(
        self,
        replicas: int = 3,
        *,
        policy: str = "affinity",
        step_time: float = 0.25,
        heartbeat_interval_s: float = 1.0,
        heartbeat_timeout_s: float = 5.0,
        autoscale: Optional[AutoscalePolicy] = None,
        autoscale_interval_s: float = 5.0,
        namespace: str = "fleet",
        statefulset: str = "runner",
        unrouted_patience_ticks: int = 200,
        roles: Optional[Dict[str, int]] = None,
        handoff_timeout_s: float = 10.0,
        slow_handoff_s: float = 0.0,
        **replica_kwargs: Any,
    ) -> None:
        self.now = 0.0
        self.step_time = step_time
        self.policy = policy
        self.heartbeat_interval_s = heartbeat_interval_s
        self._next_heartbeat = 0.0
        self.replica_kwargs = replica_kwargs
        self.router = FleetRouter(
            policy=policy, heartbeat_timeout_s=heartbeat_timeout_s
        )
        self.broker = MemoryBroker()
        self._producer = MemoryTopicProducer(self.broker, HEARTBEAT_TOPIC)
        self._reader = MemoryTopicReader(
            self.broker, HEARTBEAT_TOPIC, OffsetPosition.EARLIEST
        )
        # disaggregated fleet (roles={"prefill": P, "decode": D}): the
        # KV-handoff fabric shares the broker on its own topic (a fat
        # transfer must never delay heartbeat gossip), the assembler
        # GC's chunks orphaned by a prefill-replica crash, and every
        # handed-off session parks fleet-side until its chain lands
        self.roles = dict(roles) if roles else None
        self.assembler = HandoffAssembler(orphan_timeout_s=handoff_timeout_s)
        self._handoff_producer = MemoryTopicProducer(
            self.broker, HANDOFF_TOPIC
        )
        self._handoff_reader = MemoryTopicReader(
            self.broker, HANDOFF_TOPIC, OffsetPosition.EARLIEST
        )
        # handoff_id -> (decode replica name, accumulated import ok)
        self._handoff_routes: Dict[str, str] = {}
        self._awaiting: Dict[str, SimSession] = {}
        self.handoff_timeout_s = float(handoff_timeout_s)
        # fault injection (journey blame instrument): every handoff
        # chunk sits on the simulated wire this long before the fleet
        # sees it — the ledger must blame the tail on handoff_transit.
        # Keep it under handoff_timeout_s or the orphan sweep wins.
        self.slow_handoff_s = float(slow_handoff_s)
        self._delayed_chunks: List[Tuple[float, Dict[str, Any]]] = []
        # journey anchors the replicas can't see: per-handoff import
        # start (first-chunk reservation) and the chunk-0 manifest's
        # export stamp, consumed when the decode leg is pinned
        self._import_started: Dict[str, float] = {}
        self._handoff_export_ts: Dict[str, float] = {}
        # route-stage journey records (the fleet router is the sim's
        # "gateway"): written as their own flight artifact
        self.route_journeys: List[Dict[str, Any]] = []
        # last chunk progress per awaited handoff: a prefill replica
        # killed BEFORE any chunk flushed leaves nothing in the
        # assembler to GC, so the fleet sweeps its own awaiting table
        self._awaiting_progress: Dict[str, float] = {}
        self.replicas: Dict[str, SimReplica] = {}
        self.namespace, self.statefulset = namespace, statefulset
        self.kube = MockKubeApi()
        self.operator = Operator(self.kube)
        total = (
            sum(self.roles.values()) if self.roles is not None else replicas
        )
        self.kube.apply({
            "kind": "StatefulSet",
            "metadata": {"name": statefulset, "namespace": namespace},
            "spec": {"replicas": total},
        })
        self.autoscaler: Optional[SLOAutoscaler] = None
        self.autoscale_interval_s = autoscale_interval_s
        self._next_autoscale = 0.0
        if autoscale is not None:
            if self.roles is not None:
                raise ValueError(
                    "the sim's single-StatefulSet autoscaler does not "
                    "compose with roles= (per-pool autoscaling is the "
                    "role-scoped SLOAutoscaler, tested directly)"
                )
            self.autoscaler = SLOAutoscaler(
                autoscale,
                scale=lambda n: self.operator.scale(
                    namespace, statefulset, n
                ),
            )
        if self.roles is not None:
            for role, count in self.roles.items():
                for ordinal in range(count):
                    self._spawn(ordinal, role=role)
        else:
            for ordinal in range(replicas):
                self._spawn(ordinal)
        # fleet books
        self.sessions: List[SimSession] = []
        self._unrouted: Deque[SimSession] = deque()
        # retry budget for a session no replica will take: past it the
        # client REALLY sees the failure (this is what keeps the
        # zero-client-errors assertions falsifiable — a fleet that
        # cannot place a session does produce an error)
        self.unrouted_patience_ticks = int(unrouted_patience_ticks)
        self.reroutes = 0
        self.fleet_sheds = 0
        self.retired_hit_tokens = 0  # killed replicas' counters survive

    # -------------------------------------------------------------- #
    # replica lifecycle
    # -------------------------------------------------------------- #
    def _spawn(
        self, ordinal: int, role: Optional[str] = None
    ) -> SimReplica:
        name = (
            f"{self.statefulset}-{role}-{ordinal}" if role
            else f"{self.statefulset}-{ordinal}"
        )
        kwargs = dict(self.replica_kwargs)
        if role:
            kwargs["role"] = role
        replica = SimReplica(name, **kwargs)
        self.replicas[name] = replica
        return replica

    def kill(self, name: str) -> None:
        """Crash one runner mid-stream: condemn it in the router (the
        gateway's 503 signal) and resurrect its sessions elsewhere."""
        replica = self.replicas[name]
        self.retired_hit_tokens += replica.kv.stats["hit_tokens"]
        orphans = replica.kill()
        self.router.mark_unroutable(name, reason="crashed")
        for session in orphans:
            session.reroutes += 1
            self.reroutes += 1
            self._route_submit(session)

    def revive(self, name: str) -> None:
        self.replicas[name].rebuild()

    # -------------------------------------------------------------- #
    # traffic
    # -------------------------------------------------------------- #
    def submit(
        self, prompt: Sequence[int], max_new_tokens: int = 8
    ) -> SimSession:
        session = SimSession(prompt, max_new_tokens)
        session.submitted_at = self.now
        self.sessions.append(session)
        self._route_submit(session)
        return session

    def _route_submit(self, session: SimSession) -> None:
        """Route (or re-route) a session; a submit that races a crash
        condemns the replica and retries — only a fleet with zero
        routable replicas parks the session for the next tick (the
        client's 503-with-Retry-After, not a 500)."""
        for _ in range(len(self.replicas) + 1):
            try:
                decision = self.router.route(
                    session.admission_tokens(), now=self.now,
                    # disaggregated fleet: every cold (or re-routed)
                    # admission is a prefill leg — the decode pool only
                    # ever receives pinned handoff continuations
                    role="prefill" if self.roles is not None else None,
                )
            except NoRoutableReplica:
                break
            replica = self.replicas.get(decision.replica_id)
            if replica is None:
                self.router.forget(decision.replica_id)
                continue
            try:
                replica.submit(session, self.now)
                session._unrouted_ticks = 0
                self._record_route(
                    session,
                    replica=decision.replica_id,
                    policy=getattr(decision, "policy", self.policy),
                    matched_blocks=getattr(decision, "matched_blocks", 0),
                    matched_host_blocks=getattr(
                        decision, "matched_host_blocks", 0
                    ),
                )
                return
            except ReplicaDown:
                self.router.mark_unroutable(
                    decision.replica_id, reason="connection refused"
                )
        self._unrouted.append(session)

    def _record_route(
        self,
        session: SimSession,
        *,
        replica: str,
        policy: str,
        matched_blocks: int = 0,
        matched_host_blocks: int = 0,
    ) -> None:
        """A zero-width ``route`` journey stage — the fleet router is
        the sim's gateway, so its decisions land in their own flight
        artifact keyed by the same trace id."""
        prefix_class = (
            "handoff" if policy == "pinned"
            else "host" if matched_host_blocks
            else "warm" if matched_blocks
            else "cold"
        )
        self.route_journeys.append({
            "ts": self.now,
            "kind": "journey",
            "trace_id": session.trace_id,
            "session_id": session.id,
            "stages": [{
                "stage": "route", "start": self.now, "end": self.now,
                "policy": policy, "replica": replica,
                "prefix_class": prefix_class,
            }],
        })

    # -------------------------------------------------------------- #
    # the loop
    # -------------------------------------------------------------- #
    async def _pump_heartbeats(self) -> None:
        for replica in self.replicas.values():
            if replica.state != "down":
                heartbeat = replica.heartbeat(self.now)
                await self._producer.write(
                    Record(value=heartbeat, key=replica.name)
                )
        for record in await self._reader.read(
            max_records=10_000, timeout=0.0
        ):
            if isinstance(record.value, dict):
                self.router.observe(record.value, now=self.now)

    def _fallback_cold(self, handoff_id: str) -> None:
        """A handoff died (orphaned chunks, pool pressure, decode
        replica crash): drop whatever was reserved and re-route the
        session as a cold prefill — deterministic tokens make the
        stream bitwise wherever it lands, so the client only ever sees
        a latency bump, never a 500."""
        replica_name = self._handoff_routes.pop(handoff_id, None)
        if replica_name is not None:
            replica = self.replicas.get(replica_name)
            if replica is not None:
                replica.abort_import(handoff_id)
        session = self._awaiting.pop(handoff_id, None)
        self._awaiting_progress.pop(handoff_id, None)
        self._import_started.pop(handoff_id, None)
        export_ts = self._handoff_export_ts.pop(handoff_id, None)
        if session is not None and not session.done:
            if export_ts is not None:
                # the dead handoff's wire time is still transit the
                # ledger should attribute to the cold re-routed leg
                session._jt_transit_start = export_ts
            session.reroutes += 1
            self.reroutes += 1
            self._route_submit(session)

    async def _pump_handoffs(self) -> None:
        """Drain the ``kv_handoff`` topic: route each new handoff to an
        affinity-scored decode replica with worst-case reservation at
        FIRST chunk (import-admission), feed it chunk bytes, and on the
        final chunk commit the chain + submit the pinned decode leg.
        Then GC orphans (prefill replica died mid-handoff) back to cold
        re-routes."""
        incoming = [
            record.value
            for record in await self._handoff_reader.read(
                max_records=10_000, timeout=0.0
            )
            if isinstance(record.value, dict)
        ]
        if self.slow_handoff_s > 0.0:
            # injected fabric fault: park every fresh chunk until its
            # simulated arrival time
            self._delayed_chunks.extend(
                (self.now + self.slow_handoff_s, value)
                for value in incoming
            )
            incoming = []
        if self._delayed_chunks:
            due = [v for t, v in self._delayed_chunks if t <= self.now]
            if due:
                self._delayed_chunks = [
                    (t, v) for t, v in self._delayed_chunks
                    if t > self.now
                ]
                incoming = due + incoming
        for value in incoming:
            handoff_id = value.get("handoff_id")
            session = self._awaiting.get(handoff_id)
            if session is None:
                continue  # already aborted/completed; stale chunk
            self._awaiting_progress[handoff_id] = self.now
            manifest = value.get("manifest")
            if (
                isinstance(manifest, dict)
                and manifest.get("export_ts") is not None
            ):
                self._handoff_export_ts[handoff_id] = float(
                    manifest["export_ts"]
                )
            if handoff_id not in self._handoff_routes:
                try:
                    decision = self.router.route(
                        session.admission_tokens(), now=self.now,
                        role="decode",
                    )
                except NoRoutableReplica:
                    self._fallback_cold(handoff_id)
                    continue
                replica = self.replicas.get(decision.replica_id)
                size = self.replica_kwargs.get("block_size", 8)
                adm = session.admission_tokens()
                worst = adm[: len(adm) // size * size]
                if replica is None or not replica.begin_import(
                    handoff_id, worst
                ):
                    self._fallback_cold(handoff_id)
                    continue
                self._handoff_routes[handoff_id] = decision.replica_id
                self._import_started[handoff_id] = self.now
            replica = self.replicas.get(self._handoff_routes[handoff_id])
            if replica is not None:
                replica.feed_import(
                    handoff_id, int(value.get("sim_bytes", 0) or 0)
                )
            assembled = self.assembler.offer(value, now=self.now)
            if assembled is None:
                continue
            replica_name = self._handoff_routes.pop(handoff_id, None)
            session = self._awaiting.pop(handoff_id, None)
            self._awaiting_progress.pop(handoff_id, None)
            import_start = self._import_started.pop(handoff_id, self.now)
            export_ts = self._handoff_export_ts.pop(handoff_id, None)
            if export_ts is None:
                export_ts = (assembled.get("manifest") or {}).get(
                    "export_ts"
                )
            replica = (
                self.replicas.get(replica_name) if replica_name else None
            )
            committed = replica is not None and replica.commit_import(
                handoff_id, tokens=assembled["payload"]["tokens"]
            )
            if session is None:
                continue
            if committed:
                # journey cross-leg markers: the decode leg's record
                # prices transit (manifest stamp → first-chunk
                # reservation) and the import window itself
                if export_ts is not None:
                    session._jt_transit_start = float(export_ts)
                session._jt_import = (import_start, self.now)
                try:
                    # the routed `langstream-replica` pin: the decode
                    # leg goes to the replica holding the imported
                    # chain, not through scoring again
                    replica.submit(session, self.now)
                    self._record_route(
                        session, replica=replica_name, policy="pinned"
                    )
                    continue
                except ReplicaDown:
                    pass
            session.reroutes += 1
            self.reroutes += 1
            self._route_submit(session)
        for orphan_id in self.assembler.gc(self.now):
            self._fallback_cold(orphan_id)
        # chunk-less orphans: the exporter died before anything reached
        # the fabric — nothing for the assembler to GC, so the fleet
        # times the awaiting session out itself and re-routes it cold
        for handoff_id, session in list(self._awaiting.items()):
            started = self._awaiting_progress.get(handoff_id)
            if started is None:
                self._awaiting_progress[handoff_id] = self.now
            elif self.now - started >= self.handoff_timeout_s:
                self._fallback_cold(handoff_id)

    def _reconcile_replicas(self) -> None:
        """StatefulSet semantics: ordinals ``0..replicas-1`` exist.
        Scale-up spawns the missing ordinals; scale-down removes
        ordinals past ``desired`` once drained (a down-but-in-range
        replica is the supervisor's problem, not the reconciler's)."""
        sts = self.kube.get(
            "StatefulSet", self.namespace, self.statefulset
        )
        desired = int(sts["spec"]["replicas"]) if sts else len(self.replicas)
        for ordinal in range(desired):
            if f"{self.statefulset}-{ordinal}" not in self.replicas:
                self._spawn(ordinal)
        for name in sorted(
            self.replicas, key=lambda n: int(n.rsplit("-", 1)[1])
        )[desired:]:
            replica = self.replicas[name]
            if not replica.queue and not replica.active:
                self.retired_hit_tokens += replica.kv.stats["hit_tokens"]
                self.replicas.pop(name)
                self.router.forget(name)

    async def tick(self) -> None:
        self.now += self.step_time
        retry, self._unrouted = self._unrouted, deque()
        for session in retry:
            waited = getattr(session, "_unrouted_ticks", 0) + 1
            session._unrouted_ticks = waited
            if waited > self.unrouted_patience_ticks:
                # retries exhausted: the client's 503s harden into a
                # real failure (counted by client_errors())
                session.errors.append(
                    f"503: no routable replica after {waited} retries"
                )
                continue
            self._route_submit(session)
        for replica in list(self.replicas.values()):
            result = replica.step(self.now)
            for session in result["shed"]:
                self.fleet_sheds += 1
                session.reroutes += 1
                self._route_submit(session)
            for handoff_id, session in result.get("handoffs", ()):
                # the session leaves the prefill replica: the fleet owns
                # it until its chain lands on a decode replica (or the
                # orphan GC re-routes it cold)
                self._awaiting[handoff_id] = session
            for record in result.get("records", ()):
                await self._handoff_producer.write(
                    Record(value=record, key=record["handoff_id"])
                )
        await self._pump_handoffs()
        if self.now >= self._next_heartbeat:
            self._next_heartbeat = self.now + self.heartbeat_interval_s
            await self._pump_heartbeats()
        if self.autoscaler is not None and self.now >= self._next_autoscale:
            self._next_autoscale = self.now + self.autoscale_interval_s
            sts = self.kube.get(
                "StatefulSet", self.namespace, self.statefulset
            )
            current = int(sts["spec"]["replicas"])
            self.autoscaler.step(self.router, current, now=self.now)
            self._reconcile_replicas()

    async def run(self, ticks: int) -> None:
        for _ in range(ticks):
            await self.tick()

    async def run_until_idle(self, max_ticks: int = 10_000) -> None:
        for _ in range(max_ticks):
            await self.tick()
            if self._unrouted or self._awaiting:
                continue
            if all(
                not r.queue and not r.active
                and not r.handoff_outbox
                for r in self.replicas.values()
            ) and all(s.done or s.errors for s in self.sessions):
                return
        raise TimeoutError(
            f"fleet not idle after {max_ticks} ticks "
            f"(unrouted={len(self._unrouted)}, "
            f"awaiting_handoff={len(self._awaiting)})"
        )

    # -------------------------------------------------------------- #
    # books
    # -------------------------------------------------------------- #
    def fleet_hit_tokens(self) -> int:
        return self.retired_hit_tokens + sum(
            r.kv.stats["hit_tokens"] for r in self.replicas.values()
        )

    def fleet_shed_total(self) -> int:
        return self.fleet_sheds

    def fleet_recompute_tokens(self) -> int:
        """Eviction-recompute across live replicas — the waste column
        the tiered A/B is judged on (retired replicas' counters are
        crash recompute, a different bill)."""
        return sum(
            r.recompute_tokens for r in self.replicas.values()
        )

    def fleet_host_hit_tokens(self) -> int:
        return sum(
            r.host_hit_tokens for r in self.replicas.values()
        )

    def host_tier_totals(self) -> Dict[str, int]:
        totals = {"demoted_blocks": 0, "promoted_blocks": 0, "evictions": 0}
        for replica in self.replicas.values():
            if replica.kv.host is None:
                continue
            stats = replica.kv.host.snapshot_stats()
            for key in totals:
                totals[key] += stats[key]
        return totals

    def client_errors(self) -> int:
        return sum(len(s.errors) for s in self.sessions)

    def handoff_totals(self) -> Dict[str, int]:
        totals = {"exported": 0, "imported": 0, "aborted": 0, "bytes": 0}
        for replica in self.replicas.values():
            for key in totals:
                totals[key] += replica.handoff_stats[key]
        return totals

    def max_tpot_excursion(self) -> float:
        return max(
            (s.max_tpot_excursion() for s in self.sessions), default=0.0
        )

    def write_flight_artifacts(self, directory: str) -> List[str]:
        """Lay the fleet's journey records out as per-replica
        ``flight_*.jsonl`` artifacts (meta line first, carrying the
        replica identity) plus one for the fleet router's route
        decisions — the exact on-disk shape a real pod's flight
        recorder leaves, so ``langstream-tpu journey`` joins simulated
        fleets through the same code path as real ones."""
        os.makedirs(directory, exist_ok=True)
        paths: List[str] = []

        def write(
            name: str, role: str, records: List[Dict[str, Any]]
        ) -> None:
            path = os.path.join(directory, f"flight_sim-{name}.jsonl")
            with open(path, "w", encoding="utf-8") as handle:
                handle.write(json.dumps({
                    "ts": 0.0, "kind": "meta",
                    "replica": name, "fleet_role": role,
                }) + "\n")
                for record in records:
                    handle.write(json.dumps(record) + "\n")
            paths.append(path)

        for name, replica in self.replicas.items():
            write(name, replica.role, replica.journeys)
        write("fleet-router", "router", self.route_journeys)
        return paths

    def gauges(self) -> Dict[str, float]:
        out = self.router.gauges(now=self.now)
        out["fleet_replicas_current"] = float(
            sum(1 for r in self.replicas.values() if r.state != "down")
        )
        if self.autoscaler is not None:
            out.update(self.autoscaler.gauges())
        if self.roles is not None:
            out.update(self.assembler.gauges())
        return out


# ------------------------------------------------------------------ #
# shared-prefix traffic + the routed-vs-round-robin A/B artifact
# ------------------------------------------------------------------ #
@dataclasses.dataclass
class TrafficSpec:
    groups: int = 4
    sessions_per_group: int = 16
    prefix_blocks: int = 4       # shared prefix length, in blocks
    suffix_tokens: int = 8       # unique per-session tail
    max_new_tokens: int = 8
    wave_size: int = 8           # sessions submitted per wave
    ticks_between_waves: int = 4
    seed: int = 1234


def make_prompts(
    spec: TrafficSpec, block_size: int
) -> List[List[int]]:
    rng = random.Random(spec.seed)
    prefixes = [
        [rng.randrange(2, 30000)
         for _ in range(spec.prefix_blocks * block_size)]
        for _ in range(spec.groups)
    ]
    prompts = []
    for group, prefix in enumerate(prefixes):
        for _ in range(spec.sessions_per_group):
            prompts.append(
                prefix + [rng.randrange(2, 30000)
                          for _ in range(spec.suffix_tokens)]
            )
    # interleave groups the way real traffic arrives (round-robin over
    # groups, NOT group-sorted — affinity has to earn its hits)
    order = list(range(len(prompts)))
    rng.shuffle(order)
    return [prompts[i] for i in order]


async def run_leg(
    policy: str,
    spec: TrafficSpec,
    *,
    replicas: int = 4,
    block_size: int = 8,
    queue_timeout_s: Optional[float] = 8.0,
    **fleet_kwargs: Any,
) -> Dict[str, Any]:
    fleet = SimFleet(
        replicas,
        policy=policy,
        block_size=block_size,
        queue_timeout_s=queue_timeout_s,
        **fleet_kwargs,
    )
    # prime the router's view before the first routing decision
    await fleet._pump_heartbeats()
    prompts = make_prompts(spec, block_size)
    waves = [
        prompts[i:i + spec.wave_size]
        for i in range(0, len(prompts), spec.wave_size)
    ]
    for wave in waves:
        for prompt in wave:
            fleet.submit(prompt, max_new_tokens=spec.max_new_tokens)
        await fleet.run(spec.ticks_between_waves)
    await fleet.run_until_idle()
    return _leg_record(fleet, policy, replicas)


def _leg_record(
    fleet: SimFleet, policy: str, replicas: int
) -> Dict[str, Any]:
    ttfts = sorted(
        s.first_token_at - s.submitted_at
        for s in fleet.sessions
        if s.first_token_at is not None and s.submitted_at is not None
    )
    total_tokens = sum(len(s.tokens) for s in fleet.sessions)
    record = {
        "metric": "fleet_sim",
        "policy": policy,
        "value": float(fleet.fleet_hit_tokens()),
        "prefix_hit_tokens": fleet.fleet_hit_tokens(),
        "requests_shed": fleet.fleet_shed_total(),
        "reroutes": fleet.reroutes,
        "client_errors": fleet.client_errors(),
        "sessions": len(fleet.sessions),
        "replicas": replicas,
        "sim_seconds": round(fleet.now, 3),
        "ttft_p50_s": round(ttfts[len(ttfts) // 2], 3) if ttfts else None,
        # tail columns (ISSUE 15): the disagg A/B's verdict fields —
        # worst same-replica inter-token gap any client saw, p95 TTFT,
        # and fleet tok/s (the equal-throughput premise the tail win
        # is judged at)
        "ttft_p95_s": (
            round(ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.95))], 3)
            if ttfts else None
        ),
        "max_tpot_excursion_s": round(fleet.max_tpot_excursion(), 3),
        "tok_s": (
            round(total_tokens / fleet.now, 3) if fleet.now else 0.0
        ),
        "total_tokens": total_tokens,
        # bitwise contract: every finished stream equals its replica-
        # independent oracle, wherever (and however often) it re-routed
        "streams_exact": all(
            s.tokens == s.expected_tokens()
            for s in fleet.sessions if s.done
        ),
    }
    if fleet.roles is not None:
        record["roles"] = dict(fleet.roles)
        record.update(
            {f"handoff_{k}": v for k, v in fleet.handoff_totals().items()}
        )
        record["handoffs_orphaned"] = fleet.assembler.stats[
            "handoffs_orphaned"
        ]
    # tiered-pool columns (ISSUE 18): the A/B verdict fields — how much
    # re-teach work eviction burned, and how much the host tier absorbed
    record["evicted_recompute_tokens"] = fleet.fleet_recompute_tokens()
    if any(r.kv_host_blocks > 0 for r in fleet.replicas.values()):
        record["kv_host_hit_tokens"] = fleet.fleet_host_hit_tokens()
        record.update(
            {f"host_{k}": v for k, v in fleet.host_tier_totals().items()}
        )
    return record


# disagg A/B traffic: a short shared prefix (affinity still earns its
# hits) + a LONG unique suffix per session, so every admission is a
# multi-step monolithic prefill the prefix cache cannot absorb — the
# interference the unified leg suffers on every replica
# (prefill_blocking) and the disaggregated fleet removes from its
# decode pool entirely
DISAGG_SPEC = TrafficSpec(
    groups=4,
    sessions_per_group=8,
    prefix_blocks=2,
    suffix_tokens=64,
    max_new_tokens=16,
    wave_size=4,
    ticks_between_waves=3,
)

DISAGG_REPLICA_KWARGS = dict(
    block_size=8,
    slots=4,
    prefill_rate=16,
    num_blocks=512,
    prefill_blocking=True,
    handoff_chunks_per_tick=8,
)


async def run_disagg_leg(
    mode: str,
    spec: TrafficSpec = DISAGG_SPEC,
    *,
    replicas: int = 4,
    pools: Optional[Tuple[int, int]] = None,
    queue_timeout_s: Optional[float] = 16.0,
    kill: Optional[Tuple[str, float]] = None,
    journey_dir: Optional[str] = None,
    **fleet_kwargs: Any,
) -> Dict[str, Any]:
    """One leg of the disaggregated-vs-unified A/B on identical traffic
    and equal total capacity: ``mode="disagg"`` splits ``replicas``
    into prefill/decode pools with KV handoff over the fabric;
    ``mode="unified"`` is the same fleet with every replica doing both
    (the pre-disagg shape). ``kill=(name, at_sim_s)`` crashes one
    replica mid-run — the zero-client-500s criterion under a
    mid-handoff prefill death."""
    kwargs = dict(DISAGG_REPLICA_KWARGS)
    kwargs.update(fleet_kwargs.pop("replica_kwargs", {}))
    roles = None
    if mode == "disagg":
        # default pool split: decode-heavy (the workload is decode-
        # bound once prefill is batched on its own pool — the DeepServe
        # sizing argument); ``pools`` overrides for other traffic mixes
        prefill_pool, decode_pool = pools or (
            max(1, replicas // 4), replicas - max(1, replicas // 4)
        )
        if prefill_pool + decode_pool != replicas:
            raise ValueError("pools must sum to the replica count")
        roles = {"prefill": prefill_pool, "decode": decode_pool}
    elif mode != "unified":
        raise ValueError(f"unknown disagg leg mode {mode!r}")
    fleet = SimFleet(
        replicas,
        policy="affinity",
        roles=roles,
        queue_timeout_s=queue_timeout_s,
        **kwargs,
        **fleet_kwargs,
    )
    await fleet._pump_heartbeats()
    prompts = make_prompts(spec, kwargs["block_size"])
    waves = [
        prompts[i:i + spec.wave_size]
        for i in range(0, len(prompts), spec.wave_size)
    ]
    killed = False
    for wave in waves:
        for prompt in wave:
            fleet.submit(prompt, max_new_tokens=spec.max_new_tokens)
        await fleet.run(spec.ticks_between_waves)
        if kill and not killed and fleet.now >= kill[1]:
            fleet.kill(kill[0])
            killed = True
    await fleet.run_until_idle()
    record = _leg_record(fleet, mode, replicas)
    if kill:
        record["killed_replica"] = kill[0]
    if journey_dir is not None:
        record["journey_artifacts"] = fleet.write_flight_artifacts(
            journey_dir
        )
    return record


# tiered-pool A/B traffic: MORE shared-prefix groups than one replica's
# HBM pool can keep resident, re-arriving in shuffled waves — an
# un-tiered pool evicts a group's prefix between its arrivals and
# re-prefills it (evicted_recompute); the host tier absorbs the same
# evictions as demotions and answers the re-arrival with a promotion
TIERED_SPEC = TrafficSpec(
    groups=8,
    sessions_per_group=8,
    prefix_blocks=8,
    suffix_tokens=8,
    max_new_tokens=8,
    wave_size=8,
    ticks_between_waves=2,
)

TIERED_REPLICA_KWARGS = dict(
    block_size=8,
    slots=4,
    prefill_rate=32,
    num_blocks=40,  # ~half of one replica's share of the prefix set
)


async def run_tiered_leg(
    mode: str,
    spec: TrafficSpec = TIERED_SPEC,
    *,
    replicas: int = 2,
    kv_host_blocks: int = 256,
    queue_timeout_s: Optional[float] = 16.0,
    **fleet_kwargs: Any,
) -> Dict[str, Any]:
    """One leg of the tiered-vs-untiered pool A/B on identical
    pool-pressure traffic: ``mode="tiered"`` gives every replica a
    host-DRAM demotion arena (and gossips its digests, so the router
    prices host hits); ``mode="untiered"`` is the same fleet with the
    HBM-only pool. Judged on the evicted_recompute_tokens cut at
    >=0.9x tok/s."""
    if mode not in ("tiered", "untiered"):
        raise ValueError(f"unknown tiered leg mode {mode!r}")
    kwargs = dict(TIERED_REPLICA_KWARGS)
    kwargs.update(fleet_kwargs.pop("replica_kwargs", {}))
    kwargs["kv_host_blocks"] = kv_host_blocks if mode == "tiered" else 0
    fleet = SimFleet(
        replicas,
        policy="affinity",
        queue_timeout_s=queue_timeout_s,
        **kwargs,
        **fleet_kwargs,
    )
    await fleet._pump_heartbeats()
    prompts = make_prompts(spec, kwargs["block_size"])
    waves = [
        prompts[i:i + spec.wave_size]
        for i in range(0, len(prompts), spec.wave_size)
    ]
    for wave in waves:
        for prompt in wave:
            fleet.submit(prompt, max_new_tokens=spec.max_new_tokens)
        await fleet.run(spec.ticks_between_waves)
    await fleet.run_until_idle()
    return _leg_record(fleet, mode, replicas)


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(
        description="routed-vs-round-robin fleet A/B on simulated traffic"
    )
    parser.add_argument("--replicas", type=int, default=4)
    parser.add_argument("--groups", type=int, default=4)
    parser.add_argument("--sessions-per-group", type=int, default=16)
    parser.add_argument("--seed", type=int, default=1234)
    parser.add_argument(
        "--disagg", action="store_true",
        help="run the prefill/decode disaggregation A/B instead "
             "(bench_fleet_disagg.json vs bench_fleet_unified.json: "
             "role pools + paged-KV handoff over the topic fabric vs "
             "the same capacity unified, judged on max-TPOT-excursion "
             "and p95 TTFT at equal tok/s)",
    )
    parser.add_argument(
        "--tiers", action="store_true",
        help="run the tiered-vs-untiered KV pool A/B instead "
             "(bench_fleet_tiered.json vs bench_fleet_untiered.json: "
             "host-DRAM demotion arenas + tier-tagged gossip vs the "
             "HBM-only pool on identical pool-pressure traffic, judged "
             "on the evicted_recompute_tokens cut at equal tok/s)",
    )
    parser.add_argument(
        "--kv-host-blocks", type=int, default=256,
        help="--tiers: host arena capacity per replica, in blocks",
    )
    parser.add_argument(
        "--out", default="bench_artifacts",
        help="directory for bench_fleet_routed.json / bench_fleet_rr.json "
             "(--disagg: bench_fleet_disagg.json / bench_fleet_unified.json; "
             "--tiers: bench_fleet_tiered.json / bench_fleet_untiered.json)",
    )
    args = parser.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    if args.tiers:
        spec = dataclasses.replace(
            TIERED_SPEC,
            groups=args.groups if args.groups != 4 else TIERED_SPEC.groups,
            sessions_per_group=min(
                args.sessions_per_group, TIERED_SPEC.sessions_per_group
            ),
            seed=args.seed,
        )
        legs = {
            "bench_fleet_tiered.json": "tiered",
            "bench_fleet_untiered.json": "untiered",
        }
        for filename, mode in legs.items():
            record = asyncio.run(run_tiered_leg(
                mode, spec, kv_host_blocks=args.kv_host_blocks,
            ))
            path = os.path.join(args.out, filename)
            with open(path, "w") as handle:
                handle.write(json.dumps(record) + "\n")
            print(json.dumps(record))
        return
    if args.disagg:
        spec = dataclasses.replace(
            DISAGG_SPEC,
            groups=args.groups,
            sessions_per_group=min(
                args.sessions_per_group, DISAGG_SPEC.sessions_per_group
            ),
            seed=args.seed,
        )
        legs = {
            "bench_fleet_disagg.json": "disagg",
            "bench_fleet_unified.json": "unified",
        }
        for filename, mode in legs.items():
            record = asyncio.run(run_disagg_leg(
                mode, spec, replicas=args.replicas,
                # the disagg leg leaves journey flight artifacts next
                # to the A/B record: `langstream-tpu journey <out>`
                # renders its cross-replica waterfalls
                journey_dir=args.out if mode == "disagg" else None,
            ))
            path = os.path.join(args.out, filename)
            with open(path, "w") as handle:
                handle.write(json.dumps(record) + "\n")
            print(json.dumps(record))
        return
    spec = TrafficSpec(
        groups=args.groups,
        sessions_per_group=args.sessions_per_group,
        seed=args.seed,
    )
    legs = {
        "bench_fleet_routed.json": "affinity",
        "bench_fleet_rr.json": "round_robin",
    }
    for filename, policy in legs.items():
        record = asyncio.run(
            run_leg(policy, spec, replicas=args.replicas)
        )
        path = os.path.join(args.out, filename)
        with open(path, "w") as handle:
            handle.write(json.dumps(record) + "\n")
        print(json.dumps(record))


if __name__ == "__main__":
    main()
