"""Prefix-affinity router: send each session to the replica that
already holds its KV prefix.

The paged prefix cache (``providers/jax_local/paged.py``) keys cached
blocks by ``(parent_block, chunk_tokens)`` — chaining through the
parent makes the key collision-free because a chunk's KV depends on the
whole token prefix, which the parent chain uniquely identifies. A
router cannot speak block ids (they are private to one pool), so this
module re-expresses the same chain in a pool-free form: a **rolling
keyed digest** per full block of tokens,

    d_i = blake2b(chunk_tokens_i, key=d_{i-1})        (d_0 keyed empty)

which any front door can compute from the prompt alone and any runner
can compute from its resident chains (:func:`digests_from_keys` walks
the manager's published ``(parent, chunk)`` map). Two chains share a
digest iff they share the entire token prefix — the AIBrix hash-chain
idea (arxiv 2504.03648) with an actual hash because the ids must cross
process boundaries.

Routing (AIBrix/DeepServe shape — prefix-aware first, load-aware
fallback):

1. drop replicas that are **unroutable**: heartbeat older than the
   timeout, state ``degraded``/``rebuilding``/``down`` (the PR 9
   supervisor's 503 becomes a routing signal here, not a client
   error), condemned by :meth:`FleetRouter.mark_unroutable`, or
   draining for scale-down;
2. score each remaining replica by the number of **leading** prompt
   digests present in its advertised chain-digest set (longest cached
   prefix wins — a stale digest can only cost a cache miss, never an
   error);
3. route to the best score; ties and zero-match prompts fall back to
   least queue depth (the router bumps its local queue estimate per
   decision so a burst between heartbeats doesn't dogpile one replica).

Heartbeats are plain dicts (see ``fleet/heartbeat.py`` for the schema
and the topic-fabric pump); :meth:`FleetRouter.observe` applies one,
dropping out-of-order sequence numbers so a delayed heartbeat can
never resurrect a condemned replica or roll back a digest set.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

# record header stamped by fleet-aware front doors (gateway produce
# path) carrying the routing decision to the topic fabric
REPLICA_HEADER = "langstream-replica"

_DIGEST_SIZE = 12  # bytes; 24 hex chars on the wire


def _chunk_digest(parent: bytes, chunk: Sequence[int]) -> bytes:
    data = ",".join(str(int(t)) for t in chunk).encode()
    return hashlib.blake2b(data, digest_size=_DIGEST_SIZE, key=parent).digest()


def prompt_digests(
    tokens: Sequence[int], block_size: int, limit: Optional[int] = None
) -> List[str]:
    """Rolling hash-chain digests for ``tokens``, one per FULL block
    (partial trailing blocks never match, mirroring the manager's
    block-granular admission). ``limit`` caps the chain length — a
    100k-token prompt must not stall the front door."""
    if block_size <= 0:
        return []
    out: List[str] = []
    parent = b""
    blocks = len(tokens) // block_size
    if limit is not None:
        blocks = min(blocks, limit)
    for i in range(blocks):
        parent = _chunk_digest(parent, tokens[i * block_size:(i + 1) * block_size])
        out.append(parent.hex())
    return out


def digests_from_keys(
    keys: Mapping[int, Tuple[int, Tuple[int, ...]]],
    memo: Optional[Dict[int, object]] = None,
) -> Set[str]:
    """Digest set for a manager's published chain map
    (``PagedKVManager.published_keys()``: block -> (parent_block,
    chunk_tokens); parent ``-1`` = chain root). Iterative walk — chains
    can be thousands of blocks deep and must not hit the recursion
    limit.

    ``memo`` (e.g. ``PagedKVManager.digest_memo``) persists digests
    across calls: a block's digest is immutable while it stays
    published, so heartbeat N+1 only hashes chunks published since
    heartbeat N instead of re-hashing the whole pool every beat.
    Entries are stored as ``block -> ((parent, chunk), digest)`` and
    only seeded when the stored key matches this snapshot's key for
    the block — a block id recycled onto a different chain (including
    by a racy write-back after an eviction) fails the match and is
    simply recomputed, never advertised stale. Only real digests are
    persisted — the empty poison marker for a torn snapshot's broken
    ancestry stays call-local, since the ancestor may well be present
    next call."""
    persistent = memo
    local: Dict[int, bytes] = {}
    if persistent is not None:
        for block, entry in persistent.items():
            if not (isinstance(entry, tuple) and len(entry) == 2):
                continue
            key, digest = entry
            if isinstance(digest, bytes) and keys.get(block) == key:
                local[block] = digest
    memo = local

    def resolve(block: int) -> Optional[bytes]:
        stack = [block]
        while stack:
            top = stack[-1]
            if top in memo:
                stack.pop()
                continue
            entry = keys.get(top)
            if entry is None:
                # ancestor missing from the snapshot (capped or torn):
                # the chain below it cannot be keyed — skip it
                memo[top] = b""
                stack.pop()
                continue
            parent, chunk = entry
            if parent >= 0 and parent not in memo:
                stack.append(parent)
                continue
            parent_digest = b"" if parent < 0 else memo[parent]
            if parent >= 0 and not parent_digest:
                memo[top] = b""  # broken ancestry poisons descendants
            else:
                memo[top] = _chunk_digest(parent_digest, chunk)
            stack.pop()
        return memo.get(block) or None

    out: Set[str] = set()
    for block in keys:
        digest = resolve(block)
        if digest:
            out.add(digest.hex())
    if persistent is not None:
        for block, digest in local.items():
            if digest and block in keys:
                # never persist the broken-ancestry marker; key the
                # entry to its chain so recycling invalidates it
                persistent[block] = (keys[block], digest)
    return out


class NoRoutableReplica(Exception):
    """Every known replica is stale, degraded, draining, or condemned."""


@dataclasses.dataclass
class ReplicaState:
    """The router's last-known view of one runner replica."""

    replica_id: str
    seq: int = -1
    epoch: str = ""  # process identity; "" = sender predates the field
    # epochs this replica has ALREADY moved past: a replayed record
    # from a superseded process must read as stale, not as yet another
    # restart (bounded by actual restart count)
    prior_epochs: Set[str] = dataclasses.field(default_factory=set)
    last_seen: float = float("-inf")
    state: str = "serving"  # serving|degraded|rebuilding|down
    # disaggregation pool membership: prefill|decode|unified — set by
    # the replica's own heartbeat (serve --fleet-role); a unified
    # replica serves either leg, which is also every pre-disagg
    # replica's implicit role
    role: str = "unified"
    queue_depth: float = 0.0
    active_sessions: float = 0.0
    block_size: int = 0
    digests: Set[str] = dataclasses.field(default_factory=set)
    # host-DRAM tier (ISSUE 18): chains demoted out of HBM but still
    # promotable without recompute — worth routing to, at a discount
    # (the H2D scatter is cheap next to a cold re-prefill)
    host_digests: Set[str] = dataclasses.field(default_factory=set)
    gauges: Dict[str, float] = dataclasses.field(default_factory=dict)
    draining: bool = False
    condemned_at_seq: Optional[int] = None
    condemn_reason: str = ""

    def fresh(self, now: float, timeout: float) -> bool:
        return now - self.last_seen <= timeout

    def routable(self, now: float, timeout: float) -> bool:
        return (
            self.state == "serving"
            and self.fresh(now, timeout)
            and not self.draining
            and self.condemned_at_seq is None
        )


@dataclasses.dataclass
class RouteDecision:
    replica_id: str
    policy: str            # affinity | least_queue | round_robin
    matched_blocks: int = 0
    matched_tokens: int = 0
    # of matched_blocks, how many the chosen replica holds only in its
    # host-DRAM tier (promotion, not a free HBM hit)
    matched_host_blocks: int = 0


class FleetRouter:
    """Prefix-affinity router over a heartbeat-fed replica view.

    Thread-safe: the gateway observes heartbeats from a consumer task
    while request handlers route concurrently. ``policy`` selects the
    production behavior (``affinity``) or the A/B baseline
    (``round_robin`` — blind cycling, the pre-fleet gateway shape).
    """

    def __init__(
        self,
        *,
        policy: str = "affinity",
        heartbeat_timeout_s: float = 10.0,
    ) -> None:
        if policy not in ("affinity", "round_robin"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self.policy = policy
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        # the replica view and routing counters: heartbeats (consumer
        # task) and request handlers touch them concurrently, so every
        # read AND write holds the lock (prompt hashing stays outside
        # it — see route())
        self.replicas: Dict[str, ReplicaState] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._rr = 0  # guarded-by: _lock
        self._routed: Dict[str, int] = {  # guarded-by: _lock
            "affinity": 0, "least_queue": 0, "round_robin": 0,
            "sticky": 0,
        }
        self._matched_tokens = 0  # guarded-by: _lock
        self._matched_host_tokens = 0  # guarded-by: _lock
        self._sticky_stale = 0  # guarded-by: _lock

    # ------------------------------------------------------------------ #
    # heartbeat view
    # ------------------------------------------------------------------ #
    def observe(self, heartbeat: Mapping[str, object], now: Optional[float] = None) -> bool:
        """Apply one heartbeat dict; returns False when dropped
        (unknown shape or out-of-order seq). A heartbeat never throws:
        a malformed gossip record must not take the router down."""
        now = time.monotonic() if now is None else now
        replica_id = heartbeat.get("replica")
        if not isinstance(replica_id, str) or not replica_id:
            return False
        seq = int(heartbeat.get("seq", 0) or 0)
        epoch = str(heartbeat.get("epoch", "") or "")
        with self._lock:
            state = self.replicas.get(replica_id)
            if state is None:
                state = ReplicaState(replica_id=replica_id)
                self.replicas[replica_id] = state
            if epoch and epoch != state.epoch and epoch in state.prior_epochs:
                return False  # replayed record from a superseded process
            if epoch and state.epoch and epoch != state.epoch:
                # PROVABLY a different process (pod restart): the old
                # epoch's seq numbering, condemnation, and drain mark
                # die with it (StatefulSets reuse ordinals — a re-grown
                # replica must not inherit its predecessor's drain)
                state.prior_epochs.add(state.epoch)
                state.condemned_at_seq = None
                state.draining = False
            elif seq <= state.seq:
                # out-of-order gossip never rolls a LIVE view back, and
                # a SAME-epoch lower seq is provably a replay of this
                # very process's past records — dead-pod replays must
                # not mark a stale replica serving again
                if epoch and epoch == state.epoch:
                    return False
                if state.fresh(now, self.heartbeat_timeout_s):
                    return False
                # epoch-less sender, stale view: accept as a possible
                # restart — but the condemnation is REBASED, not
                # cleared: an at-least-once transport can replay a dead
                # replica's last heartbeats, and only a live stream (a
                # subsequent NEWER-seq serving beat) may resurrect a
                # condemned replica
                if state.condemned_at_seq is not None:
                    state.condemned_at_seq = seq
            state.epoch = epoch or state.epoch
            state.seq = seq
            state.last_seen = now
            state.state = str(heartbeat.get("state", "serving"))
            state.role = str(heartbeat.get("role", "") or "unified")
            state.queue_depth = float(heartbeat.get("queue_depth", 0) or 0)
            state.active_sessions = float(
                heartbeat.get("active_sessions", 0) or 0
            )
            state.block_size = int(heartbeat.get("block_size", 0) or 0)
            digests = heartbeat.get("chain_digests")
            if isinstance(digests, (list, set, tuple)):
                # full replacement, not a merge: evicted chains age out
                # of scoring with the next heartbeat
                state.digests = {str(d) for d in digests}
            host_digests = heartbeat.get("host_chain_digests")
            if isinstance(host_digests, (list, set, tuple)):
                # same replacement rule for the host tier; pre-tier
                # senders simply never carry the field
                state.host_digests = {str(d) for d in host_digests}
            gauges = heartbeat.get("gauges")
            if isinstance(gauges, Mapping):
                state.gauges = {
                    str(k): float(v) for k, v in gauges.items()
                    if isinstance(v, (int, float))
                }
            # a replica that healed (supervisor rebuild finished) clears
            # its condemnation by gossiping serving at a NEWER seq
            if (
                state.condemned_at_seq is not None
                and seq > state.condemned_at_seq
                and state.state == "serving"
            ):
                state.condemned_at_seq = None
        return True

    def mark_unroutable(self, replica_id: str, reason: str = "condemned") -> None:
        """Condemn a replica immediately (gateway saw 503/refused, the
        supervisor reported degraded): stop routing new sessions there
        until a NEWER serving heartbeat arrives."""
        with self._lock:
            state = self.replicas.setdefault(
                replica_id, ReplicaState(replica_id=replica_id)
            )
            state.condemned_at_seq = state.seq
            state.condemn_reason = reason

    def mark_draining(self, replica_id: str, draining: bool = True) -> None:
        """Scale-down drain: stop routing NEW sessions; in-flight ones
        finish on the replica (prefix chains age out with them)."""
        with self._lock:
            state = self.replicas.setdefault(
                replica_id, ReplicaState(replica_id=replica_id)
            )
            state.draining = draining

    def forget(self, replica_id: str) -> None:
        with self._lock:
            self.replicas.pop(replica_id, None)

    def routable(self, now: Optional[float] = None) -> List[ReplicaState]:
        now = time.monotonic() if now is None else now
        with self._lock:
            return [
                s for s in self.replicas.values()
                if s.routable(now, self.heartbeat_timeout_s)
            ]

    def snapshot_states(self) -> List[ReplicaState]:
        """Lock-held snapshot of the replica view, id-sorted — what
        out-of-band readers (the autoscaler loop) must iterate instead
        of ``.replicas`` so a concurrent heartbeat insert can't blow up
        their iteration."""
        with self._lock:
            return sorted(
                self.replicas.values(), key=lambda s: s.replica_id
            )

    def state_of(self, replica_id: str) -> Optional[ReplicaState]:
        with self._lock:
            return self.replicas.get(replica_id)

    # ------------------------------------------------------------------ #
    # routing
    # ------------------------------------------------------------------ #
    def route(
        self,
        prompt_tokens: Optional[Sequence[int]] = None,
        now: Optional[float] = None,
        *,
        role: Optional[str] = None,
        session_replica: Optional[str] = None,
    ) -> RouteDecision:
        """Pick a replica for a new session. Raises
        :class:`NoRoutableReplica` when the whole fleet is unroutable —
        the caller's 503-with-Retry-After moment.

        ``role`` narrows to one disaggregation pool (``prefill`` /
        ``decode``): candidates of that role are preferred, with
        ``unified`` replicas as the fallback when the pool is empty or
        wholly unroutable, and any routable replica as the last resort
        — a fleet that never configured roles routes exactly as
        before, and a role-aware caller never dead-ends on a role.

        ``session_replica`` is the stickiness pin: the stamped
        ``langstream-replica`` header from the reply that served this
        session. A warm follow-up's KV lives on that replica NOW, but
        its chain digests may not have gossiped yet (publish-at-finish
        beats the next heartbeat by up to a full interval), so the pin
        outranks digest scoring — with a staleness fallback: a pinned
        replica that is condemned, draining, stale, or unknown drops
        the pin and the follow-up re-enters normal scoring (a cache
        miss at worst, never a dead-end)."""
        now = time.monotonic() if now is None else now
        if session_replica is not None:
            with self._lock:
                pinned = self.replicas.get(session_replica)
                if pinned is not None and pinned.routable(
                    now, self.heartbeat_timeout_s
                ):
                    pinned.queue_depth += 1.0
                    self._routed["sticky"] = (
                        self._routed.get("sticky", 0) + 1
                    )
                    return RouteDecision(pinned.replica_id, "sticky")
                self._sticky_stale += 1
        # hash OUTSIDE the lock: the digest chain is O(prompt) blake2b
        # work, and holding the router-wide lock for it would serialize
        # every concurrent route/observe/gauges behind one request.
        # Chains are per-decision only — a cross-call cache keyed on a
        # token prefix would hand one prompt another's chain.
        chains: Dict[int, List[str]] = {}
        if prompt_tokens is not None and self.policy == "affinity":
            with self._lock:
                sizes = {
                    s.block_size for s in self.replicas.values()
                    if s.block_size > 0
                    and (s.digests or s.host_digests)
                    and s.routable(now, self.heartbeat_timeout_s)
                }
            for block_size in sizes:
                chains[block_size] = prompt_digests(
                    prompt_tokens, block_size, limit=512
                )
        with self._lock:
            candidates = [
                s for s in self.replicas.values()
                if s.routable(now, self.heartbeat_timeout_s)
            ]
            if role is not None:
                pool = [s for s in candidates if s.role == role]
                if not pool:
                    # no live replica of this role: unified replicas
                    # absorb the leg (and an un-roled fleet is ALL
                    # unified, so disagg-aware callers degrade cleanly)
                    pool = [s for s in candidates if s.role == "unified"]
                # last resort — both the role pool and the unified tier
                # are empty: route to ANYONE routable. Deliberate
                # availability-over-purity: a cold prefill on a decode
                # replica costs a TPOT excursion; an unplaceable
                # session costs the client a 503 (test-pinned in
                # test_router_routes_by_role_with_unified_fallback)
                candidates = pool or candidates
            if not candidates:
                raise NoRoutableReplica(
                    f"no routable replica among {sorted(self.replicas)}"
                )
            candidates.sort(key=lambda s: s.replica_id)
            if self.policy == "round_robin":
                chosen = candidates[self._rr % len(candidates)]
                self._rr += 1
                decision = RouteDecision(chosen.replica_id, "round_robin")
            else:
                best, best_score = None, -1.0
                best_hbm, best_host = 0, 0
                for state in candidates:
                    score, hbm, host = 0.0, 0, 0
                    # a block size that appeared between the two lock
                    # sections simply scores 0 this decision
                    chain = chains.get(state.block_size)
                    if chain and (state.digests or state.host_digests):
                        # tier pricing (ISSUE 18): an HBM-resident block
                        # is a free hit, a host-tier block still pays
                        # the H2D promote — hbm-hit > host-hit > cold,
                        # so a full HBM chain beats the same chain
                        # demoted, but a demoted chain still beats any
                        # replica that would cold-prefill it
                        for digest in chain:
                            if digest in state.digests:
                                score += 1.0
                                hbm += 1
                            elif digest in state.host_digests:
                                score += 0.5
                                host += 1
                            else:
                                break
                    if score > best_score or (
                        score == best_score
                        and best is not None
                        and state.queue_depth < best.queue_depth
                    ):
                        best, best_score = state, score
                        best_hbm, best_host = hbm, host
                assert best is not None
                chosen = best
                if best_score > 0:
                    decision = RouteDecision(
                        chosen.replica_id, "affinity",
                        matched_blocks=best_hbm + best_host,
                        matched_tokens=(
                            (best_hbm + best_host) * chosen.block_size
                        ),
                        matched_host_blocks=best_host,
                    )
                else:
                    decision = RouteDecision(chosen.replica_id, "least_queue")
            # local estimate bump: a burst routed between heartbeats
            # spreads instead of dogpiling the momentarily-least-loaded
            chosen.queue_depth += 1.0
            self._routed[decision.policy] = (
                self._routed.get(decision.policy, 0) + 1
            )
            self._matched_tokens += decision.matched_tokens
            self._matched_host_tokens += (
                decision.matched_host_blocks * chosen.block_size
            )
            return decision

    # ------------------------------------------------------------------ #
    # metrics
    # ------------------------------------------------------------------ #
    def gauges(self, now: Optional[float] = None) -> Dict[str, float]:
        """Fleet gauges in the inline-label form the shared renderer
        (``api/metrics.prometheus_text``) already speaks — served by
        the gateway's /metrics and read by ``langstream-tpu top``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            out: Dict[str, float] = {}
            routed = sum(self._routed.values())
            for policy, count in sorted(self._routed.items()):
                out[f'fleet_routed_total{{policy="{policy}"}}'] = float(count)
            if self.policy == "affinity":
                out["fleet_affinity_hit_rate"] = round(
                    self._routed["affinity"] / routed, 4
                ) if routed else 0.0
                out["fleet_prefix_match_tokens_total"] = float(
                    self._matched_tokens
                )
                out["fleet_host_match_tokens_total"] = float(
                    self._matched_host_tokens
                )
            # session stickiness: pins honored ride the policy="sticky"
            # routed counter above; this is the fallback leg (pin was
            # stale/condemned/unknown → digest scoring took over)
            out["fleet_sticky_fallbacks_total"] = float(self._sticky_stale)
            routable = 0
            for state in sorted(
                self.replicas.values(), key=lambda s: s.replica_id
            ):
                label = f'{{replica="{state.replica_id}"}}'
                out[f"fleet_replica_queue_depth{label}"] = float(
                    state.queue_depth
                )
                if state.role != "unified":
                    out[
                        f'fleet_replica_role{{replica='
                        f'"{state.replica_id}",role="{state.role}"}}'
                    ] = 1.0
                if state.routable(now, self.heartbeat_timeout_s):
                    display, routable = "serving", routable + 1
                elif state.draining:
                    display = "draining"
                elif not state.fresh(now, self.heartbeat_timeout_s):
                    display = "stale"
                elif state.condemned_at_seq is not None:
                    display = "condemned"
                else:
                    display = state.state
                out[
                    f'fleet_replica_state{{replica="{state.replica_id}",'
                    f'state="{display}"}}'
                ] = 1.0
            out["fleet_replicas_known"] = float(len(self.replicas))
            out["fleet_replicas_routable"] = float(routable)
            return out
