"""Fleet layer: N independent runner pods become one scheduled fleet.

Three pieces, all CPU-verifiable (memory topics + MockKubeApi):

- :mod:`~langstream_tpu.fleet.router` — prefix-affinity routing over
  gossiped hash-chain digests (the paged prefix cache's
  ``(parent_block, chunk)`` chaining re-expressed as rolling keyed
  digests that cross process boundaries), with least-queue-depth
  fallback and degraded/condemned/draining replicas taken out of
  rotation.
- :mod:`~langstream_tpu.fleet.autoscaler` — SLO burn rates + queue
  depth + shed deltas → hysteretic replica-count decisions actuated
  through ``Operator.scale`` (drain-then-shrink on the way down).
- :mod:`~langstream_tpu.fleet.sim` — M fake engines with REAL paged
  prefix caches behind memory topics; the acceptance instrument for
  affinity-vs-round-robin hit tokens, kill-mid-stream re-routing, and
  scale-up/down behavior (``tests/test_fleet.py``), plus the
  ``bench_fleet_*.json`` A/B artifacts ``tools/ab_analyze.py`` digests.

See ``docs/fleet.md`` for the heartbeat schema, scoring, and policy.
"""

from __future__ import annotations

from typing import Dict, Optional

from langstream_tpu.fleet.autoscaler import (  # noqa: F401
    AutoscaleDecision,
    AutoscalePolicy,
    SLOAutoscaler,
)
from langstream_tpu.fleet.handoff import (  # noqa: F401
    HANDOFF_TOPIC,
    HandoffAssembler,
    handoff_records,
    manifest_for_request,
)
from langstream_tpu.fleet.router import (  # noqa: F401
    REPLICA_HEADER,
    FleetRouter,
    NoRoutableReplica,
    RouteDecision,
    digests_from_keys,
    prompt_digests,
)


class FleetController:
    """Router + optional autoscaler behind one face: the object a
    front door (gateway, OpenAI server) registers to get routing
    decisions and a single merged ``gauges()`` for its /metrics."""

    def __init__(
        self,
        router: FleetRouter,
        autoscaler: Optional[SLOAutoscaler] = None,
        *,
        replicas_current=None,
    ) -> None:
        self.router = router
        self.autoscaler = autoscaler
        # zero-arg callable returning the actuated replica count (e.g.
        # a StatefulSet spec read); None = report the router's view
        self._replicas_current = replicas_current

    def route(self, prompt_tokens=None, now=None, **kwargs) -> RouteDecision:
        """Routing pass-through; ``role=`` / ``session_replica=`` ride
        the kwargs (prefill/decode pool selection + session
        stickiness, :meth:`FleetRouter.route`)."""
        return self.router.route(prompt_tokens, now=now, **kwargs)

    def gauges(self, now: Optional[float] = None) -> Dict[str, float]:
        out = self.router.gauges(now=now)
        if self._replicas_current is not None:
            out["fleet_replicas_current"] = float(self._replicas_current())
        else:
            out["fleet_replicas_current"] = out.get(
                "fleet_replicas_known", 0.0
            )
        if self.autoscaler is not None:
            out.update(self.autoscaler.gauges())
        return out
