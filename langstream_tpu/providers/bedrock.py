"""AWS Bedrock provider (SigV4 REST, no boto3).

Reference: ``langstream-agents/langstream-ai-agents/src/main/java/ai/
langstream/ai/agents/services/impl/BedrockServiceProvider.java:47`` —
resources of type ``bedrock-configuration`` with ``access-key`` /
``secret-key`` / ``region``; completions call InvokeModel with
model-family-specific request parameters and read the completion out of
the response with a configurable expression. The TPU build signs the
request natively (``aws_sign.py``) instead of pulling in an SDK.

Config keys:

- ``access-key`` / ``secret-key`` / ``region`` (+ optional
  ``session-token``)
- ``endpoint-override`` — full base URL (tests; VPC endpoints)

Completion options (per step configuration):

- ``model``                — Bedrock modelId (used in the URL)
- ``request-parameters``   — dict merged into the request body
- ``response-completions-path`` — dotted path to the completion text;
  when unset, common fields are tried (``completion``, ``generation``,
  ``outputs[0].text``, ``content[0].text``, ``results[0].outputText``).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from langstream_tpu.api.service import (
    ChatChunk,
    ChatCompletionResult,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    StreamingChunksConsumer,
)
from langstream_tpu.providers.aws_sign import sign_request


def _dig(payload: Any, path: str) -> Any:
    """Dotted-path lookup with [n] indexing: ``outputs[0].text``."""
    node = payload
    for raw in path.split("."):
        part = raw
        while part:
            if "[" in part:
                name, _, rest = part.partition("[")
                index, _, part = rest.partition("]")
                if name:
                    node = node[name]
                node = node[int(index)]
            else:
                node = node[part]
                part = ""
    return node


_DEFAULT_PATHS = (
    "completion",                  # anthropic (legacy)
    "content[0].text",             # anthropic messages
    "generation",                  # meta llama
    "outputs[0].text",             # mistral
    "results[0].outputText",       # amazon titan
)


class BedrockCompletionsService(CompletionsService):
    def __init__(self, config: Dict[str, Any]) -> None:
        self.region = config.get("region", "us-east-1")
        self.access_key = config.get("access-key", "")
        self.secret_key = config.get("secret-key", "")
        self.session_token = config.get("session-token")
        self.endpoint = (
            config.get("endpoint-override")
            or f"https://bedrock-runtime.{self.region}.amazonaws.com"
        ).rstrip("/")
        self._session = None

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def _invoke(self, model: str, body: Dict[str, Any]) -> Dict[str, Any]:
        payload = json.dumps(body).encode()
        url = f"{self.endpoint}/model/{model}/invoke"
        headers = sign_request(
            method="POST", url=url, region=self.region,
            service="bedrock", access_key=self.access_key,
            secret_key=self.secret_key, body=payload,
            headers={"content-type": "application/json"},
            session_token=self.session_token,
        )
        session = await self._get_session()
        async with session.post(url, data=payload, headers=headers) as resp:
            text = await resp.text()
            if resp.status >= 300:
                raise IOError(f"bedrock invoke HTTP {resp.status}: {text[:500]}")
            return json.loads(text)

    @staticmethod
    def _render_prompt(messages: List[ChatMessage]) -> str:
        return "\n".join(
            f"{m.role}: {m.content}" if m.role else m.content
            for m in messages
        )

    async def get_chat_completions(
        self,
        messages: List[ChatMessage],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        model = options.get("model")
        if not model:
            raise ValueError("bedrock completions require a 'model' id")
        body = dict(options.get("request-parameters") or {})
        if "messages" in body:
            body["messages"] = [
                {"role": m.role, "content": m.content} for m in messages
            ]
        else:
            body.setdefault("prompt", self._render_prompt(messages))
        if options.get("max-tokens") and "max_tokens" not in body:
            body["max_tokens"] = options["max-tokens"]
        payload = await self._invoke(model, body)
        path = options.get("response-completions-path")
        if path:
            content = str(_dig(payload, path))
        else:
            content = None
            for candidate in _DEFAULT_PATHS:
                try:
                    content = str(_dig(payload, candidate))
                    break
                except (KeyError, IndexError, TypeError):
                    continue
            if content is None:
                raise ValueError(
                    "could not locate the completion in the Bedrock "
                    f"response (keys: {sorted(payload)}); set "
                    "'response-completions-path'"
                )
        if stream_consumer is not None:
            # Bedrock invoke is non-streaming here: emit one final chunk
            stream_consumer.consume_chunk(
                "bedrock", 0, ChatChunk(content=content, index=0), last=True
            )
        return ChatCompletionResult(
            content=content,
            finish_reason="stop",
            prompt_tokens=0,
            completion_tokens=0,
        )

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()


class BedrockEmbeddingsService(EmbeddingsService):
    def __init__(self, completions: BedrockCompletionsService, model: str):
        self._svc = completions
        self.model = model or "amazon.titan-embed-text-v1"

    async def compute_embeddings(self, texts: List[str]) -> List[List[float]]:
        out: List[List[float]] = []
        for text in texts:
            payload = await self._svc._invoke(  # noqa: SLF001 — same client
                self.model, {"inputText": text}
            )
            out.append(payload.get("embedding") or payload["embeddings"][0])
        return out

    async def close(self) -> None:
        await self._svc.close()


class BedrockServiceProvider(ServiceProvider):
    name = "bedrock"

    def supports(self, resource_config: Dict[str, Any]) -> bool:
        return (
            resource_config.get("type") == "bedrock-configuration"
            or "bedrock" in resource_config
        )

    def get_completions_service(
        self, resource_config: Dict[str, Any]
    ) -> CompletionsService:
        return BedrockCompletionsService(
            resource_config.get("configuration", resource_config)
        )

    def get_embeddings_service(
        self, resource_config: Dict[str, Any], model: Optional[str] = None
    ) -> EmbeddingsService:
        return BedrockEmbeddingsService(
            BedrockCompletionsService(
                resource_config.get("configuration", resource_config)
            ),
            model,
        )
