"""AI service providers.

Equivalent of the reference's provider set under
``langstream-agents/langstream-ai-agents`` (OpenAI / HuggingFace / VertexAI /
Bedrock, resolved through ``ServiceProviderRegistry``). Here the flagship is
``jax_local`` — in-process JAX/XLA inference on the TPU attached to the
runner — plus an OpenAI-compatible REST client (for remote fallback parity)
and a deterministic mock for tests.
"""

from langstream_tpu.providers.registry import (
    ServiceProviderRegistry,
    default_registry,
    register_provider,
)

__all__ = ["ServiceProviderRegistry", "default_registry", "register_provider"]
