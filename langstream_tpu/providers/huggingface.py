"""HuggingFace provider: local torch/transformers embeddings or the HF
inference REST API.

Parity with the reference's ``HuggingFaceProvider``
(``langstream-agents/langstream-ai-agents/.../HuggingFaceProvider.java:47``):
``provider: local`` loads a sentence-transformer-style model in-process
(the reference uses DJL/PyTorch JNI; here plain transformers on CPU —
the TPU-native embedding path lives in ``jax_local``), ``provider: api``
calls the hosted inference API.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from langstream_tpu.api.service import (
    ChatCompletionResult,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    StreamingChunksConsumer,
)


class LocalTransformersEmbeddingsService(EmbeddingsService):
    """CPU embeddings via transformers/torch (mean-pooled, normalized) —
    the BASELINE config #1 path (all-MiniLM-L6-v2 on CPU)."""

    def __init__(self, config: Dict[str, Any], model: Optional[str]) -> None:
        self.model_name = model or config.get(
            "model", "sentence-transformers/all-MiniLM-L6-v2"
        )
        self._model = None
        self._tokenizer = None

    def _load(self):
        if self._model is None:
            import torch  # noqa: F401
            from transformers import AutoModel, AutoTokenizer

            self._tokenizer = AutoTokenizer.from_pretrained(self.model_name)
            self._model = AutoModel.from_pretrained(self.model_name)
            self._model.eval()
        return self._model, self._tokenizer

    async def compute_embeddings(self, texts: List[str]) -> List[List[float]]:
        import asyncio

        return await asyncio.get_running_loop().run_in_executor(
            None, self._compute_sync, texts
        )

    def _compute_sync(self, texts: List[str]) -> List[List[float]]:
        import torch

        model, tokenizer = self._load()
        encoded = tokenizer(
            texts, padding=True, truncation=True, max_length=512, return_tensors="pt"
        )
        with torch.no_grad():
            output = model(**encoded)
        hidden = output.last_hidden_state
        mask = encoded["attention_mask"].unsqueeze(-1).to(hidden.dtype)
        pooled = (hidden * mask).sum(1) / mask.sum(1).clamp(min=1e-9)
        normalized = torch.nn.functional.normalize(pooled, p=2, dim=1)
        return normalized.tolist()


class HFAPIEmbeddingsService(EmbeddingsService):
    def __init__(self, config: Dict[str, Any], model: Optional[str]) -> None:
        self.model = model or config.get("model", "sentence-transformers/all-MiniLM-L6-v2")
        self.url = config.get(
            "api-url", "https://api-inference.huggingface.co/pipeline/feature-extraction"
        ).rstrip("/")
        self.access_key = config.get("access-key", "")
        self._session = None

    async def compute_embeddings(self, texts: List[str]) -> List[List[float]]:
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                headers={"Authorization": f"Bearer {self.access_key}"}
            )
        async with self._session.post(
            f"{self.url}/{self.model}", json={"inputs": texts}
        ) as response:
            response.raise_for_status()
            return await response.json()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class _UnsupportedCompletions(CompletionsService):
    async def get_chat_completions(
        self,
        messages: List[ChatMessage],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        raise NotImplementedError(
            "hugging-face resources provide embeddings only (as in the "
            "reference); use jax-local or open-ai for completions"
        )


class HuggingFaceServiceProvider(ServiceProvider):
    name = "hugging-face"

    def supports(self, resource_config: Dict[str, Any]) -> bool:
        return (
            resource_config.get("type")
            in ("hugging-face", "hugging-face-configuration")
            or "hugging-face" in resource_config
        )

    def get_completions_service(self, resource_config: Dict[str, Any]) -> CompletionsService:
        return _UnsupportedCompletions()

    def get_embeddings_service(
        self, resource_config: Dict[str, Any], model: Optional[str] = None
    ) -> EmbeddingsService:
        if resource_config.get("provider", "local") == "api":
            return HFAPIEmbeddingsService(resource_config, model)
        return LocalTransformersEmbeddingsService(resource_config, model)
