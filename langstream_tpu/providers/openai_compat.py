"""OpenAI-compatible REST provider (remote fallback path).

Parity with the reference's ``OpenAIServiceProvider``
(``langstream-agents/langstream-ai-agents/.../OpenAIServiceProvider.java:26``,
``OpenAICompletionService.java:52``): resources of type
``open-ai-configuration`` (or with an ``open-ai`` key) talk to any
OpenAI-compatible endpoint (OpenAI, Azure, vLLM, llama.cpp server, ...) over
HTTPS with SSE streaming. In the TPU build this is the *fallback* — the
flagship path is ``jax-local``, which serves the same SPI in-process.
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Dict, List, Optional

from langstream_tpu.api.service import (
    ChatChunk,
    ChatCompletionResult,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    StreamingChunksConsumer,
)


class OpenAICompatCompletionsService(CompletionsService):
    def __init__(self, config: Dict[str, Any]) -> None:
        self.url = (config.get("url") or "https://api.openai.com/v1").rstrip("/")
        self.access_key = config.get("access-key", "")
        self.default_model = config.get("model")
        self._session = None

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                headers={"Authorization": f"Bearer {self.access_key}"}
            )
        return self._session

    async def get_chat_completions(
        self,
        messages: List[ChatMessage],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        body: Dict[str, Any] = {
            "model": options.get("model", self.default_model),
            "messages": [{"role": m.role, "content": m.content} for m in messages],
            "stream": stream_consumer is not None,
        }
        return await self._request_completion(
            "chat/completions", body,
            lambda choice: (
                choice.get("delta", choice.get("message", {})) or {}
            ).get("content"),
            options, stream_consumer,
        )

    async def get_text_completions(
        self,
        prompt: List[str],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        """Legacy /completions endpoint: the prompt continues verbatim
        (reference: OpenAICompletionService.getTextCompletions)."""
        body: Dict[str, Any] = {
            "model": options.get("model", self.default_model),
            "prompt": "".join(prompt),
            "stream": stream_consumer is not None,
        }
        return await self._request_completion(
            "completions", body,
            lambda choice: choice.get("text"),
            options, stream_consumer,
        )

    # options forwarded verbatim to the OpenAI body (dashes -> underscores)
    FORWARDED_OPTIONS = (
        "max-tokens", "temperature", "top-p", "stop",
        "presence-penalty", "frequency-penalty", "seed", "logit-bias",
    )

    async def _request_completion(
        self,
        path: str,
        body: Dict[str, Any],
        extract_content,
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer],
    ) -> ChatCompletionResult:
        """Shared request path for chat and text completions; only the
        endpoint and the per-choice content extractor differ."""
        session = await self._get_session()
        for key in self.FORWARDED_OPTIONS:
            if options.get(key) is not None:
                body[key.replace("-", "_")] = options[key]
        endpoint = f"{self.url}/{path}"
        if stream_consumer is None:
            async with session.post(endpoint, json=body) as response:
                response.raise_for_status()
                payload = await response.json()
            choice = payload["choices"][0]
            usage = payload.get("usage", {})
            return ChatCompletionResult(
                content=extract_content(choice) or "",
                finish_reason=choice.get("finish_reason", "stop"),
                prompt_tokens=usage.get("prompt_tokens", 0),
                completion_tokens=usage.get("completion_tokens", 0),
            )
        # SSE streaming
        answer_id = uuid.uuid4().hex
        parts: List[str] = []
        index = 0
        last_emitted = False
        async with session.post(endpoint, json=body) as response:
            response.raise_for_status()
            async for raw_line in response.content:
                line = raw_line.decode("utf-8").strip()
                if not line.startswith("data:"):
                    continue
                data = line[len("data:"):].strip()
                if data == "[DONE]":
                    break
                event = json.loads(data)
                choices = event.get("choices") or []
                if not choices:
                    continue  # e.g. bare usage frames
                choice = choices[0]
                content = extract_content(choice)
                finished = choice.get("finish_reason") is not None
                if content:
                    parts.append(content)
                    stream_consumer.consume_chunk(
                        answer_id, index,
                        ChatChunk(content=content, index=index),
                        last=finished,
                    )
                    index += 1
                    last_emitted = finished
                elif finished:
                    stream_consumer.consume_chunk(
                        answer_id, index,
                        ChatChunk(content="", index=index), last=True,
                    )
                    last_emitted = True
        if not last_emitted:
            # servers that end with bare [DONE] (no finish_reason event):
            # flush the terminal marker so chunk batchers drain their tail
            stream_consumer.consume_chunk(
                answer_id, index, ChatChunk(content="", index=index), last=True
            )
        return ChatCompletionResult(content="".join(parts))

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class OpenAICompatEmbeddingsService(EmbeddingsService):
    def __init__(self, config: Dict[str, Any], model: Optional[str]) -> None:
        self.url = (config.get("url") or "https://api.openai.com/v1").rstrip("/")
        self.access_key = config.get("access-key", "")
        self.model = model or config.get("embeddings-model", "text-embedding-3-small")
        self._session = None

    async def compute_embeddings(self, texts: List[str]) -> List[List[float]]:
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                headers={"Authorization": f"Bearer {self.access_key}"}
            )
        async with self._session.post(
            f"{self.url}/embeddings", json={"model": self.model, "input": texts}
        ) as response:
            response.raise_for_status()
            payload = await response.json()
        data = sorted(payload["data"], key=lambda d: d["index"])
        return [d["embedding"] for d in data]

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class OpenAICompatServiceProvider(ServiceProvider):
    name = "open-ai"

    def supports(self, resource_config: Dict[str, Any]) -> bool:
        return (
            resource_config.get("type") in ("open-ai", "open-ai-configuration")
            or "open-ai" in resource_config
        )

    def get_completions_service(self, resource_config: Dict[str, Any]) -> CompletionsService:
        return OpenAICompatCompletionsService(resource_config)

    def get_embeddings_service(
        self, resource_config: Dict[str, Any], model: Optional[str] = None
    ) -> EmbeddingsService:
        return OpenAICompatEmbeddingsService(resource_config, model)
