"""Deterministic mock AI provider for tests and pipeline dry-runs.

Owns ``resources:`` entries of type ``mock-ai`` (or with a ``mock-ai:`` key).
Completions echo a configurable template; embeddings are deterministic
hash-seeded unit vectors — so integration tests of the full pipeline
(the reference mocks provider HTTP with WireMock in ``ChatCompletionsIT``;
here the mock sits behind the same ServiceProvider SPI instead).
"""

from __future__ import annotations

import asyncio
import hashlib
import math
import uuid
from typing import Any, Dict, List, Optional

from langstream_tpu.api.service import (
    ChatChunk,
    ChatCompletionResult,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    StreamingChunksConsumer,
)


class MockCompletionsService(CompletionsService):
    def __init__(self, config: Dict[str, Any]) -> None:
        # template may use {prompt} (last user message) and {model}
        self.template = config.get("response-template", "echo: {prompt}")
        self.chunk_words = int(config.get("chunk-words", 1))
        self.delay = float(config.get("chunk-delay", 0.0))

    async def get_chat_completions(
        self,
        messages: List[ChatMessage],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        prompt = messages[-1].content if messages else ""
        text = self.template.format(prompt=prompt, model=options.get("model", ""))
        if stream_consumer is not None:
            answer_id = uuid.uuid4().hex
            words = text.split(" ")
            chunks = [
                " ".join(words[i : i + self.chunk_words])
                for i in range(0, len(words), self.chunk_words)
            ]
            for index, chunk in enumerate(chunks):
                if self.delay:
                    await asyncio.sleep(self.delay)
                content = chunk if index == 0 else " " + chunk
                stream_consumer.consume_chunk(
                    answer_id,
                    index,
                    ChatChunk(content=content, index=index),
                    last=index == len(chunks) - 1,
                )
        return ChatCompletionResult(
            content=text,
            prompt_tokens=sum(len(m.content.split()) for m in messages),
            completion_tokens=len(text.split()),
        )


class MockEmbeddingsService(EmbeddingsService):
    def __init__(self, config: Dict[str, Any], model: Optional[str]) -> None:
        self.dimensions = int(config.get("dimensions", 8))
        self.model = model
        self.calls: List[List[str]] = []  # visible to tests: batch shapes

    async def compute_embeddings(self, texts: List[str]) -> List[List[float]]:
        self.calls.append(list(texts))
        out = []
        for text in texts:
            digest = hashlib.sha256(text.encode("utf-8")).digest()
            vector = [
                (digest[i % len(digest)] - 127.5) / 127.5
                for i in range(self.dimensions)
            ]
            norm = math.sqrt(sum(v * v for v in vector)) or 1.0
            out.append([v / norm for v in vector])
        return out


class MockServiceProvider(ServiceProvider):
    name = "mock-ai"

    def supports(self, resource_config: Dict[str, Any]) -> bool:
        return (
            resource_config.get("type") in ("mock-ai", "mock")
            or "mock-ai" in resource_config
        )

    def get_completions_service(self, resource_config: Dict[str, Any]) -> CompletionsService:
        return MockCompletionsService(resource_config)

    def get_embeddings_service(
        self, resource_config: Dict[str, Any], model: Optional[str] = None
    ) -> EmbeddingsService:
        return MockEmbeddingsService(resource_config, model)
