"""Weight-only int8 quantization for serving.

TPU decode is weights-bound: every step re-reads all parameters from HBM
while the MXU sits mostly idle. Storing matmul weights as int8 with
per-output-channel scales halves the bytes read per step (vs bf16),
which translates almost directly into decode throughput — and lets an
8B-parameter model fit a single 16 GB v5e chip.

Dequantization happens *inside* the consuming matmul: ``dq()`` emits
``q.astype(dtype) * scale``, which XLA fuses into the einsum so int8 is
what crosses HBM and the multiply-add runs in bf16 on the MXU. No custom
kernels needed; this is the standard JAX serving recipe.

``QTensor`` is a NamedTuple, hence automatically a pytree node: scans
slice the leading layer axis of both ``q`` and ``scale``, and
``shard_params`` descends into it when given a matching QTensor of
logical axes (see :func:`quantize_logical_axes`).

Reference parity: none — the reference's models live behind provider
HTTPS APIs (SURVEY §2.4); quantization is net-new for the in-process
backend, analogous to what its external providers do server-side.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from langstream_tpu.parallel.mesh import L, LogicalAxes


class QTensor(NamedTuple):
    q: jnp.ndarray      # int8, original weight shape
    scale: jnp.ndarray  # f32, weight shape minus the contraction axis


def quantize(w: jnp.ndarray, contract_axis: int = -2) -> QTensor:
    """Symmetric per-channel int8: scales taken over the contraction
    (input) axis so each output channel dequantizes independently.

    For stacked weights [L, in, out] the default ``contract_axis=-2``
    is the ``in`` axis → scale [L, out].
    """
    w32 = jnp.asarray(w, dtype=jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=contract_axis, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=jnp.squeeze(scale, axis=contract_axis))


def dq(w: Any, dtype: Any) -> jnp.ndarray:
    """Dequantize-or-cast: QTensor → bf16 weight (fused into the consumer
    matmul by XLA), plain array → cast. Model code calls this on every
    matmul weight so quantized and full-precision params are
    interchangeable."""
    if isinstance(w, QTensor):
        scale = jnp.expand_dims(w.scale, axis=-2)
        return (w.q.astype(dtype) * scale.astype(dtype))
    return w.astype(dtype) if w.dtype != dtype else w


def qeinsum(spec: str, x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """Einsum against an optionally-quantized weight, scale applied to
    the OUTPUT.

    The decode path is weights-bound, so what matters is that int8 is the
    only thing crossing HBM. ``dq()``'s operand-side expression
    ``convert(int8)*broadcast(scale)`` is not reliably fused into the dot
    by XLA:TPU — when it isn't, every step materializes the bf16 weight
    (3× the traffic int8 was meant to save). Per-output-channel scales
    commute with the contraction, so we contract against the bare
    ``convert(int8)`` (which XLA does fuse into the MXU operand stream)
    and multiply the [*, out] result by the scale — an elementwise op on
    activations, not weights.

    Requires ``spec`` to contract the weight's second-to-last axis and
    end with its last axis (true of every matmul in the model).
    """
    if isinstance(w, QTensor):
        out = jnp.einsum(spec, x, w.q.astype(x.dtype))
        return out * w.scale.astype(x.dtype)
    return jnp.einsum(spec, x, w.astype(x.dtype) if w.dtype != x.dtype else w)


# parameter names quantized for the dense Llama family; MoE expert
# weights keep bf16 for now (expert matmuls are already batched small)
QUANTIZED_PARAMS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head")


def quantize_params(
    params: Dict[str, Any], num_experts: int = 0
) -> Dict[str, Any]:
    """Quantize the large matmul weights of a stacked-params pytree.
    Embedding and norms stay full precision (lookups/elementwise).
    Idempotent: already-quantized leaves pass through."""
    out = dict(params)
    moe_names = {"w_gate", "w_up", "w_down"} if num_experts else set()
    for name in QUANTIZED_PARAMS:
        if (
            name in out
            and name not in moe_names
            and not isinstance(out[name], QTensor)
        ):
            out[name] = quantize(out[name])
    return out


def init_quantized_params(
    config, seed: int = 0, direct: Optional[bool] = None
) -> Dict[str, Any]:
    """Random-init directly in int8 (benchmarking): never materializes
    the bf16 weights, so an 8B model inits in ~9 GB instead of peaking
    at 24 GB (bf16 + int8) — the difference between fitting one v5e
    chip and not. ``direct=None`` picks by size (small models go
    through the exact init + quantize path)."""
    import math

    from langstream_tpu.providers.jax_local import model as model_lib

    key = jax.random.PRNGKey(seed)
    h = config.hidden_size
    scale = 1.0 / math.sqrt(h) / 127.0

    def q_init(k, shape):
        q = jax.random.randint(k, shape, -127, 128, dtype=jnp.int8)
        return QTensor(
            q=q, scale=jnp.full(shape[:-2] + shape[-1:], scale, jnp.float32)
        )

    if direct is None:
        direct = config.num_params() >= 5e8 and not config.num_experts
    if not direct or config.num_experts:
        # MoE always goes through exact init + quantize: the direct path
        # below emits dense-shaped MLP weights with no router
        return quantize_params(
            model_lib.init_params(config, seed=seed), config.num_experts
        )

    nh, nkv, hd = config.num_heads, config.num_kv_heads, config.dims_per_head
    f, v, layers = config.intermediate_size, config.vocab_size, config.num_layers
    keys = jax.random.split(key, 10)
    dtype = config.dtype
    # zero-centered norm convention (Gemma): identity weight is 0
    norm_fill = 0.0 if config.norm_plus_one else 1.0
    out: Dict[str, Any] = {
        "embedding": (
            jax.random.normal(keys[0], (v, h), dtype=dtype) * (1.0 / math.sqrt(h))
        ),
        "wq": q_init(keys[1], (layers, h, nh * hd)),
        "wk": q_init(keys[2], (layers, h, nkv * hd)),
        "wv": q_init(keys[3], (layers, h, nkv * hd)),
        "wo": q_init(keys[4], (layers, nh * hd, h)),
        "w_gate": q_init(keys[5], (layers, h, f)),
        "w_up": q_init(keys[6], (layers, h, f)),
        "w_down": q_init(keys[7], (layers, f, h)),
        "attn_norm": jnp.full((layers, h), norm_fill, dtype=jnp.float32),
        "mlp_norm": jnp.full((layers, h), norm_fill, dtype=jnp.float32),
        "final_norm": jnp.full((h,), norm_fill, dtype=jnp.float32),
    }
    if config.post_norms:
        out["post_attn_norm"] = jnp.full(
            (layers, h), norm_fill, dtype=jnp.float32
        )
        out["post_mlp_norm"] = jnp.full(
            (layers, h), norm_fill, dtype=jnp.float32
        )
    if config.qkv_bias:
        out["bq"] = jnp.zeros((layers, nh * hd), dtype=jnp.float32)
        out["bk"] = jnp.zeros((layers, nkv * hd), dtype=jnp.float32)
        out["bv"] = jnp.zeros((layers, nkv * hd), dtype=jnp.float32)
    if not config.tie_embeddings:
        out["lm_head"] = q_init(keys[8], (h, v))
    return out


def init_quantized_params_cached(
    config, seed: int = 0, cache_dir: Optional[str] = None
) -> Dict[str, Any]:
    """``init_quantized_params`` with an opt-in on-disk cache
    (``LS_WEIGHTS_CACHE_DIR``), so a retry loop (heal watcher, bench
    re-attempts) can skip random-init + quantize entirely.

    Default OFF and the retry tooling leaves it off: on-device random
    init runs ~10 small jits that live in the persistent compile cache,
    so a warm attempt's init is seconds of on-chip compute — while
    loading the cache means pushing ~9 GB of host bytes through the
    axon relay (`jax.device_put`), which is exactly the transfer class
    that wedges when the relay degrades. Use when the device path to
    the host is fast (real local TPU) or init itself is the bottleneck
    (the bench's per-phase ``timings_s`` shows which).

    bf16 leaves ride as uint16 views (numpy can't serialize ml_dtypes
    reliably); dtype strings travel in a manifest entry. Writes are
    atomic (tmp + rename) so a killed attempt can't leave a truncated
    cache that poisons the next one."""
    import json
    import logging
    import os
    import time

    import numpy as np

    cache_dir = cache_dir or os.environ.get("LS_WEIGHTS_CACHE_DIR", "")
    if not cache_dir:
        return init_quantized_params(config, seed=seed)
    os.makedirs(cache_dir, exist_ok=True)
    # sweep orphaned tmp files from killed attempts (a mid-savez kill
    # leaves a multi-GB partial that nothing else deletes); only ones
    # older than 5 min, so a concurrent writer's live tmp survives
    now = time.time()
    for name in os.listdir(cache_dir):
        if ".tmp" in name:
            stale = os.path.join(cache_dir, name)
            try:
                if now - os.path.getmtime(stale) > 300:
                    os.unlink(stale)
            except OSError:
                pass
    # the key must separate every config whose INIT VALUES differ, not
    # just shape-identical ones: a norm-convention flip (norm_plus_one
    # fills norms with 0 instead of 1), sandwich norms, qkv biases, or a
    # tied head all change the pytree contents while num_params() can
    # stay equal — loading another preset's cache silently serves wrong
    # weights (ADVICE r5). Readable dims stay up front; the digest folds
    # in the full weight-relevant field set (runtime-only knobs like
    # use_flash are excluded so kernel A/Bs share one cache entry).
    import dataclasses
    import hashlib

    sig_fields = (
        "vocab_size", "hidden_size", "intermediate_size", "num_layers",
        "num_heads", "num_kv_heads", "head_dim", "num_experts",
        "num_experts_per_tok", "tie_embeddings", "post_norms",
        "qkv_bias", "norm_plus_one", "scale_embedding", "act", "dtype",
    )
    known = {f.name for f in dataclasses.fields(type(config))}
    signature = "|".join(
        f"{name}={getattr(config, name)!r}"
        for name in sig_fields if name in known
    )
    convention = "".join(
        tag for tag, on in (
            ("z1", config.norm_plus_one), ("pn", config.post_norms),
            ("qb", config.qkv_bias), ("te", config.tie_embeddings),
        ) if on
    ) or "std"
    digest = hashlib.sha1(signature.encode()).hexdigest()[:10]
    key = (
        f"int8_{config.num_layers}L_{config.hidden_size}h_"
        f"{config.num_params()}p_{convention}_{digest}_s{seed}"
    )
    path = os.path.join(cache_dir, key + ".npz")
    spec = jax.eval_shape(lambda: init_quantized_params(config, seed=seed))
    spec_leaves, treedef = jax.tree_util.tree_flatten(spec)

    def storable(arr):
        # uint16 view for 2-byte custom dtypes; wider types are native
        return (
            np.asarray(arr).view(np.uint16)
            if arr.dtype.itemsize == 2 and arr.dtype.kind == "V"
            or str(arr.dtype) == "bfloat16"
            else np.asarray(arr)
        )

    if os.path.exists(path):
        try:
            with np.load(path, allow_pickle=False) as data:
                dtypes = json.loads(bytes(data["manifest"]).decode())
                if len(dtypes) != len(spec_leaves):
                    raise ValueError("leaf count mismatch")
                leaves = []
                for i, (s, dt) in enumerate(zip(spec_leaves, dtypes)):
                    raw = data[f"a{i}"]
                    arr = raw.view(jnp.bfloat16) if dt == "bfloat16" else raw
                    if arr.shape != s.shape or str(arr.dtype) != str(s.dtype):
                        raise ValueError(f"leaf {i} mismatch")
                    leaves.append(jax.device_put(arr))
            return jax.tree_util.tree_unflatten(treedef, leaves)
        except Exception as error:  # noqa: BLE001 — stale/corrupt: re-init
            try:
                os.unlink(path)
            except OSError:
                pass
            logging.getLogger(__name__).warning(
                "weights cache %s unusable (%r); re-initializing", path, error
            )
    params = init_quantized_params(config, seed=seed)
    leaves = jax.tree_util.tree_leaves(params)
    arrays = {f"a{i}": storable(leaf) for i, leaf in enumerate(leaves)}
    arrays["manifest"] = np.frombuffer(
        json.dumps([str(leaf.dtype) for leaf in leaves]).encode(), np.uint8
    ).copy()
    tmp = path + f".tmp{os.getpid()}"
    np.savez(tmp, **arrays)
    # np.savez appends .npz to names lacking it
    os.replace(tmp if os.path.exists(tmp) else tmp + ".npz", path)
    return params


def quantize_logical_axes(
    axes: Dict[str, Any], params: Dict[str, Any]
) -> Dict[str, Any]:
    """Mirror a logical-axes pytree onto quantized params: quantized
    leaves become QTensor(q=original axes, scale=axes minus the
    contraction axis) so ``shard_params`` descends in lockstep."""
    out = dict(axes)
    for name, value in params.items():
        if isinstance(value, QTensor) and name in out:
            names = out[name].names
            scale_names = names[:-2] + (names[-1],)
            out[name] = QTensor(
                q=L(*names), scale=L(*scale_names)
            )
    return out
