"""Continuous-batching decode engine — the serving core of ``jax-local``.

Design (TPU-first, see SURVEY.md §7 phase 4/5):

- **Slot-based static batch**: the KV cache holds ``max_slots`` sequences
  of ``max_seq_len``; every decode step runs ALL slots through one jitted
  ``decode_step`` — static shapes, one compilation, MXU-friendly batched
  matmuls. Empty slots ride along masked (their tokens are ignored), so
  admission/retirement never recompiles.
- **Continuous batching**: requests join mid-flight. A joining request
  prefills into its slot (bucketed prompt lengths → few compilations) while
  other slots keep decoding; a finishing request frees its slot
  immediately. No batch barrier — exactly the property the runner's
  emit-as-you-complete contract preserves upstream.
- **Dedicated device thread**: the asyncio side enqueues requests
  (thread-safe) and receives per-token callbacks via
  ``loop.call_soon_threadsafe``; device dispatch never blocks the event
  loop.
- **Session KV reuse** (BASELINE config #5): a finished request may pin its
  slot under a session id; a follow-up with the same session id whose
  prompt extends the pinned history skips re-prefilling the shared prefix
  (teacher-forced suffix only). Keyed by record key upstream, so broker
  partitioning gives replica affinity.
- **In-jit sampling**: greedy / temperature / top-k / top-p sampling,
  presence & frequency penalties, logit_bias, and per-request seeded
  keys all run on device inside the decode jit (tiered with ``lax.cond``
  so greedy traffic skips the sort); only the sampled token ids [S]
  cross to host per chunk.
"""

from __future__ import annotations

import asyncio
import dataclasses
import functools
import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from langstream_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    param_shardings,
    shard_params,
    validate_mesh,
)
from langstream_tpu.api import errors as api_errors
from langstream_tpu.providers.jax_local import model as model_lib
from langstream_tpu.runtime import faults, flight
from langstream_tpu.runtime.tracing import get_tracer

logger = logging.getLogger(__name__)

# live engines, for /metrics exposure (weak: a stopped engine's buffers
# must not be pinned by the metrics path)
import weakref

_LIVE_ENGINES: "weakref.WeakSet" = weakref.WeakSet()

# decode-step latency histogram across every engine in the process
# (observed once per chunk at wall/steps; buckets tuned to the ms range
# a decode step lives in)
from langstream_tpu.api.metrics import Histogram
from langstream_tpu.runtime import accounting
from langstream_tpu.runtime import journey as journey_ledger

DECODE_STEP_SECONDS = Histogram(
    "jax_engine_decode_step_seconds",
    buckets=(0.001, 0.002, 0.005, 0.01, 0.02, 0.035, 0.05, 0.075,
             0.1, 0.15, 0.25, 0.5, 1.0),
)
# per-request latency histograms: TTFT (submit → first token), TPOT
# (mean inter-token gap), end-to-end. Observed at _finish; the SLO
# burn-rate tracker reads timestamped snapshots of these same buckets
TTFT_SECONDS = Histogram(
    "jax_engine_ttft_seconds",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.2, 0.35, 0.5, 0.75, 1.0,
             2.0, 5.0, 10.0, 30.0),
)
TPOT_SECONDS = Histogram(
    "jax_engine_tpot_seconds",
    buckets=(0.002, 0.005, 0.01, 0.02, 0.035, 0.05, 0.075, 0.1,
             0.15, 0.25, 0.5, 1.0),
)
REQUEST_SECONDS = Histogram("jax_engine_request_seconds")
# per-chunk roofline utilization (fractions of the per-chip peak):
# MFU = model FLOP utilization, MBU = HBM-bandwidth utilization
_UTIL_BUCKETS = (0.01, 0.02, 0.05, 0.1, 0.15, 0.2, 0.3, 0.4, 0.5,
                 0.6, 0.7, 0.8, 0.9, 1.0)
MFU_PER_CHUNK = Histogram("jax_engine_mfu_per_chunk", buckets=_UTIL_BUCKETS)
MBU_PER_CHUNK = Histogram("jax_engine_mbu_per_chunk", buckets=_UTIL_BUCKETS)


def _supervisor_module():
    """The supervisor module iff something in this process already
    imported it — the ONE gate that keeps unsupervised processes from
    ever paying for (or exporting) the self-healing metric families."""
    import sys as _sys

    return _sys.modules.get("langstream_tpu.runtime.supervisor")


def engines_histograms():
    out = {
        h.name: h.snapshot()
        for h in (
            DECODE_STEP_SECONDS, TTFT_SECONDS, TPOT_SECONDS,
            REQUEST_SECONDS, MFU_PER_CHUNK, MBU_PER_CHUNK,
        )
    }
    # per-stage journey histograms (ISSUE 20) and recovery_seconds ride
    # every surface the engine histograms reach (runner pods, the
    # OpenAI server, the gateway)
    out.update(journey_ledger.stage_histograms())
    supervisor_mod = _supervisor_module()
    if supervisor_mod is not None:
        out.update(supervisor_mod.supervisor_histograms())
    return out


def engines_snapshot() -> Dict[str, float]:
    """Prometheus-gauge view over every live engine in this process:
    decode-step latency, slot occupancy, token/prefill counters
    (reference: AgentRunner.java:99-113 exposes runtime internals the
    same way; here the runtime internal is the TPU engine)."""
    out: Dict[str, float] = {}
    tokens = steps = chunks = 0
    session_hits = prefix_hits = prefix_tokens = 0
    decode_time = prefill_time = 0.0
    active_slot_steps = total_slot_steps = 0
    paged_engines = 0
    kv_blocks_in_use = kv_blocks_total = 0
    prefix_hit_tokens = prefix_evictions = 0
    handoff_exported_bytes = handoff_imported_bytes = 0
    handoff_exports = handoff_imports = handoff_imported_tokens = 0
    host_engines = 0
    kv_host_blocks_in_use = kv_host_blocks_total = 0
    host_demotions = host_promotions = host_evictions = 0
    host_demote_bytes = host_promote_bytes = 0
    kv_host_hit_tokens = host_promote_aborts = 0
    useful_tokens = 0
    wasted: Dict[str, int] = {
        reason: 0
        for reason in (
            "cancelled", "evicted_recompute", "draft_rejected",
            # supervisor resurrection: tokens re-prefilled to fast-
            # forward a crashed session back to its pre-crash state
            "crash_replay",
            # prompt-padding ghosts: split-path bucket rounding (up to
            # ~2x a prompt's FLOPs at the worst bucket edge) vs the
            # mixed path's ≤ width−1 per window — the padding win the
            # chunked-prefill A/B is judged on
            "prefill_padding",
            # mixed-step carry: tokens a speculatively chained step
            # sampled for rows whose request had already stopped or
            # been cancelled by the time the step was host-processed
            "carry_invalidated",
            # prefill/decode disaggregation: tokens whose KV handoff
            # aborted (pool pressure / torn payload / layout mismatch)
            # and had to be re-prefilled on the decode replica
            "handoff_aborted",
        )
    }
    shed_engines = 0
    shed: Dict[str, int] = {"queue_timeout": 0}
    spec_engines = 0
    spec_drafted = spec_accepted = 0
    mixed_engines = 0
    mixed_chained = 0
    # mixed-step carry: why speculative chains broke — pre-seeded so
    # every series exists before the first event (rate() alerts)
    carry_invalidations: Dict[str, int] = {
        reason: 0
        for reason in (
            "admission", "replay", "budget", "epoch", "condemned",
            "width", "drained", "stale_row",
        )
    }
    decode_flops = decode_bytes = prefill_flops = 0.0
    peaks: Optional[accounting.PeakSpecs] = None
    # snapshot-tolerant reads of engine-thread-owned state: a supervisor
    # rebuild registers the replacement engine FROM the dying engine
    # thread, and the engine thread inserts new wasted/shed reasons
    # lazily — iterating either container live from a scrape thread can
    # raise "changed size during iteration" (the build_heartbeat race
    # class, PR 10). stable_list/stable_items retry the snapshot.
    from langstream_tpu.utils.threadsafe import stable_items, stable_list

    live_engines = stable_list(_LIVE_ENGINES)
    for engine in live_engines:
        stats = engine.stats
        tokens += stats["tokens_generated"]
        steps += stats["decode_steps"]
        chunks += stats["decode_chunks"]
        decode_time += stats["decode_time"]
        prefill_time += stats["prefill_time"]
        active_slot_steps += stats["active_slot_steps"]
        total_slot_steps += stats["decode_steps"] * engine.max_slots
        session_hits += stats["session_hits"]
        prefix_hits += stats["prefix_hits"]
        prefix_tokens += stats["prefix_tokens_reused"]
        useful_tokens += stats["tokens_useful"]
        for reason, count in stable_items(stats["tokens_wasted"]):
            wasted[reason] = wasted.get(reason, 0) + count
        if engine.queue_timeout_s:
            shed_engines += 1
        for reason, count in stable_items(stats.get("requests_shed", {})):
            shed[reason] = shed.get(reason, 0) + count
        decode_flops += stats["decode_flops"]
        decode_bytes += stats["decode_bytes"]
        prefill_flops += stats["prefill_flops"]
        peaks = engine.peaks
        if engine.slo is not None:
            # SLO targets + multi-window burn rates: visible from the
            # first scrape (targets are config, not traffic)
            out.update(engine.slo.gauges())
        if getattr(engine, "spec", False):
            spec_engines += 1
            spec_drafted += stats["tokens_drafted"]
            spec_accepted += stats["tokens_draft_accepted"]
        if getattr(engine, "mixed", False):
            mixed_engines += 1
            mixed_chained += stats.get("mixed_steps_chained", 0)
            for reason, count in stable_items(
                stats.get("mixed_carry_invalidations", {})
            ):
                carry_invalidations[reason] = (
                    carry_invalidations.get(reason, 0) + count
                )
        if getattr(engine, "kv_manager", None) is not None:
            paged_engines += 1
            kv_blocks_in_use += engine.kv_manager.blocks_in_use
            kv_blocks_total += engine.num_blocks
            prefix_hit_tokens += engine.kv_manager.stats["hit_tokens"]
            prefix_evictions += engine.kv_manager.stats["evictions"]
            handoff_exports += stats.get("handoff_exports", 0)
            handoff_exported_bytes += stats.get("handoff_export_bytes", 0)
            handoff_imports += stats.get("handoff_imports", 0)
            handoff_imported_bytes += stats.get("handoff_import_bytes", 0)
            handoff_imported_tokens += stats.get(
                "handoff_import_tokens", 0
            )
            arena = getattr(engine, "kv_host_arena", None)
            if arena is not None:
                host_engines += 1
                arena_stats = arena.snapshot_stats()
                kv_host_blocks_in_use += arena_stats["blocks_in_use"]
                kv_host_blocks_total += arena.capacity_blocks
                host_evictions += arena_stats["evictions"]
                host_demotions += stats.get("host_demotions", 0)
                host_demote_bytes += stats.get("host_demote_bytes", 0)
                host_promotions += stats.get("host_promotions", 0)
                host_promote_bytes += stats.get("host_promote_bytes", 0)
                kv_host_hit_tokens += stats.get("kv_host_hit_tokens", 0)
                host_promote_aborts += stats.get("host_promote_aborts", 0)
    if live_engines:
        # watchdog trips ride the engine exposition so every scrape
        # surface sees them (0 included — the series must exist BEFORE
        # the first trip for rate() alerts to work); lazy import keeps
        # engine import free of the watchdog module at load time
        from langstream_tpu.runtime.watchdog import trips_total

        out["watchdog_trips_total"] = float(trips_total())
        # admission backlog: the fleet layer's routing/scaling signal
        # (fleet/router.py least-queue fallback, fleet/autoscaler.py
        # queue pressure) — exposed from construction so an idle
        # replica scrapes 0, not no-data
        out["jax_engine_queue_depth"] = float(
            sum(engine.queue_depth for engine in live_engines)
        )
    if paged_engines:
        # paged KV pool + persistent prefix cache (kv_layout: paged):
        # pool capacity/pressure are known from construction, so these
        # are exposed BEFORE the first token — an operator verifying a
        # freshly sized-down pool must not scrape no-data
        out["kv_blocks_in_use"] = float(kv_blocks_in_use)
        out["kv_blocks_total"] = float(kv_blocks_total)
        out["prefix_cache_hit_tokens_total"] = float(prefix_hit_tokens)
        out["prefix_cache_evictions_total"] = float(prefix_evictions)
        # paged-KV handoff (prefill/decode disaggregation): exposed
        # from construction on every paged engine so the disagg A/B
        # never scrapes no-data, and a decode replica importing nothing
        # (routing misconfigured) is visible as a flat zero
        out["kv_handoff_exports_total"] = float(handoff_exports)
        out["kv_handoff_exported_bytes_total"] = float(
            handoff_exported_bytes
        )
        out["kv_handoff_imports_total"] = float(handoff_imports)
        out["kv_handoff_imported_bytes_total"] = float(
            handoff_imported_bytes
        )
        out["kv_handoff_imported_tokens_total"] = float(
            handoff_imported_tokens
        )
    if host_engines:
        # tiered KV pool (kv-host-blocks > 0): host-arena capacity /
        # pressure and the demote/promote traffic each way — gated on
        # the tier being configured so an un-tiered deployment's
        # exposition is byte-identical to pre-tier builds. Exposed from
        # construction: a freshly sized host arena must scrape 0, not
        # no-data, and kv_host_hit_tokens_total is the goodput-ledger
        # companion (promotions that replaced eviction recompute)
        out["kv_host_blocks_in_use"] = float(kv_host_blocks_in_use)
        out["kv_host_blocks_total"] = float(kv_host_blocks_total)
        out["kv_host_demotions_total"] = float(host_demotions)
        out["kv_host_demoted_bytes_total"] = float(host_demote_bytes)
        out["kv_host_promotions_total"] = float(host_promotions)
        out["kv_host_promoted_bytes_total"] = float(host_promote_bytes)
        out["kv_host_hit_tokens_total"] = float(kv_host_hit_tokens)
        out["kv_host_promote_aborts_total"] = float(host_promote_aborts)
        out["kv_host_evictions_total"] = float(host_evictions)
    if spec_engines:
        # speculative decoding (spec-decode: ngram): drafted/accepted
        # counters + the acceptance rate — exposed from construction so
        # an operator A/B-ing the knob never scrapes no-data, and a
        # collapsed acceptance rate (workload without repetition) is
        # visible before anyone reads a flight artifact
        out["spec_tokens_drafted_total"] = float(spec_drafted)
        out["spec_tokens_accepted_total"] = float(spec_accepted)
        out["spec_acceptance_rate"] = round(
            spec_accepted / spec_drafted, 4
        ) if spec_drafted else 0.0
    if mixed_engines:
        # mixed-step carry (prefill_mode: mixed): chained-step counter +
        # per-reason chain-break counters — exposed from construction so
        # the carry A/B never scrapes no-data, and a chain rate stuck at
        # zero (carry off / constant invalidation) is visible without
        # reading a flight artifact. NOTE process-global gauges: tests
        # must assert DELTAS, not absolutes (other live engines count).
        out["jax_engine_mixed_steps_chained_total"] = float(mixed_chained)
        for reason, count in sorted(carry_invalidations.items()):
            out[
                f'mixed_carry_invalidations_total{{reason="{reason}"}}'
            ] = float(count)
    if shed_engines or any(shed.values()):
        # admission deadlines armed (or sheds already happened): the
        # series must exist BEFORE the first shed so rate() alerts work
        for reason, count in sorted(shed.items()):
            out[f'requests_shed_total{{reason="{reason}"}}'] = float(count)
    # self-healing plane (runtime/supervisor.py): restart/resurrection
    # counters + the degraded-mode gauge — exposed even with ZERO live
    # engines, because mid-rebuild (old engine retired, new one still
    # compiling) is exactly when an operator scrapes for it
    supervisor_mod = _supervisor_module()
    if supervisor_mod is not None:
        out.update(supervisor_mod.supervisor_gauges())
    if not (tokens or steps):
        return out
    out["jax_engine_session_hits"] = float(session_hits)
    out["jax_engine_prefix_hits"] = float(prefix_hits)
    out["jax_engine_prefix_tokens_reused"] = float(prefix_tokens)
    out["jax_engine_tokens_generated"] = float(tokens)
    out["jax_engine_decode_steps"] = float(steps)
    out["jax_engine_decode_chunks"] = float(chunks)
    out["jax_engine_decode_time_seconds"] = round(decode_time, 6)
    out["jax_engine_prefill_time_seconds"] = round(prefill_time, 6)
    if steps:
        out["jax_engine_decode_ms_per_step"] = round(
            decode_time / steps * 1e3, 4
        )
    if total_slot_steps:
        out["jax_engine_slot_occupancy"] = round(
            active_slot_steps / total_slot_steps, 4
        )
    # goodput ledger: every generated token classified useful vs wasted
    # (labeled by reason); the ratio is the fleet's headline efficiency
    out["jax_engine_tokens_useful_total"] = float(useful_tokens)
    for reason, count in sorted(wasted.items()):
        out[
            f'jax_engine_tokens_wasted_total{{reason="{reason}"}}'
        ] = float(count)
    accounted = useful_tokens + sum(wasted.values())
    if accounted:
        out["jax_engine_goodput_ratio"] = round(
            useful_tokens / accounted, 4
        )
    # roofline utilization over all decode work so far: cumulative
    # modeled FLOPs/bytes divided by busy decode wall time and the
    # per-chip peak (per-chunk values feed the MFU/MBU histograms)
    if peaks is not None and decode_time > 0:
        out["jax_engine_mfu"] = round(
            accounting.CostModel.mfu(decode_flops, decode_time, peaks), 6
        )
        out["jax_engine_mbu"] = round(
            accounting.CostModel.mbu(decode_bytes, decode_time, peaks), 6
        )
    if peaks is not None and prefill_time > 0 and prefill_flops:
        # prefill is FLOPs-bound and runs in separate dispatches —
        # folding it into jax_engine_mfu would blur both numbers, so a
        # prefill-heavy workload gets its own utilization gauge
        out["jax_engine_prefill_mfu"] = round(
            accounting.CostModel.mfu(prefill_flops, prefill_time, peaks), 6
        )
    return out


@dataclasses.dataclass
class SamplingParams:
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0            # 0 = no top-k
    top_p: float = 0.0        # 0 = no nucleus truncation
    max_new_tokens: int = 256
    # OpenAI-style repetition penalties over the GENERATED tokens (the
    # engine keeps a per-slot token-count array on device):
    # presence subtracts a flat amount from every already-seen token's
    # logit; frequency subtracts count × the amount
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # per-request RNG seed (OpenAI `seed`): sampling keys derive from
    # (seed, cache position), so a seeded request reproduces its tokens
    # EXACTLY regardless of what else shares the batch. None = a fresh
    # auto-seed per request (still independent of batch composition).
    seed: Optional[int] = None
    # OpenAI `logit_bias`: token id → additive logit adjustment,
    # applied before sampling (±100 effectively forces/bans a token).
    # Capped at DecodeEngine.MAX_LOGIT_BIAS entries per request.
    logit_bias: Optional[Dict[int, float]] = None


@dataclasses.dataclass
class GenerationRequest:
    prompt_tokens: List[int]
    sampling: SamplingParams
    stop_tokens: Set[int] = dataclasses.field(default_factory=set)
    # called from the engine thread via call_soon_threadsafe(loop) with
    # (token_id, is_last)
    on_token: Optional[Callable[[int, bool], None]] = None
    session_id: Optional[str] = None
    future: Optional[Any] = None  # asyncio.Future or concurrent future
    loop: Optional[Any] = None
    # set from ANY thread via cancel(); the engine finishes the request
    # with reason "cancelled" at the next token boundary (or drops it
    # from the queue before admission), freeing the slot for others
    cancelled: bool = False
    # end-to-end trace context (langstream-trace-id record header /
    # x-langstream-trace-id HTTP header): the engine tags its
    # admission/prefill/request spans with it so one id links the
    # gateway, the runner, and the device timeline
    trace_id: Optional[str] = None
    # session resurrection (runtime/supervisor.py): tokens the crashed
    # predecessor engine had already ACCEPTED for this request. The
    # supervisor rewrites ``prompt_tokens`` to prompt + replay[:-1]
    # (teacher-forced through a normal prefill — the paged prefix cache
    # makes it cheap) and the harvest path fast-forwards the slot
    # through them instead of emitting a fresh sample: sampling keys
    # derive from (seed, position) and penalty counts are restored
    # position-exactly, so the continuation is bitwise identical to the
    # uncrashed oracle. ``prompt_len`` preserves the ORIGINAL prompt
    # length across (repeated) resurrections for usage accounting.
    replay_tokens: Optional[List[int]] = None
    replay_logprobs: Optional[List[float]] = None
    replay_tops: Optional[List[Tuple[List[int], List[float]]]] = None
    prompt_len: Optional[int] = None
    # prefill/decode disaggregation (fleet/handoff.py): a prefill-leg
    # request asks the engine to export the session's published KV
    # chain at finish (rides GenerationResult.kv_handoff); a decode-leg
    # replay request carries the assembled handoff payload, imported
    # into the pool at admission so the replay prefill hits the prefix
    # cache for the full prompt instead of recomputing it
    export_handoff: bool = False
    kv_import: Optional[Dict[str, Any]] = None
    # journey ledger (ISSUE 20): the prefill replica's manifest export
    # stamp (wall seconds), threaded onto the decode-leg request so the
    # engine can emit a ``handoff_transit`` stage — fabric time between
    # the export and this replica's import — in its journey record
    handoff_export_ts: Optional[float] = None

    def cancel(self) -> None:
        self.cancelled = True


@dataclasses.dataclass
class GenerationResult:
    tokens: List[int]
    prompt_tokens: int
    finish_reason: str = "stop"
    # dispatch-to-harvest age of this request's prefill: since prefill
    # overlaps decode, this is the first-token admission latency the
    # caller experienced, NOT pure device prefill compute time
    prefill_time: float = 0.0
    decode_time: float = 0.0
    # per-token log-probability under the untruncated distribution,
    # aligned 1:1 with ``tokens`` (consumed by the FLARE controller;
    # reference: FlareControllerAgent.java logprobs field)
    logprobs: List[float] = dataclasses.field(default_factory=list)
    # per-token top-K alternatives (OpenAI `top_logprobs`): one
    # (token_ids, logprobs) pair per generated token, or None when the
    # engine runs with logprobs_topk=0
    top_logprobs: Optional[List[Tuple[List[int], List[float]]]] = None
    # disaggregation prefill leg (request.export_handoff): the session's
    # published KV chain serialized for the topic fabric — tokens +
    # per-leaf pool rows (fleet/handoff.py chunks it into records)
    kv_handoff: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class _Slot:
    request: Optional[GenerationRequest] = None
    length: int = 0                 # valid cache length
    generated: Optional[List[int]] = None
    logprobs: Optional[List[float]] = None  # parallel to ``generated``
    tops: Optional[List[Tuple[List[int], List[float]]]] = None  # top-K
                                            # alternatives per token
    history: Optional[List[int]] = None  # full token history in cache
    blocks: Optional[List[int]] = None   # paged layout: this slot's pool
                                         # blocks, in sequence order
    session_id: Optional[str] = None     # pinned session (slot free but warm)
    last_used: float = 0.0               # monotonic; drives LRU eviction
    epoch: int = 0                       # bumps on assign/finish; guards
                                         # pipelined results for recycled slots
    prefilling: bool = False             # prefill dispatched, first token
                                         # not yet harvested
    # mixed dispatch (prefill_mode: mixed): next prompt index a mixed
    # step should teach (None = not admitting through the mixed path);
    # successive decode steps carry prefill_chunk-token windows until
    # the watermark reaches the prompt end
    prefill_pos: Optional[int] = None
    prefill_seq: int = 0                 # admission order (FIFO budget share)
    prefill_t0: float = 0.0              # admission ts (prefill_time anchor)
    prefill_reused: int = 0              # cache-served prefix at admission

    @property
    def active(self) -> bool:
        return self.request is not None

    @property
    def ready(self) -> bool:
        """Participating in decode chunks (prefill result harvested)."""
        return self.request is not None and not self.prefilling


def fail_request_future(
    request: "GenerationRequest", error: BaseException
) -> None:
    """Deliver ``error`` to a request's waiter from any thread — the ONE
    future-failing path shared by crash fail-fast, load shedding, the
    retired-queue straggler sweep, and the supervisor's give-up handling
    (a fix to the loop-closed race must land once, not four times)."""
    future = request.future
    if future is None:
        return

    def resolve() -> None:
        if not future.done():
            future.set_exception(error)

    if request.loop is not None:
        try:
            request.loop.call_soon_threadsafe(resolve)
        except RuntimeError:
            # waiter's loop already closed (caller gave up) — must not
            # abort failing any REMAINING waiters
            pass
    else:
        resolve()


def _bucket(length: int, buckets: List[int]) -> int:
    for size in buckets:
        if length <= size:
            return size
    return buckets[-1]


class DecodeEngine:
    """Runs one model on one mesh with continuous batching."""

    def __init__(
        self,
        config: model_lib.LlamaConfig,
        params: Dict[str, Any],
        *,
        mesh_config: Optional[MeshConfig] = None,
        max_slots: int = 8,
        max_seq_len: Optional[int] = None,
        prefill_buckets: Optional[List[int]] = None,
        decode_chunk: int = 8,
        admission_chunk: Optional[int] = None,
        seed: int = 0,
        quantize: Optional[str] = None,  # "int8" = weight-only int8
        kv_quant: Optional[str] = None,  # "int8" = int8 KV cache
        kv_layout: str = "dense",        # "dense" | "paged" (block pool)
        kv_block_size: int = 16,         # paged: tokens per pool block
        kv_blocks: Optional[int] = None,  # paged: pool size (None = the
                                          # dense-equivalent worst case)
        kv_host_blocks: int = 0,          # paged: host-DRAM demotion
                                          # tier capacity in blocks —
                                          # evicted chains demote there
                                          # and promote back on a
                                          # digest match (0 = off, the
                                          # single-tier behavior)
        paged_kernel: str = "fused",     # paged attention: "fused" (one
                                          # Pallas launch over the block
                                          # tables) | "reference" (the
                                          # gather/scatter oracle)
        spec_decode: str = "off",        # speculative decoding: "off" |
                                          # "ngram" (self-drafting
                                          # prompt-lookup, k drafted
                                          # tokens verified per step)
        spec_k: int = 4,                 # drafted tokens per decode step
        spec_ngram: int = 2,             # suffix n-gram the drafter matches
        prefill_mode: str = "split",     # paged prefill scheduling:
                                          # "split" (dedicated bucketed
                                          # prefill dispatches) | "mixed"
                                          # (token-budget chunked prefill
                                          # fused into the decode step)
        prefill_chunk: int = 64,         # mixed: max prefill tokens any
                                          # single step carries
        mixed_carry: bool = True,        # mixed: pipeline consecutive
                                          # mixed steps off the previous
                                          # step's device-resident
                                          # outputs (two-step window
                                          # plan); needs pipeline_decode
        pipeline_decode: bool = False,
        prefix_cache: bool = True,
        logprobs_topk: int = 0,
        slo: Optional[Dict[str, Any]] = None,  # {ttft_ms_p95, tpot_ms_p95}
        queue_timeout_s: Optional[float] = None,  # admission deadline:
                                          # pending requests older than
                                          # this are shed with a typed
                                          # QueueTimeoutError (None=off)
    ) -> None:
        self.config = config
        self.max_slots = max_slots
        self.decode_chunk = max(1, decode_chunk)
        # TTFT lever: when admissions are waiting at dispatch time, cap
        # the chunk at this many steps so the freshly-prefilled request
        # joins the batch sooner — a full 32-step chunk makes a new
        # arrival wait ~chunk×ms_step before its first token. Costs one
        # extra compiled decode variant and more host round trips while
        # the queue is non-empty (chaining is already off then), so it
        # is an A/B knob, default off until measured on-chip.
        self.admission_chunk = (
            min(int(admission_chunk), self.decode_chunk)
            if admission_chunk and int(admission_chunk) > 0 else None
        )
        # top-K alternative logprobs per generated token (OpenAI
        # `top_logprobs`). STATIC — it shapes the jit outputs, so 0
        # (off) keeps the serving graphs byte-identical to a build
        # without the feature; >0 adds a top_k over the logits per step
        self.logprobs_topk = max(0, int(logprobs_topk))
        # pipelined decode: dispatch chunk N+1 from chunk N's on-device
        # carry BEFORE host-processing N's tokens, hiding the host (and
        # tunnel) round trip between chunks. Finished slots may burn up
        # to one surplus chunk; results are epoch-guarded so a recycled
        # slot never receives the old request's tokens.
        self.pipeline_decode = pipeline_decode
        # cross-slot prompt-prefix reuse: a cold request whose prompt
        # shares a prefix with another live slot's cache copies those KV
        # rows on-device (bandwidth-bound) instead of recomputing the
        # prefill (FLOPs-bound), then prefills only the divergent suffix.
        # Covers n>1 choices, shared chat templates, and repeated prompts.
        self.prefix_cache = prefix_cache
        self.max_seq_len = min(
            max_seq_len or config.max_seq_len, config.max_seq_len
        )
        self.prefill_buckets = prefill_buckets or self._default_buckets()
        if mesh_config is None:
            # default: single device. Sharding is opt-in via provider
            # config (mesh: {tp: N}) so small models never get axes that
            # don't divide their head counts.
            mesh_config = MeshConfig()
        validate_mesh(
            mesh_config,
            num_heads=config.num_heads,
            num_kv_heads=config.num_kv_heads,
            intermediate_size=config.intermediate_size,
            num_experts=config.num_experts,
            allow_pp=False,  # serving has no pipeline schedule
        )
        # flash under tp>1 runs through shard_map over the head axis (see
        # model._prefill_attn); no need to disable the kernel here
        self.mesh = build_mesh(
            mesh_config, devices=jax.devices()[: mesh_config.size]
        )
        axes = model_lib.logical_axes(config)
        from langstream_tpu.providers.jax_local.quant import QTensor

        pre_quantized = any(
            isinstance(v, QTensor) for v in params.values()
        )
        if quantize or pre_quantized:
            if quantize not in (None, "int8"):
                raise ValueError(f"unknown quantization {quantize!r}")
            from langstream_tpu.providers.jax_local.quant import (
                quantize_logical_axes,
                quantize_params,
            )

            params = quantize_params(params, config.num_experts)
            axes = quantize_logical_axes(axes, params)
        with self.mesh:
            self.params = shard_params(params, axes, self.mesh)
        self.freqs = model_lib.model_freqs(config)
        if kv_quant not in (None, "int8"):
            raise ValueError(f"unknown kv cache quantization {kv_quant!r}")
        self.kv_quant = kv_quant == "int8"
        if kv_layout not in ("dense", "paged"):
            raise ValueError(f"unknown kv layout {kv_layout!r}")
        if paged_kernel not in ("fused", "reference"):
            raise ValueError(f"unknown paged kernel {paged_kernel!r}")
        self.kv_layout = kv_layout
        self.paged = kv_layout == "paged"
        # fused-vs-reference is the ROADMAP-item-1 A/B knob: "fused"
        # REQUESTS the ragged Pallas kernel; model._use_fused_paged falls
        # back to the reference composition off-TPU (sans the interpret
        # hook) / on non-MXU-aligned head dims, so the knob is safe to
        # leave at its default everywhere. tp>1 is NOT a downgrade: the
        # kernel runs per kv-head shard through its shard_map twin
        # (ragged_paged_attention_sharded), like the dense flash
        # kernels. That gate is static per engine (config shapes,
        # interpret hook, backend), so resolve it ONCE here and let
        # accounting, flight/artifact telemetry, and the dispatch
        # builders all see the kernel that actually runs — a silent
        # fused→reference fallback must not leave the byte model
        # charging fused bytes (MBU would read ~3x low).
        self.paged_kernel_requested = paged_kernel if self.paged else None
        self.paged_kernel = self.paged_kernel_requested
        # speculative decoding (ROADMAP item 2): a prompt-lookup drafter
        # proposes spec_k tokens per decode step and ONE verify forward
        # scores all of them — 1..spec_k+1 tokens per weight pass. The
        # non-speculative scan stays compiled as the oracle ("off").
        if spec_decode not in ("off", "ngram"):
            raise ValueError(f"unknown spec decode mode {spec_decode!r}")
        self.spec_decode = spec_decode
        self.spec = spec_decode == "ngram"
        self.spec_k = max(1, int(spec_k))
        self.spec_ngram = max(1, int(spec_ngram))
        # tokens a single scan step can emit (verify block width): the
        # context/budget arithmetic everywhere else keys off this
        self.spec_block = (self.spec_k + 1) if self.spec else 1
        # mixed prefill+decode dispatch (ROADMAP item 1 / ISSUE 12): on
        # the paged path, prefill stops being its own dispatch shape —
        # admitting slots park at a `prefill_pos` watermark and every
        # decode step carries up to `prefill_chunk` of their prompt
        # tokens alongside the Tq=1 decode rows in ONE fused ragged
        # launch (Sarathi-style stall-free batching), bounding any
        # single dispatch's duration and capping padding at the mixed
        # width instead of a power-of-two bucket. "split" keeps the
        # dedicated prefill dispatch + harvest machinery (the oracle
        # the mixed path is token-parity-tested against, and the
        # on-chip A/B's other leg).
        if prefill_mode not in ("split", "mixed"):
            raise ValueError(f"unknown prefill mode {prefill_mode!r}")
        if prefill_mode == "mixed" and kv_layout != "paged":
            raise ValueError(
                "prefill_mode 'mixed' requires kv_layout 'paged' — the "
                "dense cache has no per-row table indirection for the "
                "token-ragged mixed dispatch to address"
            )
        self.prefill_mode = prefill_mode
        self.mixed = prefill_mode == "mixed"
        self.prefill_chunk = max(1, int(prefill_chunk))
        # device-resident mixed-step carry (ROADMAP item 1 / ISSUE 14):
        # while admissions are chunking through mixed steps, the NEXT
        # step's window content is host-predictable from the watermark
        # bookkeeping advanced at plan time, so the engine speculatively
        # plans step N+1 and dispatches it off step N's device-resident
        # outputs (sampled tokens / cache / counts / tables / sampling
        # arrays stay on device; only the small prompt-window token
        # delta uploads) BEFORE host-processing N — hiding the host
        # round trip exactly like _dispatch_decode(carry=...). Chained
        # and unchained steps share ONE compiled program per width (the
        # fresh dispatch passes an all-False chain mask), so chaining
        # is bitwise-neutral by construction. Gated like decode
        # pipelining: both knobs must be on.
        self.mixed_carry = self.mixed and bool(mixed_carry)
        # mixed width ladder: power-of-two [S, W] dispatch widths up to
        # the (rounded-up) budget, so compilations stay logarithmic and
        # every width tiles evenly by the ragged kernel's q tile
        cap = 1
        while cap < self.prefill_chunk:
            cap *= 2
        widths = [min(8, cap)]
        while widths[-1] < cap:
            widths.append(widths[-1] * 2)
        self._mixed_widths = widths
        self._admit_seq = 0
        if self.paged_kernel == "fused" and not model_lib._use_fused_paged(
            config, config.dims_per_head, config.num_heads,
            config.num_kv_heads, self.mesh,
        ):
            self.paged_kernel = "reference"
        self.kv_manager = None
        self.kv_host_blocks = 0
        self.kv_host_arena = None
        if self.paged:
            from langstream_tpu.providers.jax_local.paged import (
                PagedKVManager,
            )

            self.block_size = max(1, int(kv_block_size))
            # per-slot table width: enough blocks to address max_seq_len
            self.max_blocks = -(-self.max_seq_len // self.block_size)
            # default pool = the dense layout's worst case (+ null
            # block); real deployments size it DOWN — short requests
            # release blocks early and shared prefixes are stored once,
            # which is the whole HBM win
            self.num_blocks = int(
                kv_blocks or max_slots * self.max_blocks + 1
            )
            if self.num_blocks < self.max_blocks + 1:
                raise ValueError(
                    f"kv_blocks={self.num_blocks} cannot hold even one "
                    f"max-length sequence ({self.max_blocks} blocks of "
                    f"{self.block_size})"
                )
            self.kv_manager = PagedKVManager(self.num_blocks, self.block_size)
            # two-tier pool (ISSUE 18): a bounded pinned host-RAM arena
            # below the HBM pool — eviction demotes victim chains
            # through the jitted handoff gather (D2H) and admission
            # promotes digest matches back through the donated handoff
            # scatter (H2D) before falling back to cold prefill
            self.kv_host_blocks = max(0, int(kv_host_blocks or 0))
            if self.kv_host_blocks:
                from langstream_tpu.providers.jax_local.paged import (
                    HostKVArena,
                )

                self.kv_host_arena = HostKVArena(self.kv_host_blocks)
                self.kv_manager.attach_host(
                    self.kv_host_arena, self._demote_block_data
                )
            else:
                self.kv_host_arena = None
            # host-authoritative block tables [slots, max_blocks]; rows
            # are uploaded per dispatch (0 = the null block)
            self._block_tables = np.zeros(
                (max_slots, self.max_blocks), dtype=np.int32
            )
            cache_sharding = param_shardings(
                model_lib.paged_cache_logical_axes(self.kv_quant), self.mesh
            )
            with self.mesh:
                # device-thread state: rethreaded (donated) through
                # every dispatch on _run_loop
                # owned-by: _run_loop
                self.cache = jax.device_put(
                    model_lib.init_paged_cache(
                        config, self.num_blocks, self.block_size,
                        kv_quant=self.kv_quant,
                    ),
                    cache_sharding,
                )
            # the jitted COW block copy pins its outputs to this layout
            # so the SPMD partitioner can never resolve the dynamic
            # block index by all-gathering the pool (see _get_block_copy)
            self._cache_sharding = cache_sharding
        else:
            cache_sharding = param_shardings(
                model_lib.cache_logical_axes(self.kv_quant), self.mesh
            )
            with self.mesh:
                # owned-by: _run_loop
                self.cache = jax.device_put(
                    model_lib.init_cache(
                        config, max_slots, self.max_seq_len,
                        kv_quant=self.kv_quant,
                    ),
                    cache_sharding,
                )
        self.slots = [_Slot() for _ in range(max_slots)]
        # efficiency accounting: analytical FLOPs/bytes per dispatch from
        # the model shape + quantization widths + KV layout, divided by
        # measured wall time and the per-chip peaks → per-chunk MFU/MBU
        self.peaks = accounting.PeakSpecs.from_env()
        self.cost_model = accounting.CostModel.from_model_config(
            config,
            weight_quant=(
                "int8" if (quantize == "int8" or pre_quantized) else None
            ),
            kv_quant=self.kv_quant,
            kv_block_size=self.block_size if self.paged else 1,
            paged_kernel=self.paged_kernel,
            # per-CHIP accounting under tensor parallelism: weights and
            # KV shard over tp, so a chip's share of the work divides —
            # billing whole-model FLOPs/bytes per chip would overstate
            # MFU/MBU by ~tp× on sharded engines
            tp=dict(self.mesh.shape).get("tp", 1),
        )
        # SLO burn-rate tracking over the process-wide TTFT/TPOT
        # histograms (targets come from serve/provider config)
        self.slo = (
            accounting.SLOTracker(
                slo, {"ttft": TTFT_SECONDS, "tpot": TPOT_SECONDS}
            )
            if slo else None
        )
        # goodput ledger support: sessions whose warm cache was evicted,
        # so a follow-up's re-prefill can be booked as wasted recompute
        # (value = cached history length at eviction; bounded FIFO)
        self._evicted_sessions: Dict[str, int] = {}
        self.base_seed = seed
        self._seed_sequence = 0
        # per-slot generated-token counts for presence/frequency
        # penalties; lives on device, threaded (donated) through every
        # prefill/decode dispatch like the KV cache. Explicitly
        # replicated over the mesh: on tp>1 an unplaced buffer would sit
        # on device 0 only, and lowering engine variants from live avals
        # (precompile, the StableHLO assertion tests) would see
        # incompatible device sets before the first dispatch resolves it
        from jax.sharding import NamedSharding, PartitionSpec

        with self.mesh:
            self._counts = jax.device_put(
                jnp.zeros((max_slots, config.vocab_size), jnp.int32),
                NamedSharding(self.mesh, PartitionSpec()),
            )

        self._queue: "queue.Queue[Optional[GenerationRequest]]" = queue.Queue()
        # admission backlog, popped only by the device thread (submit()
        # hands off through the thread-safe queue; len() reads from
        # other threads are point-in-time snapshots)
        self._pending: List[GenerationRequest] = []  # owned-by: _run_loop
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._crashed: Optional[BaseException] = None
        # supervised mode (runtime/supervisor.py): when set, a crashed
        # device thread hands its live sessions to this hook instead of
        # failing every waiter — crash → rebuild → resume, not crash →
        # mass 500. Unset (the default) keeps the fail-fast behavior.
        self.on_crash: Optional[Callable[[BaseException], None]] = None
        # admission deadline for load shedding (serve --queue-timeout-s)
        self.queue_timeout_s = (
            float(queue_timeout_s) if queue_timeout_s else None
        )
        # EWMA decode-step seconds: the Retry-After estimator for shed
        # requests (queue depth × step time ≈ when a slot frees up)
        self._step_ewma: Optional[float] = None
        self._counts_restore_fn: Optional[Any] = None
        # set once drain_for_recovery has swept the queue: a submit that
        # lands after the sweep must fail itself (nothing reads it)
        self._recovery_drained = False
        self._compiled_prefill: Dict[int, Any] = {}
        self._prefill_offset_fns: Dict[int, Any] = {}
        self._decode_fns: Dict[int, Any] = {}
        self._spec_decode_fns: Dict[int, Any] = {}
        self._mixed_fns: Dict[int, Any] = {}
        self._copy_fns: Dict[int, Any] = {}
        self._block_copy_fn: Optional[Any] = None
        # KV-handoff gather/scatter jits, memoized per pow2-padded
        # block-chain width (same retrace budget as every builder)
        self._handoff_export_fns: Dict[int, Any] = {}
        self._handoff_import_fns: Dict[int, Any] = {}
        # prefill dispatches whose first tokens are not yet harvested
        # (FIFO — the device executes dispatches in order)
        self._prefill_inflight: List[Dict[str, Any]] = []  # owned-by: _run_loop
        # end of the latest accounted decode interval (busy-time union)
        self._decode_busy_until = 0.0
        # end of the latest processed mixed step (host-gap evidence for
        # the mixed-step carry: unchained steps pay the gap, chained
        # steps collapse it)  # owned-by: _run_loop
        self._last_mixed_end = 0.0
        # counters mutated only on the device thread; cross-thread
        # readers (engines_snapshot, build_heartbeat, the watchdog)
        # take snapshot-tolerant reads — see _stable_items there
        self.stats = self._fresh_stats()  # owned-by: _run_loop
        # per-chunk dispatch log: (steps, active_slots, wall_seconds) —
        # the occupancy/step-time evidence the bench prints (bounded)
        self.chunk_log: List[Tuple[int, int, float]] = []  # owned-by: _run_loop
        # token-denominated twin of chunk_log covering EVERY device
        # dispatch (prefill windows included): the interference-bound
        # evidence — in mixed mode no entry's prefill_tokens may exceed
        # prefill_chunk, while a split-path cold prompt logs its whole
        # bucket in one entry (bounded like chunk_log)
        self.dispatch_log: List[Dict[str, Any]] = []  # owned-by: _run_loop
        # multi-host SPMD serving: when set (serving/mirror.py), every
        # device dispatch is also published as a compact record so
        # follower hosts replay the identical jit sequence on their
        # shards of the same global mesh
        self.mirror: Optional[Any] = None
        # observability plane: per-request spans (NOOP unless
        # LANGSTREAM_TRACE_DIR is set) + the crash-surviving flight
        # recorder (no-op unless configured / LANGSTREAM_FLIGHT_DIR)
        self.tracer = get_tracer("engine")
        flight.configure_from_env()
        # deterministic chaos (LANGSTREAM_FAULTS): zero-cost no-ops when
        # unarmed; arrival counters are process-global, so a one-shot
        # fault consumed here stays consumed across a supervisor rebuild
        faults.configure_from_env()
        flight.record(
            "engine_start",
            slots=max_slots,
            ctx=self.max_seq_len,
            mesh=dict(self.mesh.shape),
            decode_chunk=self.decode_chunk,
            kv_quant=bool(self.kv_quant),
            kv_layout=self.kv_layout,
            kv_blocks=self.num_blocks if self.paged else 0,
            kv_host_blocks=self.kv_host_blocks,
            paged_kernel=self.paged_kernel or "",
            paged_kernel_requested=self.paged_kernel_requested or "",
            spec_decode=self.spec_decode,
            spec_k=self.spec_k if self.spec else 0,
            prefill_mode=self.prefill_mode,
            prefill_chunk=self.prefill_chunk if self.mixed else 0,
        )
        _LIVE_ENGINES.add(self)

    @staticmethod
    def _fresh_stats() -> Dict[str, Any]:
        return {
            "tokens_generated": 0,
            "requests": 0,
            "prefill_calls": 0,
            "warm_prefill_calls": 0,
            "decode_steps": 0,
            "session_hits": 0,
            "prefix_hits": 0,            # cross-slot prefix-copy admissions
            "prefix_tokens_reused": 0,   # KV rows copied instead of recomputed
            "decode_chunks": 0,
            "decode_time": 0.0,      # wall secs inside decode dispatches
            "prefill_time": 0.0,     # wall secs inside prefill dispatches
            "active_slot_steps": 0,  # sum of active slots over decode steps
            # wall-clock breakdown of everything OUTSIDE device dispatches,
            # so "unaccounted" time has a name (VERDICT r2 weak #1)
            "idle_time": 0.0,        # engine thread blocked on empty queue
            "emit_time": 0.0,        # host token bookkeeping + callbacks
            # goodput ledger: tokens that reached a live caller vs tokens
            # burned on cancelled requests / eviction-induced re-prefill
            "tokens_useful": 0,
            "tokens_wasted": {},     # reason -> tokens
            # load shedding: pending requests failed fast at their
            # admission deadline instead of starving in _pending
            "requests_shed": {},     # reason -> requests
            # roofline accumulators (modeled work per dispatch kind)
            "decode_flops": 0.0,
            "decode_bytes": 0.0,
            "prefill_flops": 0.0,
            # speculative decoding: drafted candidates vs candidates the
            # verify pass accepted (rejected = the new wasted reason)
            "tokens_drafted": 0,
            "tokens_draft_accepted": 0,
            # decode wall-time normalizer for the watchdog: tokens an
            # AVERAGE active slot gained, summed over chunks — equals
            # decode_steps for plain decode, grows ~(1+accept·k) faster
            # under speculation, so per-token latency stays comparable
            "decode_token_steps": 0.0,
            # mixed-step carry (prefill_mode: mixed): total mixed steps,
            # how many were dispatched off the previous step's device
            # carry, and why chains broke (reason -> events) — the
            # chain-rate evidence the carry A/B is judged on
            "mixed_steps": 0,
            "mixed_steps_chained": 0,
            "mixed_carry_invalidations": {},
            # summed device idle between consecutive mixed steps (the
            # per-step host tax; ~0 while chains hold)
            "mixed_gap_time": 0.0,
            # paged-KV handoff (prefill/decode disaggregation): exports
            # serialized off this engine's pool, imports written into
            # it, and the device bytes each way — the transfer price
            # the disagg A/B reads next to its tail win
            "handoff_exports": 0,
            "handoff_export_bytes": 0,
            "handoff_imports": 0,
            "handoff_import_bytes": 0,
            "handoff_import_tokens": 0,
            # tiered KV pool (host-DRAM demotion tier): blocks moved
            # each way with their D2H/H2D bytes, prompt tokens served
            # by promotions instead of recompute, and promotions that
            # tore mid-scatter and fell back to cold prefill
            "host_demotions": 0,
            "host_demote_bytes": 0,
            "host_promotions": 0,
            "host_promote_bytes": 0,
            "kv_host_hit_tokens": 0,
            "host_promote_aborts": 0,
        }

    # lint: allow(owned-by-violation) -- bench/warmup contract: callers
    #   reset counters only while the engine is idle (no dispatch in
    #   flight); a concurrent reset would at worst lose a sample, and
    #   the replacement dicts/lists are fully formed before publication
    def reset_stats(self) -> None:
        """Zero the counters (e.g. after warmup, before measurement)."""
        self.stats = self._fresh_stats()
        self.chunk_log = []
        self.dispatch_log = []

    def _default_buckets(self) -> List[int]:
        buckets, size = [], 64
        limit = self.max_seq_len if hasattr(self, "max_seq_len") else 4096
        while size < limit:
            buckets.append(size)
            size *= 2
        buckets.append(limit)
        return buckets

    # ------------------------------------------------------------------ #
    # jitted device functions
    # ------------------------------------------------------------------ #
    def _tp_mesh(self):
        """The mesh iff tensor parallelism is actually on — the one rule
        for whether model code routes Pallas kernels through their
        shard_map wrappers (a bare Mosaic call has no SPMD partitioning
        rule). Used by prefill AND decode jits; keep them in lockstep."""
        return self.mesh if dict(self.mesh.shape).get("tp", 1) > 1 else None

    def _get_prefill(self, bucket: int):
        """Prefill + first-token sampling in ONE jit: the engine never
        blocks on prefill — sampling on-device means harvesting is a pure
        D2H read of [B] tokens once the dispatch completes, so decode
        chunks for already-running slots keep flowing underneath."""
        fn = self._compiled_prefill.get(bucket)
        if fn is None:
            config, freqs = self.config, self.freqs
            mesh = self._tp_mesh()
            topk = self.logprobs_topk

            def sample_first(logits, slot_ids, counts, temperature, top_k,
                             top_p, seeds, lengths, bias_ids, bias_vals):
                keys = _sampling_keys(seeds, lengths)
                rows = jnp.arange(logits.shape[0])[:, None]
                adjusted = logits.at[rows, bias_ids].add(bias_vals)
                sampled = _sample(adjusted, temperature, top_k, keys, top_p)
                lp = _token_logprob(logits, sampled)
                tops = _top_logprobs(logits, topk) if topk else None
                # fresh request: reset the slot's penalty counts, then
                # count the first sampled token
                counts = counts.at[slot_ids].set(0)
                counts = counts.at[slot_ids, sampled].add(1)
                return counts, sampled, lp, tops

            if self.paged:
                paged_kernel = self.paged_kernel

                @functools.partial(jax.jit, donate_argnums=(1, 6))
                def run(params, cache, tokens, lengths, slot_ids, tables,
                        counts, temperature, top_k, top_p, seeds,
                        bias_ids, bias_vals):
                    cache, logits = model_lib.paged_prefill(
                        config, params, cache, tokens, lengths, tables,
                        freqs, mesh=mesh, kernel=paged_kernel,
                    )
                    counts, sampled, lp, tops = sample_first(
                        logits, slot_ids, counts, temperature, top_k,
                        top_p, seeds, lengths, bias_ids, bias_vals,
                    )
                    return cache, counts, sampled, lp, tops

            else:

                @functools.partial(jax.jit, donate_argnums=(1, 5))
                def run(params, cache, tokens, lengths, slot_ids, counts,
                        temperature, top_k, top_p, seeds,
                        bias_ids, bias_vals):
                    cache, logits = model_lib.prefill(
                        config, params, cache, tokens, lengths, slot_ids,
                        freqs, mesh=mesh,
                    )
                    counts, sampled, lp, tops = sample_first(
                        logits, slot_ids, counts, temperature, top_k,
                        top_p, seeds, lengths, bias_ids, bias_vals,
                    )
                    return cache, counts, sampled, lp, tops

            fn = run
            self._compiled_prefill[bucket] = fn
        return fn

    def _get_prefill_offset(self, bucket: int):
        fn = self._prefill_offset_fns.get(bucket)
        if fn is None:
            config, freqs = self.config, self.freqs
            mesh = self._tp_mesh()
            topk = self.logprobs_topk

            def sample_first(logits, slot_ids, counts, temperature, top_k,
                             top_p, seeds, offsets, lengths,
                             bias_ids, bias_vals):
                # key position = the row's TOTAL cache length, so a warm
                # continuation samples exactly like a cold run of the
                # same full prompt
                keys = _sampling_keys(seeds, offsets + lengths)
                rows = jnp.arange(logits.shape[0])[:, None]
                adjusted = logits.at[rows, bias_ids].add(bias_vals)
                sampled = _sample(adjusted, temperature, top_k, keys, top_p)
                lp = _token_logprob(logits, sampled)
                tops = _top_logprobs(logits, topk) if topk else None
                counts = counts.at[slot_ids].set(0)
                counts = counts.at[slot_ids, sampled].add(1)
                return counts, sampled, lp, tops

            if self.paged:
                paged_kernel = self.paged_kernel

                @functools.partial(jax.jit, donate_argnums=(1, 7))
                def run(params, cache, tokens, lengths, offsets, slot_ids,
                        tables, counts, temperature, top_k, top_p, seeds,
                        bias_ids, bias_vals):
                    cache, logits = model_lib.paged_prefill_at_offset(
                        config, params, cache, tokens, lengths, offsets,
                        tables, freqs, mesh=mesh, kernel=paged_kernel,
                    )
                    counts, sampled, lp, tops = sample_first(
                        logits, slot_ids, counts, temperature, top_k,
                        top_p, seeds, offsets, lengths, bias_ids, bias_vals,
                    )
                    return cache, counts, sampled, lp, tops

            else:

                @functools.partial(jax.jit, donate_argnums=(1, 6))
                def run(params, cache, tokens, lengths, offsets, slot_ids,
                        counts, temperature, top_k, top_p, seeds,
                        bias_ids, bias_vals):
                    cache, logits = model_lib.prefill_at_offset(
                        config, params, cache, tokens, lengths, offsets,
                        slot_ids, freqs,
                    )
                    counts, sampled, lp, tops = sample_first(
                        logits, slot_ids, counts, temperature, top_k,
                        top_p, seeds, offsets, lengths, bias_ids, bias_vals,
                    )
                    return cache, counts, sampled, lp, tops

            fn = run
            self._prefill_offset_fns[bucket] = fn
        return fn

    def _get_decode(self, steps: int = 1):
        """Jitted K-step decode: a ``lax.scan`` of decode+sample, so one
        host↔device dispatch yields K tokens per slot. Chunking amortizes
        dispatch latency (which dominates when the chip sits behind a
        network tunnel or when the model is small); stop conditions are
        applied host-side afterwards, surplus steps for a finished slot
        are discarded and its length pointer rewound.

        With ``spec_decode: ngram`` every scan step is draft→verify→
        accept instead (:meth:`_get_spec_decode`) and yields 1..spec_k+1
        tokens per slot per step; this plain scan stays compiled as the
        non-speculative oracle."""
        if self.spec:
            return self._get_spec_decode(steps)
        fn = self._decode_fns.get(steps)
        if fn is None:
            config, freqs = self.config, self.freqs
            mesh = self._tp_mesh()
            topk = self.logprobs_topk
            paged = self.paged
            paged_kernel = self.paged_kernel

            def run_impl(params, cache, tokens, lengths, active, write_mask,
                         tables, counts, temperature, top_k, top_p,
                         presence, frequency, seeds, bias_ids, bias_vals):
                slots = tokens.shape[0]

                def body(carry, _):
                    cache, tokens, lengths, counts = carry
                    if paged:
                        cache, logits = model_lib.paged_decode_step(
                            config, params, cache, tokens, lengths,
                            tables, freqs, write_mask, mesh=mesh,
                            kernel=paged_kernel,
                        )
                    else:
                        cache, logits = model_lib.decode_step(
                            config, params, cache, tokens, lengths, freqs,
                            write_mask, mesh=mesh,
                        )
                    # presence/frequency penalties over generated tokens
                    # (identity when both are 0 — exact float math)
                    adjusted = (
                        logits
                        - presence[:, None] * (counts > 0)
                        - frequency[:, None] * counts
                    )
                    adjusted = adjusted.at[
                        jnp.arange(slots)[:, None], bias_ids
                    ].add(bias_vals)
                    # per-slot keys from (seed, position): sampling never
                    # depends on what else shares the batch
                    keys = _sampling_keys(seeds, lengths)
                    sampled = _sample(adjusted, temperature, top_k, keys, top_p)
                    # logprob under the RAW untruncated distribution (the
                    # model's own confidence — what FLARE consumes)
                    lp = _token_logprob(logits, sampled)
                    sampled = jnp.where(active, sampled, 0)
                    counts = counts.at[jnp.arange(slots), sampled].add(
                        active.astype(jnp.int32)
                    )
                    lengths = jnp.where(active, lengths + 1, lengths)
                    ys = (sampled, lp)
                    if topk:
                        ys = ys + _top_logprobs(logits, topk)
                    return (cache, sampled, lengths, counts), ys

                (
                    (cache, final_tokens, final_lengths, counts),
                    ys,
                ) = jax.lax.scan(
                    body, (cache, tokens, lengths, counts), None, length=steps
                )
                out, lps = ys[0], ys[1]
                # [steps, S, K] -> [S, steps, K] to match out.T's layout
                tops = (
                    (ys[2].transpose(1, 0, 2), ys[3].transpose(1, 0, 2))
                    if topk else None
                )
                # final carry is returned ON DEVICE so a pipelined next
                # chunk can chain without a host round trip
                return (
                    cache, counts, out.T, lps.T, tops,
                    final_tokens, final_lengths,
                )

            if paged:

                @functools.partial(jax.jit, donate_argnums=(1, 7))
                def run(params, cache, tokens, lengths, active, write_mask,
                        tables, counts, temperature, top_k, top_p,
                        presence, frequency, seeds, bias_ids, bias_vals):
                    return run_impl(
                        params, cache, tokens, lengths, active, write_mask,
                        tables, counts, temperature, top_k, top_p,
                        presence, frequency, seeds, bias_ids, bias_vals,
                    )

            else:

                @functools.partial(jax.jit, donate_argnums=(1, 6))
                def run(params, cache, tokens, lengths, active, write_mask,
                        counts, temperature, top_k, top_p,
                        presence, frequency, seeds, bias_ids, bias_vals):
                    return run_impl(
                        params, cache, tokens, lengths, active, write_mask,
                        None, counts, temperature, top_k, top_p,
                        presence, frequency, seeds, bias_ids, bias_vals,
                    )

            fn = run
            self._decode_fns[steps] = fn
        return fn

    def _get_spec_decode(self, steps: int):
        """Jitted K-step SPECULATIVE decode scan (``spec_decode: ngram``).
        Each scan step: (1) the prompt-lookup drafter proposes up to
        spec_k tokens from the slot's own device-resident token history,
        (2) ONE verify forward scores the [S, 1+spec_k] candidate block
        at every position (dense :func:`model.verify_step`; paged rides
        the fused kernel's existing Tq>1 formulation), (3) the
        acceptance pass emits 1..spec_k+1 tokens per slot with the exact
        sampling semantics of the oracle scan (greedy exact-match /
        rejection sampling, penalties, bias, seeded keys). Rejected
        suffixes roll back by NOT advancing lengths — rows past the
        accepted length are causally invisible and overwritten in order
        by later steps (paged blocks were reserved at admission, so no
        allocator churn). Emitted counts ride the scan outputs so the
        host sees a variable number of tokens per dispatch."""
        fn = self._spec_decode_fns.get(steps)
        if fn is None:
            from langstream_tpu.providers.jax_local import (
                spec_decode as spec_lib,
            )

            config, freqs = self.config, self.freqs
            mesh = self._tp_mesh()
            topk = self.logprobs_topk
            paged = self.paged
            paged_kernel = self.paged_kernel
            k = self.spec_k
            ngram = self.spec_ngram
            block_width = self.spec_block
            width = self.max_seq_len  # history array width

            def run_impl(params, cache, tokens, lengths, active, write_mask,
                         history, tables, counts, temperature, top_k, top_p,
                         presence, frequency, seeds, bias_ids, bias_vals):
                slots = tokens.shape[0]

                def body(carry, _):
                    cache, tokens, lengths, counts, history = carry
                    drafts, num = spec_lib.draft_ngram(
                        history, lengths, active, ngram=ngram, k=k,
                    )
                    block = jnp.concatenate(
                        [tokens[:, None], drafts], axis=1
                    )  # [S, 1+k]
                    valid_lens = jnp.where(active, 1 + num, 0)
                    if paged:
                        cache, logits = model_lib.paged_verify_step(
                            config, params, cache, block, lengths,
                            valid_lens, tables, freqs,
                            write_mask=write_mask, mesh=mesh,
                            kernel=paged_kernel,
                        )
                    else:
                        cache, logits = model_lib.verify_step(
                            config, params, cache, block, lengths,
                            valid_lens, freqs, write_mask=write_mask,
                            mesh=mesh,
                        )
                    emitted, lps, valid, counts, tops = (
                        spec_lib.accept_block(
                            logits, block, num, counts, active,
                            temperature, top_k, top_p, seeds, lengths,
                            presence, frequency, bias_ids, bias_vals, topk,
                        )
                    )
                    m = valid.sum(axis=1).astype(jnp.int32)  # [S] emitted
                    # append the emitted tokens to the device history
                    # (positions lengths..lengths+m-1; invalid → dropped)
                    pos = lengths[:, None] + jnp.arange(block_width)[None, :]
                    pos = jnp.where(valid, pos, width)
                    history = history.at[
                        jnp.arange(slots)[:, None], pos
                    ].set(emitted, mode="drop")
                    last = jnp.take_along_axis(
                        emitted,
                        jnp.clip(m - 1, 0, block_width - 1)[:, None],
                        axis=1,
                    )[:, 0]
                    tokens = jnp.where(active & (m > 0), last, tokens)
                    lengths = lengths + jnp.where(active, m, 0)
                    ys = (emitted, lps, valid, num)
                    if topk:
                        ys = ys + tops
                    return (cache, tokens, lengths, counts, history), ys

                (
                    (cache, final_tokens, final_lengths, counts,
                     final_history),
                    ys,
                ) = jax.lax.scan(
                    body, (cache, tokens, lengths, counts, history),
                    None, length=steps,
                )
                # [steps, S, B] -> [S, steps, B]
                out = ys[0].transpose(1, 0, 2)
                lps = ys[1].transpose(1, 0, 2)
                valid = ys[2].transpose(1, 0, 2)
                drafted = ys[3].transpose(1, 0)  # [S, steps]
                tops = (
                    (ys[4].transpose(1, 0, 2, 3), ys[5].transpose(1, 0, 2, 3))
                    if topk else None
                )
                return (
                    cache, counts, out, lps, valid, drafted, tops,
                    final_tokens, final_lengths, final_history,
                )

            if paged:

                @functools.partial(jax.jit, donate_argnums=(1, 6, 8))
                def run(params, cache, tokens, lengths, active, write_mask,
                        history, tables, counts, temperature, top_k, top_p,
                        presence, frequency, seeds, bias_ids, bias_vals):
                    return run_impl(
                        params, cache, tokens, lengths, active, write_mask,
                        history, tables, counts, temperature, top_k, top_p,
                        presence, frequency, seeds, bias_ids, bias_vals,
                    )

            else:

                @functools.partial(jax.jit, donate_argnums=(1, 6, 7))
                def run(params, cache, tokens, lengths, active, write_mask,
                        history, counts, temperature, top_k, top_p,
                        presence, frequency, seeds, bias_ids, bias_vals):
                    return run_impl(
                        params, cache, tokens, lengths, active, write_mask,
                        history, None, counts, temperature, top_k, top_p,
                        presence, frequency, seeds, bias_ids, bias_vals,
                    )

            fn = run
            self._spec_decode_fns[steps] = fn
        return fn

    def _get_mixed(self, width: int):
        """Jitted mixed prefill+decode step (``prefill_mode: mixed``):
        ONE fused dispatch where every ready slot rides as a Tq=1
        decode row and admitting slots carry ``width``-capped prefill
        windows — :func:`model.paged_mixed_step` plus in-jit sampling
        with the split paths' EXACT semantics, so mixed and split legs
        are token-parity comparable:

        - decode rows sample like the decode scan body: penalties over
          the slot's count row, logit bias, keys from (seed, post-write
          length);
        - a window that COMPLETES its prompt samples like the prefill
          paths' ``sample_first``: counts reset then the first token
          counted, NO penalties (fresh request), keys from (seed,
          total prompt length);
        - mid-prefill and idle rows discard their sample and leave the
          count row untouched.

        Mixed-step carry: the program additionally takes the PREVIOUS
        step's device-resident sampled tokens plus a host ``chain_mask``
        and splices them into column 0 of chained rows — a fresh
        dispatch passes zeros + an all-False mask (integer identity), so
        chained and unchained steps run the SAME compiled program per
        width and chaining is bitwise-neutral by construction (the
        decode carry's contract). The returned ``sampled`` array is the
        next chain's device-resident token operand."""
        fn = self._mixed_fns.get(width)
        if fn is None:
            config, freqs = self.config, self.freqs
            mesh = self._tp_mesh()
            topk = self.logprobs_topk
            paged_kernel = self.paged_kernel

            @functools.partial(jax.jit, donate_argnums=(1, 9))
            def run(params, cache, tokens, offsets, num_tokens,
                    write_mask, decode_mask, completes, tables, counts,
                    prev_sampled, chain_mask,
                    temperature, top_k, top_p, presence, frequency,
                    seeds, bias_ids, bias_vals):
                # chained rows ride the previous mixed step's on-device
                # sample as their pending token (host never saw it yet)
                tokens = tokens.at[:, 0].set(
                    jnp.where(chain_mask, prev_sampled, tokens[:, 0])
                )
                cache, logits = model_lib.paged_mixed_step(
                    config, params, cache, tokens, offsets, num_tokens,
                    tables, freqs, write_mask=write_mask, mesh=mesh,
                    kernel=paged_kernel,
                )
                slots = tokens.shape[0]
                rows = jnp.arange(slots)
                sample_mask = decode_mask | completes
                # completing rows reset their penalty counts FIRST
                # (sample_first semantics — order is irrelevant for the
                # sample itself since penalties don't apply to them)
                counts = jnp.where(completes[:, None], 0, counts)
                penalized = (
                    logits
                    - presence[:, None] * (counts > 0)
                    - frequency[:, None] * counts
                )
                adjusted = jnp.where(
                    decode_mask[:, None], penalized, logits
                )
                adjusted = adjusted.at[rows[:, None], bias_ids].add(
                    bias_vals
                )
                # key position = the row's TOTAL cache length after this
                # step: decode rows match the scan body's `lengths`,
                # completing windows match sample_first's prompt length
                keys = _sampling_keys(seeds, offsets + num_tokens)
                sampled = _sample(adjusted, temperature, top_k, keys,
                                  top_p)
                lp = _token_logprob(logits, sampled)
                tops = _top_logprobs(logits, topk) if topk else None
                sampled = jnp.where(sample_mask, sampled, 0)
                counts = counts.at[rows, sampled].add(
                    sample_mask.astype(jnp.int32)
                )
                return cache, counts, sampled, lp, tops

            fn = run
            self._mixed_fns[width] = fn
        return fn

    def _get_copy_prefix(self, bucket: int):
        """Jitted cross-slot KV copy: move ``bucket`` cache rows starting
        at ``offset`` from slot ``src`` to slot ``dst``. Pure device-side
        data movement — for a B-token prefix this reads+writes
        ``B * layers * kv_heads * head_dim * 2`` elements (a few MB),
        orders of magnitude cheaper than recomputing the prefill.
        ``params`` is unused; it keeps the (params, cache, ...) argument
        shape every other engine dispatch has, so :meth:`precompile` can
        drive all variants uniformly."""
        fn = self._copy_fns.get(bucket)
        if fn is None:

            @functools.partial(jax.jit, donate_argnums=(1,))
            def run(params, cache, src, dst, offset):
                del params

                def move(c):
                    # rank-agnostic: value leaves are 5-d, int8-KV scale
                    # leaves 4-d — both are [layers, slot, seq, ...]
                    tail = (0,) * (c.ndim - 3)
                    chunk = jax.lax.dynamic_slice(
                        c, (0, src, offset) + tail,
                        (c.shape[0], 1, bucket) + c.shape[3:],
                    )
                    return jax.lax.dynamic_update_slice(
                        c, chunk, (0, dst, offset) + tail
                    )

                return (jax.tree_util.tree_map(move, cache),)

            fn = run
            self._copy_fns[bucket] = fn
        return fn

    def _get_block_copy(self):
        """Jitted pool-block copy (paged layout): duplicate block ``src``
        into ``dst`` across every layer and cache leaf. This is the
        copy-on-write primitive — a session follow-up that diverges
        mid-block gets a private copy of the boundary block before its
        suffix prefill overwrites rows a published chain still needs.
        ``params`` is unused; it keeps the uniform (params, cache, ...)
        dispatch shape (see :meth:`_get_copy_prefix`). Outputs carry the
        pool's sharding constraint: the copied block index is dynamic
        and the block axis replicated, so without the pin the SPMD
        partitioner may resolve the slice by all-gathering the
        kv-head-sharded pool under tp>1."""
        fn = self._block_copy_fn
        if fn is None:
            sharding = self._cache_sharding

            @functools.partial(jax.jit, donate_argnums=(1,))
            def run(params, cache, src, dst):
                del params

                def move(c, s):
                    # [layers, num_blocks, block_size, ...] — value AND
                    # scale leaves share the leading three axes
                    tail = (0,) * (c.ndim - 2)
                    chunk = jax.lax.dynamic_slice(
                        c, (0, src) + tail,
                        (c.shape[0], 1) + c.shape[2:],
                    )
                    return jax.lax.with_sharding_constraint(
                        jax.lax.dynamic_update_slice(
                            c, chunk, (0, dst) + tail
                        ),
                        s,
                    )

                return (jax.tree_util.tree_map(move, cache, sharding),)

            fn = run
            self._block_copy_fn = fn
        return fn

    def _dispatch_block_copy(self, src: int, dst: int) -> None:
        if self.mirror is not None:
            # COW is a device dispatch: followers must duplicate the
            # same pool block on their shard, in stream order, or every
            # later read of the private copy diverges
            self._check_mirror_layout()
            self.mirror.publish(
                "block_copy", {}, [np.int32(src), np.int32(dst)]
            )
        run = self._get_block_copy()
        (self.cache,) = run(
            self.params, self.cache, np.int32(src), np.int32(dst)
        )
        self.kv_manager.stats["cow_copies"] += 1

    # ------------------------------------------------------------------ #
    # paged-KV handoff (prefill/decode disaggregation, fleet/handoff.py)
    # ------------------------------------------------------------------ #
    @staticmethod
    def _handoff_pad(n: int) -> int:
        """Pow2-padded block-chain width: bounds the export/import jits
        to one lowering per width bucket instead of one per chain
        length (the retrace-budget rule, analysis/retrace.py)."""
        return 1 << max(0, int(n - 1).bit_length())

    def _get_handoff_export(self, width: int):
        """Jitted pool gather for a handoff export: every cache leaf's
        rows for ``width`` table blocks, ``[layers, width, …]`` per
        leaf. No donation — the pool stays live (the exported chain is
        still published and serving). Dynamic block ids index a
        replicated axis, so no sharding constraint is needed: each
        kv-head shard gathers its own rows and the host concatenation
        is the unsharded view."""
        fn = self._handoff_export_fns.get(width)
        if fn is None:

            @jax.jit
            def run(cache, blocks):
                return jax.tree_util.tree_map(
                    lambda c: jnp.take(c, blocks, axis=1), cache
                )

            fn = run
            self._handoff_export_fns[width] = fn
        return fn

    def _get_handoff_import(self, width: int):
        """Jitted pool scatter for a handoff import: write ``width``
        blocks of per-leaf rows into their freshly reserved pool slots.
        Donates the cache like every mutating dispatch; padded entries
        target the null block (their zero rows are never read through a
        live length mask). Outputs carry the pool's sharding constraint
        for the same reason the block copy does — the scattered block
        axis is replicated, and without the pin the partitioner may
        materialize the kv-head-sharded pool whole under tp>1."""
        fn = self._handoff_import_fns.get(width)
        if fn is None:
            sharding = self._cache_sharding

            @functools.partial(jax.jit, donate_argnums=(1,))
            def run(params, cache, blocks, data):
                del params

                def put(c, d, s):
                    return jax.lax.with_sharding_constraint(
                        c.at[:, blocks].set(d.astype(c.dtype)), s
                    )

                return (
                    jax.tree_util.tree_map(put, cache, data, sharding),
                )

            fn = run
            self._handoff_import_fns[width] = fn
        return fn

    def _export_handoff(
        self, slot: _Slot, request: Optional[GenerationRequest] = None
    ) -> Optional[Dict[str, Any]]:
        """Serialize the finishing slot's published chain for the topic
        fabric: full blocks of ``history[:length]`` (exactly what
        :meth:`PagedKVManager.publish` made matchable — the final
        sampled token is never in the cache, so it rides the manifest's
        teacher-forced replay instead). Returns the payload
        ``fleet.handoff.handoff_records`` chunks, or None when nothing
        is exportable (no full block yet). ``request`` (the finishing
        request — ``slot.request`` is already cleared by ``_finish``)
        labels the trace span; the payload's ``export_ts`` lets the
        serving layer stamp the chunk-0 manifest so the decode side can
        compute ``handoff_transit``."""
        full = slot.length // self.block_size
        if full <= 0 or not slot.blocks:
            return None
        export_t0 = time.perf_counter()
        export_wall = time.time()
        tokens = slot.history[: full * self.block_size]
        blocks = slot.blocks[:full]
        width = self._handoff_pad(full)
        padded = np.zeros((width,), dtype=np.int32)
        padded[:full] = blocks
        run = self._get_handoff_export(width)
        gathered = run(self.cache, padded)
        arrays = {
            leaf: np.asarray(value)[:, :full]
            for leaf, value in gathered.items()
        }
        # lazy: the canonical byte accounting lives with the wire
        # schema (one definition for gauges, assembler, and sim)
        from langstream_tpu.fleet.handoff import payload_nbytes

        payload = {
            "tokens": list(tokens),
            "arrays": arrays,
            "block_size": self.block_size,
            "kv_quant": bool(self.kv_quant),
            # the transit anchor: rides the chunk-0 manifest
            # (manifest_for_request) so the decode leg can subtract.
            # Stamped AFTER the arrays are materialized — transit
            # measures the fabric, not this replica's serialization
            "export_ts": time.time(),
        }
        nbytes = payload_nbytes(payload)
        self.stats["handoff_exports"] += 1
        self.stats["handoff_export_bytes"] += nbytes
        flight.record(
            "kv_handoff_export",
            tokens=len(tokens),
            blocks=full,
            nbytes=nbytes,
        )
        if self.tracer.enabled:
            self.tracer.event(
                "engine.handoff_export",
                time.perf_counter() - export_t0,
                trace_id=(request.trace_id or "") if request else "",
                start_wall=export_wall,
                tokens=len(tokens),
                blocks=full,
                bytes=nbytes,
                aborted=False,
                replica=flight.get_identity().get("replica", ""),
            )
        return payload

    def _import_pending_handoffs(self) -> None:
        """Import every pending request's handoff payload BEFORE the
        admission scan, on the engine thread (the manager's owner): the
        written chain publishes under the normal ``(parent_block,
        chunk)`` keys, so the request's own admission — and any
        concurrent same-prefix admission — then hits the prefix cache
        instead of re-prefilling. A failed import (pool pressure, shape
        mismatch, torn payload) bills ``handoff_aborted`` and degrades
        to recompute — never a caller-visible error."""
        if not self.paged or not self.prefix_cache:
            return
        for request in self._pending:
            if request.kv_import is None:
                continue
            payload, request.kv_import = request.kv_import, None
            import_start = time.time()
            ok = self._import_handoff(
                payload, trace_id=request.trace_id or ""
            )
            if ok:
                # journey ledger: the decode leg's handoff_import stage
                # window + admission class (the later prefix-cache hit
                # this import manufactured must not book as "hbm-hit")
                request._jt_import = (  # type: ignore[attr-defined]
                    import_start, time.time()
                )
                request._jt_admit_class = (  # type: ignore[attr-defined]
                    "handoff-import"
                )

    def _import_handoff(
        self, payload: Dict[str, Any], trace_id: str = ""
    ) -> bool:
        manager = self.kv_manager
        tokens = list(payload.get("tokens") or [])
        arrays = payload.get("arrays") or {}
        size = int(payload.get("block_size", 0) or 0)
        full = len(tokens) // size if size else 0
        import_t0 = time.perf_counter()
        import_wall = time.time()

        def aborted(reason: str) -> bool:
            self._waste("handoff_aborted", len(tokens))
            flight.record(
                "kv_handoff_import_aborted",
                reason=reason, tokens=len(tokens),
            )
            if self.tracer.enabled:
                self.tracer.event(
                    "engine.handoff_import",
                    time.perf_counter() - import_t0,
                    trace_id=trace_id,
                    start_wall=import_wall,
                    tokens=len(tokens),
                    aborted=True,
                    reason=reason,
                )
            return False

        if self.mirror is not None:
            # followers replay dispatch records, and the import scatter
            # carries host-built arrays no record schema ships yet —
            # refuse rather than fork the mirrored pools
            return aborted("mirror")
        if (
            full <= 0
            or size != self.block_size
            or bool(payload.get("kv_quant", False)) != bool(self.kv_quant)
            or set(arrays) != set(self.cache)
        ):
            return aborted("layout_mismatch")
        for leaf, expect in self.cache.items():
            shape = tuple(np.asarray(arrays[leaf]).shape)
            if shape != (expect.shape[0], full, *expect.shape[2:]):
                return aborted("shape_mismatch")
        reserved = manager.import_session(tokens)
        if reserved is None:
            return aborted("pool_exhausted")
        chain, fresh = reserved
        try:
            if fresh:
                # only the blocks the local cache does NOT already hold
                # are written; a (partially) resident prefix keeps its
                # local rows — they are bitwise the same content
                start = len(chain)
                width = self._handoff_pad(len(fresh))
                padded = np.zeros((width,), dtype=np.int32)
                padded[: len(fresh)] = fresh
                data = {}
                for leaf, array in arrays.items():
                    piece = np.ascontiguousarray(
                        np.asarray(array)[:, start:full]
                    )
                    if width > len(fresh):
                        pad = [(0, 0)] * piece.ndim
                        pad[1] = (0, width - len(fresh))
                        piece = np.pad(piece, pad)
                    data[leaf] = piece
                run = self._get_handoff_import(width)
                (self.cache,) = run(self.params, self.cache, padded, data)
        except Exception:  # noqa: BLE001 — unwind before ids recycle
            manager.abort_import(chain + fresh)
            raise
        manager.commit_import(tokens, chain + fresh)
        nbytes = payload.get("nbytes")
        if not isinstance(nbytes, (int, float)):
            from langstream_tpu.fleet.handoff import payload_nbytes

            nbytes = payload_nbytes(payload)
        self.stats["handoff_imports"] += 1
        self.stats["handoff_import_bytes"] += int(nbytes)
        self.stats["handoff_import_tokens"] += len(tokens)
        flight.record(
            "kv_handoff_import",
            tokens=len(tokens),
            blocks_written=len(fresh),
            blocks_local=len(chain),
            nbytes=int(nbytes),
        )
        if self.tracer.enabled:
            self.tracer.event(
                "engine.handoff_import",
                time.perf_counter() - import_t0,
                trace_id=trace_id,
                start_wall=import_wall,
                tokens=len(tokens),
                blocks=len(chain) + len(fresh),
                bytes=int(nbytes),
                aborted=False,
                replica=flight.get_identity().get("replica", ""),
            )
        return True

    # ------------------------------------------------------------------ #
    # tiered KV pool: host-DRAM demotion / promotion (ISSUE 18)
    # ------------------------------------------------------------------ #
    # lint: allow(owned-by-violation) -- engine-thread by contract: the
    #   manager stores this as its demote hook (attach_host) and calls
    #   it only inside the eviction pass of allocate(), which runs on
    #   _run_loop()'s admission scan; the AST reachability pass cannot
    #   follow the stored-callback indirection
    def _demote_block_data(
        self, block: int
    ) -> Optional[Tuple[Dict[str, Any], int]]:
        """Data-plane hook the manager calls while demoting one victim
        block: gather the block's pool rows D2H through the memoized
        handoff-export jit (width 1 — demotion happens block-by-block
        inside the eviction pass, before the id returns to the free
        list, so the gather dispatch always precedes any new owner's
        write in stream order). Returns ``(leaf tree, nbytes)`` —
        ``np.asarray`` preserves bf16 and int8+scales bitwise — or
        None when demotion must be skipped (mirrored engines replay
        dispatch records that carry no host-tier schema)."""
        if self.mirror is not None:
            return None
        run = self._get_handoff_export(1)
        gathered = run(self.cache, np.asarray([block], dtype=np.int32))
        data = {
            leaf: np.asarray(value)[:, 0]
            for leaf, value in gathered.items()
        }
        nbytes = sum(a.nbytes for a in data.values())
        self.stats["host_demotions"] += 1
        self.stats["host_demote_bytes"] += nbytes
        flight.record("kv_host_demote", block=block, nbytes=nbytes)
        return data, nbytes

    def _host_probe(
        self, prompt: Sequence[int], match: Optional[Tuple[List[int], int]]
    ) -> List[Any]:
        """Host-tier continuation of the HBM prefix scan: the demoted
        entries that extend ``match``'s chain, truncated at the first
        entry without captured rows (an accounting-only entry cannot
        be promoted)."""
        if (
            not self.paged
            or not self.prefix_cache
            or self.kv_manager.host is None
            or self.mirror is not None
        ):
            return []
        start = len(match[0]) if match is not None else 0
        entries = self.kv_manager.host_match(prompt, start)
        out: List[Any] = []
        for entry in entries:
            if entry.data is None:
                break
            out.append(entry)
        return out

    def _promote_host_chain(
        self,
        prompt: Sequence[int],
        matched: List[int],
        matched_tokens: int,
        entries: List[Any],
        fresh: List[int],
    ) -> int:
        """Scatter ``entries`` (host-tier continuation of the matched
        HBM chain) into the first ``len(entries)`` freshly reserved
        blocks through the donated, sharding-pinned handoff-import jit,
        then publish the promoted chain — publish-at-commit: the rows'
        writes are dispatched HERE, so any reader (same-round warm
        suffix, later mixed window) is ordered after them on the
        stream. Any failure aborts BEFORE anything publishes: the fresh
        blocks stay private, the admission proceeds as a cold prefill,
        and the caller never sees an error. Returns promoted blocks
        (0 = aborted)."""
        count = len(entries)
        target = fresh[:count]
        size = self.block_size
        try:
            if faults.fire("host_promote_torn") is not None:
                raise RuntimeError("chaos: torn host promotion")
            width = self._handoff_pad(count)
            padded = np.zeros((width,), dtype=np.int32)
            padded[:count] = target
            data: Dict[str, Any] = {}
            for leaf, expect in self.cache.items():
                rows = np.stack(
                    [np.asarray(entry.data[leaf]) for entry in entries],
                    axis=1,
                )
                if rows.shape != (
                    expect.shape[0], count, *expect.shape[2:]
                ):
                    raise ValueError(
                        f"host entry shape {rows.shape} does not fit "
                        f"pool leaf {leaf}"
                    )
                if width > count:
                    pad = [(0, 0)] * rows.ndim
                    pad[1] = (0, width - count)
                    rows = np.pad(rows, pad)
                data[leaf] = rows
            run = self._get_handoff_import(width)
            (self.cache,) = run(self.params, self.cache, padded, data)
        except Exception:  # noqa: BLE001 — abort-before-recycle
            self.stats["host_promote_aborts"] += 1
            flight.record(
                "kv_host_promote_aborted",
                blocks=count, tokens=count * size,
            )
            return 0
        end = matched_tokens + count * size
        self.kv_manager.publish(list(prompt[:end]), matched + target)
        nbytes = sum(entry.nbytes for entry in entries)
        self.stats["host_promotions"] += count
        self.stats["host_promote_bytes"] += nbytes
        self.stats["kv_host_hit_tokens"] += count * size
        arena = self.kv_manager.host
        if arena is not None:
            arena.note_promoted(count)
        flight.record(
            "kv_host_promote",
            blocks=count, tokens=count * size, nbytes=nbytes,
        )
        return count

    def _dispatch_prefix_copy(self, src: int, dst: int, length: int) -> None:
        """Copy cache rows [0:length) of ``src`` into ``dst`` in
        bucket-sized windows. Windows may overshoot the exact length:
        rows past the shared prefix are either overwritten by the
        suffix prefill or masked by the slot's length, and decode writes
        a row before ever attending to it — so no masking is needed."""
        largest = self.prefill_buckets[-1]
        position = 0
        while position < length:
            remaining = length - position
            bucket = (
                largest if remaining > largest
                else _bucket(remaining, self.prefill_buckets)
            )
            if self.mirror is not None:
                self.mirror.publish("copy", {"bucket": bucket}, [
                    np.int32(src), np.int32(dst), np.int32(position),
                ])
            run = self._get_copy_prefix(bucket)
            (self.cache,) = run(
                self.params,
                self.cache,
                np.int32(src),
                np.int32(dst),
                np.int32(position),
            )
            position += bucket
        self.stats["prefix_hits"] += 1
        self.stats["prefix_tokens_reused"] += length

    def _variant_jobs(self) -> List[Tuple[Any, Tuple[Any, ...]]]:
        """One (jit fn, arg avals) entry per prefill/decode variant the
        engine can ever dispatch — the single source both precompile
        phases drive from, so they cannot drift. Args 0/1 are always
        params/cache avals; every other arg is a plain data array
        (zeros are valid stand-ins for all of them)."""

        def aval(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)

        params_aval = jax.tree_util.tree_map(aval, self.params)
        cache_aval = jax.tree_util.tree_map(aval, self.cache)
        counts_aval = aval(self._counts)

        def vec(n, dtype):
            return jax.ShapeDtypeStruct((n,), dtype)

        def tables(n):
            # paged: per-row block tables ride every dispatch
            return (
                (jax.ShapeDtypeStruct((n, self.max_blocks), jnp.int32),)
                if self.paged else ()
            )

        jobs: List[Tuple[Any, Tuple[Any, ...]]] = []
        size = 1
        # mixed mode retires the bucketed prefill dispatches entirely:
        # prompts enter through the mixed decode-step windows below, so
        # compiling the (bucket × group-size) prefill lattice would be
        # pure waste (and followers never receive those records either)
        while not self.mixed and size <= self.max_slots:
            for bucket in self.prefill_buckets:
                sampling = (
                    vec(size, jnp.float32), vec(size, jnp.int32),
                    vec(size, jnp.float32), vec(size, jnp.uint32),
                    jax.ShapeDtypeStruct(
                        (size, self.MAX_LOGIT_BIAS), jnp.int32
                    ),
                    jax.ShapeDtypeStruct(
                        (size, self.MAX_LOGIT_BIAS), jnp.float32
                    ),
                )
                tokens = jax.ShapeDtypeStruct((size, bucket), jnp.int32)
                jobs.append((self._get_prefill(bucket), (
                    params_aval, cache_aval, tokens,
                    vec(size, jnp.int32), vec(size, jnp.int32),
                    *tables(size), counts_aval, *sampling,
                )))
                jobs.append((self._get_prefill_offset(bucket), (
                    params_aval, cache_aval, tokens,
                    vec(size, jnp.int32), vec(size, jnp.int32),
                    vec(size, jnp.int32), *tables(size),
                    counts_aval, *sampling,
                )))
            size *= 2
        scalar = jax.ShapeDtypeStruct((), jnp.int32)
        if self.paged:
            jobs.append((self._get_block_copy(), (
                params_aval, cache_aval, scalar, scalar,
            )))
        elif self.prefix_cache:
            for bucket in self.prefill_buckets:
                jobs.append((self._get_copy_prefix(bucket), (
                    params_aval, cache_aval, scalar, scalar, scalar,
                )))
        slots = self.max_slots
        step_variants = {self.decode_chunk, 1}
        if self.admission_chunk:
            step_variants.add(self.admission_chunk)
        # spec decode threads the per-slot token history (drafting
        # source) through the scan carry as one extra [S, max_seq] array
        history = (
            (jax.ShapeDtypeStruct(
                (slots, self.max_seq_len), jnp.int32
            ),)
            if self.spec else ()
        )
        for steps in step_variants:
            jobs.append((self._get_decode(steps), (
                params_aval, cache_aval,
                vec(slots, jnp.int32), vec(slots, jnp.int32),
                vec(slots, jnp.bool_), vec(slots, jnp.bool_),
                *history, *tables(slots), counts_aval,
                vec(slots, jnp.float32), vec(slots, jnp.int32),
                vec(slots, jnp.float32), vec(slots, jnp.float32),
                vec(slots, jnp.float32), vec(slots, jnp.uint32),
                jax.ShapeDtypeStruct(
                    (slots, self.MAX_LOGIT_BIAS), jnp.int32
                ),
                jax.ShapeDtypeStruct(
                    (slots, self.MAX_LOGIT_BIAS), jnp.float32
                ),
            )))
        if self.mixed:
            for width in self._mixed_widths:
                jobs.append((self._get_mixed(width), (
                    params_aval, cache_aval,
                    jax.ShapeDtypeStruct((slots, width), jnp.int32),
                    vec(slots, jnp.int32), vec(slots, jnp.int32),
                    vec(slots, jnp.bool_), vec(slots, jnp.bool_),
                    vec(slots, jnp.bool_),
                    jax.ShapeDtypeStruct(
                        (slots, self.max_blocks), jnp.int32
                    ),
                    counts_aval,
                    # mixed-step carry operands: the previous step's
                    # sampled tokens + the chain mask (zeros/False on a
                    # fresh dispatch — one program serves both)
                    vec(slots, jnp.int32), vec(slots, jnp.bool_),
                    vec(slots, jnp.float32), vec(slots, jnp.int32),
                    vec(slots, jnp.float32), vec(slots, jnp.float32),
                    vec(slots, jnp.float32), vec(slots, jnp.uint32),
                    jax.ShapeDtypeStruct(
                        (slots, self.MAX_LOGIT_BIAS), jnp.int32
                    ),
                    jax.ShapeDtypeStruct(
                        (slots, self.MAX_LOGIT_BIAS), jnp.float32
                    ),
                )))
        return jobs

    # lint: allow(owned-by-violation) -- pre-traffic by contract (see
    #   docstring): must run before the engine thread serves requests,
    #   while the device thread is idle or not yet started
    def precompile(self, workers: int = 4, execute: bool = True) -> None:
        """Compile-and-execute every (bucket, pow2-group-size) prefill
        variant and the decode chunks BEFORE serving traffic. Group sizes
        are timing-dependent (admission batching), so relying on warmup
        traffic to cover them is racy — a variant first seen under load
        stalls every active request for the whole compile. Dummy rows
        target slot 0, so this must run before real requests occupy the
        cache (call right after construction; ``start()`` is fine too
        since the engine thread is idle until the first submit).

        Two phases over the SAME job list (:meth:`_variant_jobs`):
        (1) every variant is lowered + compiled concurrently in a thread
        pool — on a big model a cold cache means tens of ~minute-long
        XLA compiles, and they parallelize well; the results land in the
        persistent compile cache. (2) each variant executes once
        sequentially with zero-filled args (its compile step now hits
        the cache), which also warms the jit call caches."""
        from concurrent.futures import ThreadPoolExecutor

        # phase 1's executables reach phase 2 (and later processes) only
        # through the persistent compile cache — without one configured,
        # parallel compilation would be pure waste, so default it
        if not jax.config.jax_compilation_cache_dir:
            jax.config.update(
                "jax_compilation_cache_dir", "/tmp/jax_compile_cache"
            )
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )

        jobs = self._variant_jobs()

        def build(job):
            fn, args = job
            with self.mesh:
                fn.lower(*args).compile()

        started = time.perf_counter()
        with ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(build, jobs))
        logger.info(
            "precompiled %d variants in %.1fs",
            len(jobs), time.perf_counter() - started,
        )
        if not execute:
            # cache-warming mode (bench BENCH_COMPILE_ONLY): every
            # variant's executable is in the persistent cache; skip the
            # execute-once pass (callers that never serve don't need
            # warm jit call caches or slot-0 garbage rows)
            return
        with self.mesh:
            for fn, avals in jobs:
                # real params + live cache (donated and rethreaded), zeros
                # for every data arg (incl. seeds — values are ignored).
                # Zero decode `active`/`write_mask` masks mean no cache row
                # is written; prefill windows write garbage into slot 0's
                # rows, which is why this must run before traffic.
                args: List[Any] = [self.params, self.cache]
                for spec in avals[2:]:
                    args.append(jnp.zeros(spec.shape, spec.dtype))
                outputs = fn(*args)
                self.cache = outputs[0]
            jax.block_until_ready(self.cache)

    # ------------------------------------------------------------------ #
    # public API (thread-safe)
    # ------------------------------------------------------------------ #
    def start(self) -> None:
        if self._crashed is not None:
            if self.on_crash is not None:
                raise api_errors.EngineRebuildingError(
                    "engine is rebuilding after a crash; retry shortly",
                    retry_after_s=2.0,
                )
            raise RuntimeError("decode engine crashed") from self._crashed
        if self._thread is not None:
            return
        # monotone bool handshake with the loop: start/stop own the
        # True/False transitions, the loop only reads it (and clears it
        # on crash exit); a stale read costs one idle-poll iteration
        # lint: allow(cross-thread-mutation) -- single-word flag store;
        #   readers tolerate one-iteration staleness by design
        self._running = True
        self._thread = threading.Thread(
            target=self._run_loop, name="jax-local-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._queue.put(None)
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        flight.record(
            "engine_stop",
            tokens=self.stats["tokens_generated"],
            requests=self.stats["requests"],
            decode_steps=self.stats["decode_steps"],
        )
        flight.flush()
        if self.mirror is not None:
            try:
                self.mirror.publish("stop", {}, [])
            except Exception:
                # writer already dead (follower dropped) — still close
                logger.warning("mirror: stop record not delivered")
            self.mirror.close()

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot: the submit queue plus the
        admission-pending list. Read from any thread (both reads are
        atomic snapshots); the fleet layer's routing/scaling signal and
        the ``jax_engine_queue_depth`` gauge."""
        return self._queue.qsize() + len(self._pending)

    def submit(self, request: GenerationRequest) -> None:
        if self._crashed is not None:
            if self.on_crash is not None:
                # supervised: the crash window is a bounded rebuild, not
                # a terminal state — callers get a typed retryable error
                # (503 + Retry-After on the HTTP surfaces), never a 500
                raise api_errors.EngineRebuildingError(
                    "engine is rebuilding after a crash; retry shortly",
                    retry_after_s=2.0,
                )
            raise RuntimeError("decode engine crashed") from self._crashed
        if request.replay_tokens and self.mirror is not None:
            # replay admission restores penalty counts with a dispatch
            # the follower replay protocol does not speak
            raise NotImplementedError(
                "session resurrection over the multi-host mirror is not "
                "supported"
            )
        bias = request.sampling.logit_bias
        if bias and len(bias) > self.MAX_LOGIT_BIAS:
            raise ValueError(
                f"logit_bias has {len(bias)} entries; this engine supports "
                f"at most {self.MAX_LOGIT_BIAS}"
            )
        # prompts longer than the largest bucket prefill in bucket-sized
        # windows (chunked prefill), so context length is the only limit
        limit = self.max_seq_len - 1
        if len(request.prompt_tokens) > limit:
            raise ValueError(
                f"prompt of {len(request.prompt_tokens)} tokens exceeds the "
                f"context limit of {limit} (max_seq_len {self.max_seq_len})"
            )
        # paged: no per-request block check needed — the constructor
        # guarantees the pool covers at least one max_seq_len sequence,
        # which bounds any single reservation
        # span/TTFT anchors: perf_counter for durations, wall for the
        # trace timeline (engine spans must align with gateway/runner
        # spans recorded on other clocks)
        request._submit_ts = time.perf_counter()  # type: ignore[attr-defined]
        request._submit_wall = time.time()        # type: ignore[attr-defined]
        self._queue.put(request)
        if self._crashed is not None:
            # crashed between the check above and the put: the loop will
            # never drain the queue again
            if self.on_crash is None:
                self._fail_all_pending()
            elif self._recovery_drained:
                # supervised AND the recovery drain already swept this
                # queue: nothing will ever read it again — fail any
                # strays (incl. this request, unless the drain captured
                # it, in which case its future rides the resurrection)
                # with the typed retryable error so no caller hangs
                self._fail_stragglers()

    async def generate(
        self,
        prompt_tokens: List[int],
        sampling: SamplingParams,
        *,
        stop_tokens: Optional[Set[int]] = None,
        on_token: Optional[Callable[[int, bool], None]] = None,
        session_id: Optional[str] = None,
        handle: Optional[List[GenerationRequest]] = None,
        trace_id: Optional[str] = None,
        request_fields: Optional[Dict[str, Any]] = None,
    ) -> GenerationResult:
        """Asyncio entry: submit and await the result. Pass ``handle``
        (an empty list) to receive the live request — its ``cancel()``
        ends generation at the next token boundary (used by the service
        layer for stop-string matches and disconnected clients).
        ``request_fields`` sets extra :class:`GenerationRequest` fields
        before submit — the disaggregation seam (``export_handoff`` on
        the prefill leg; ``kv_import``/``replay_tokens``/``prompt_len``
        on the decode leg's warm admission)."""
        loop = asyncio.get_running_loop()
        future: "asyncio.Future[GenerationResult]" = loop.create_future()
        request = GenerationRequest(
            prompt_tokens=list(prompt_tokens),
            sampling=sampling,
            stop_tokens=stop_tokens or set(),
            on_token=on_token,
            session_id=session_id,
            future=future,
            loop=loop,
            trace_id=trace_id,
        )
        if request_fields:
            for key, value in request_fields.items():
                setattr(request, key, value)
        if handle is not None:
            handle.append(request)
        self.start()
        self.submit(request)
        try:
            return await future
        except asyncio.CancelledError:
            # caller gave up (client disconnect, task cancelled): free
            # the slot at the next token boundary instead of decoding a
            # full answer nobody reads
            request.cancel()
            raise

    # ------------------------------------------------------------------ #
    # engine thread
    # ------------------------------------------------------------------ #
    def _run_loop(self) -> None:
        logger.info(
            "engine started: %d slots × %d ctx, mesh %s",
            self.max_slots, self.max_seq_len, dict(self.mesh.shape),
        )
        try:
            with self.mesh:
                inflight = None
                while self._running:
                    self._drain_queue(
                        block=not self._any_active()
                        and not self._pending
                        and inflight is None
                        and not self._prefill_inflight
                        and not self._any_admitting()
                    )
                    if not self._running:
                        break
                    if (
                        self._pending
                        and inflight is None
                        and any(not s.active for s in self.slots)
                    ):
                        # admission linger: give a burst of submissions a
                        # beat to land so prefill batches fill up and decode
                        # waves stay aligned (amortizes dispatch latency).
                        # Skipped while a chunk is in flight — lingering
                        # then would add 3 ms to THAT chunk's harvest
                        # latency, taxing every running stream's TPOT for
                        # a batching benefit the next dispatch gets anyway
                        time.sleep(0.003)
                        self._drain_queue(block=False)
                    # dispatch prefills WITHOUT blocking: they queue behind
                    # the in-flight decode chunk and overlap with the next
                    # ones; their slots join decode once harvested. (mixed
                    # mode: admission only parks the slot at its watermark
                    # — the windows ride the decode steps below)
                    self._admit()
                    if inflight is not None:
                        # overlap: chain the next chunk off the device-side
                        # carry BEFORE blocking on this one's tokens
                        chained = None
                        if inflight.get("mixed"):
                            # mixed-step carry: the next window's content
                            # is host-predictable from the watermark
                            # bookkeeping advanced at dispatch, so plan
                            # step N+1 and dispatch it off N's device
                            # outputs; any contradiction falls back to
                            # the host-built dispatch (and is counted)
                            plan_next = self._plan_mixed_chain(inflight)
                            if isinstance(plan_next, dict):
                                chained = self._dispatch_mixed(
                                    carry=inflight, plan_next=plan_next
                                )
                            else:
                                self._note_carry_invalidation(plan_next)
                        elif self.pipeline_decode and self._can_chain(
                            inflight
                        ):
                            chained = self._dispatch_decode(carry=inflight)
                        self._process_decode(inflight)
                        inflight = chained
                    # pick up finished prefills; block for the oldest one
                    # only when decode has nothing to run anyway
                    self._harvest_prefills(
                        block=inflight is None and not self._any_ready()
                        and not self._any_admitting()
                    )
                    if inflight is None and (
                        self._any_ready() or self._any_admitting()
                    ):
                        inflight = self._dispatch_decode()
                        if not self.pipeline_decode or (
                            inflight.get("mixed") and not self.mixed_carry
                        ):
                            # unpipelined engines (and mixed engines with
                            # the carry off) process immediately: the
                            # next window's content then depends on THIS
                            # step's completion bookkeeping
                            self._process_decode(inflight)
                            inflight = None
                            self._harvest_prefills(block=False)
        except BaseException as exc:  # noqa: BLE001
            logger.exception("engine loop crashed")
            # flip the crash flag BEFORE failing waiters so a racing
            # submit() either lands in the drained queue below or raises
            self._crashed = exc
            self._running = False
            # the flight artifact is the crash's on-disk evidence —
            # flush BEFORE failing waiters (their callbacks may tear the
            # process down)
            flight.record("engine_crash", error=repr(exc)[:512])
            flight.flush()
            if self.on_crash is not None:
                # supervised: live sessions stay parked in the queue /
                # _pending / slots for the supervisor to resurrect onto
                # a rebuilt engine — the hook runs the whole detect →
                # heal arc on this (already dead) thread, then the
                # thread exits quietly (the crash is already logged,
                # flight-recorded, and handled; re-raising would only
                # spam threading's excepthook mid-recovery)
                self.on_crash(exc)
                return
            self._fail_all_pending()
            raise

    def _any_active(self) -> bool:
        return any(slot.active for slot in self.slots)

    def _any_ready(self) -> bool:
        return any(slot.ready for slot in self.slots)

    def _any_admitting(self) -> bool:
        """Mixed mode: slots parked at a prefill watermark, waiting for
        decode steps to carry their prompt windows."""
        return self.mixed and any(
            slot.prefill_pos is not None and slot.request is not None
            for slot in self.slots
        )

    def _drain_queue(self, block: bool) -> None:
        try:
            if block:
                # idle: the engine is between busy phases — the next
                # mixed step's inter-dispatch gap would measure idle
                # time, not the per-step host tax (see _process_mixed)
                self._last_mixed_end = 0.0
                started = time.perf_counter()
                try:
                    item = self._queue.get(timeout=0.05)
                finally:
                    self.stats["idle_time"] += time.perf_counter() - started
            else:
                item = self._queue.get_nowait()
            if item is not None:
                self._pending.append(item)
        except queue.Empty:
            return
        while True:
            try:
                item = self._queue.get_nowait()
                if item is not None:
                    self._pending.append(item)
            except queue.Empty:
                return

    def _find_warm_slot(self, request: GenerationRequest) -> Optional[int]:
        if request.session_id is None:
            return None
        for i, slot in enumerate(self.slots):
            if (
                not slot.active
                and slot.session_id == request.session_id
                and slot.history is not None
            ):
                return i
        return None

    def _find_slot(
        self, request: GenerationRequest, exclude: frozenset = frozenset()
    ) -> Optional[int]:
        """``exclude`` protects slots serving as cross-slot prefix-copy
        sources this admission round: their rows must stay intact until
        the copies dispatch (after the cold batch), so they cannot be
        handed out or evicted in the same round."""
        # session hit first
        warm = self._find_warm_slot(request)
        if warm is not None:
            return warm
        for i, slot in enumerate(self.slots):
            if (
                not slot.active
                and slot.session_id is None
                and i not in exclude
            ):
                return i
        # evict the least-recently USED pinned session (a hot session's
        # warm cache survives slot pressure; the stalest one pays)
        victim: Optional[int] = None
        for i, slot in enumerate(self.slots):
            if not slot.active and i not in exclude and (
                victim is None
                or slot.last_used < self.slots[victim].last_used
            ):
                victim = i
        return victim

    # a PARTIAL prefix match must cover at least this many tokens to be
    # worth a warm admission (below it, warm ≈ cold anyway); full
    # extensions of the pinned history always qualify
    WARM_MIN_PREFIX = 16
    # warm-first admission fairness: after this many jump-aheads the
    # queue head is admitted regardless, so warm traffic can't starve it
    MAX_HEAD_SKIPS = 4
    # sparse per-request logit_bias entries threaded to the device as
    # [batch, MAX_LOGIT_BIAS] (id, value) pairs; padding = (0, 0.0),
    # a harmless +0 on token 0
    MAX_LOGIT_BIAS = 64

    def _session_warm(self, index: int, request: GenerationRequest):
        """Return the reusable prefix length for a warm admission, or
        None for cold.

        Longest-common-prefix reuse (the block-prefix-cache idea): chat
        templates re-render earlier turns with role markers the raw
        generated tokens don't carry, so a follow-up prompt usually
        EXTENDS only part of the pinned history before diverging. The
        shared prefix stays in the KV cache; prefill resumes from the
        divergence point and overwrites the stale rows beyond it."""
        slot = self.slots[index]
        prompt = request.prompt_tokens
        if not (
            request.session_id is not None
            and slot.session_id == request.session_id
            and slot.history
        ):
            return None
        lcp = self._lcp(prompt, slot.history)
        if lcp == len(prompt):
            # the prompt is entirely inside the cache: re-prefill the
            # last token so fresh logits exist for the first sample
            lcp = len(prompt) - 1
        if lcp <= 0:
            return None
        full_extension = lcp == len(slot.history)
        if not full_extension and lcp < self.WARM_MIN_PREFIX:
            return None
        return lcp

    @staticmethod
    def _lcp(a: List[int], b: List[int]) -> int:
        """Longest common prefix of two token lists (chunked slice
        compares so the common case runs at C speed)."""
        limit = min(len(a), len(b))
        lcp = 0
        while lcp < limit:
            n = min(64, limit - lcp)
            if a[lcp:lcp + n] == b[lcp:lcp + n]:
                lcp += n
                continue
            while lcp < limit and a[lcp] == b[lcp]:
                lcp += 1
            break
        return lcp

    def _find_prefix_source(
        self,
        request: GenerationRequest,
        cold_reserved: frozenset,
        warm_reserved: frozenset,
    ) -> Optional[Tuple[int, int, bool]]:
        """Best cross-slot prefix source for a sessionless-cold request:
        the slot whose cache holds the longest common prefix with the
        prompt. Returns (source slot, lcp, in_round) or None.

        Eligible sources, by dispatch-ordering safety:
        - this round's cold reservations (``in_round=True``) — their
          prefill batch dispatches BEFORE the copies, and their
          prompt is known from the reserved request (this is what makes
          n>1 choices submitted together share one prefill);
        - slots with ``history`` set and no undispatched reservation:
          decoding slots (decode writes only at positions ≥ length),
          prefilling slots (their prefill is already dispatched), and
          idle pinned sessions (protected from same-round eviction via
          ``_find_slot``'s exclude set).
        Warm reservations are skipped: their cache is mid-transition."""
        prompt = request.prompt_tokens
        # the best any source can reach: the full prompt minus the
        # last token (which is always re-prefilled for fresh logits)
        full = len(prompt) - 1
        best: Optional[Tuple[int, int, bool]] = None
        for i, slot in enumerate(self.slots):
            if i in cold_reserved:
                history = slot.request.prompt_tokens if slot.request else None
                in_round = True
            elif i in warm_reserved:
                continue
            else:
                history = slot.history
                in_round = False
                if slot.length < self.WARM_MIN_PREFIX:
                    # copyable rows are capped at slot.length, so this
                    # slot can never clear the reuse threshold — skip
                    # the O(prompt_len) LCP entirely
                    continue
            if not history:
                continue
            lcp = self._lcp(prompt, history)
            if not in_round:
                # an ACTIVE slot's newest history token has no KV row
                # yet — it is written by the NEXT decode dispatch (the
                # finish path trims history[:length] for the same
                # reason); only rows [0:length) are copyable
                lcp = min(lcp, slot.length)
            if lcp == len(prompt):
                # re-prefill the last token so fresh logits exist for
                # the first sample (same rule as the session-warm path)
                lcp = len(prompt) - 1
            if lcp < self.WARM_MIN_PREFIX:
                continue
            if best is None or lcp > best[1]:
                best = (i, lcp, in_round)
                if lcp >= full:
                    # full-prefix match: nothing can beat it — stop
                    # rescanning the remaining slots (the old scan was
                    # O(slots × prompt_len) per cold admission)
                    break
        return best

    def _drop_cancelled(self) -> None:
        """Resolve cancelled-before-admission requests without ever
        spending a slot or a prefill on them."""
        if any(r.cancelled for r in self._pending):
            keep: List[GenerationRequest] = []
            for queued in self._pending:
                if queued.cancelled:
                    self._resolve_cancelled(queued)
                else:
                    keep.append(queued)
            self._pending = keep

    def _shed_expired(self) -> None:
        """Admission deadlines (serve ``--queue-timeout-s``): a pending
        request older than the deadline fails FAST with a typed
        :class:`~langstream_tpu.api.errors.QueueTimeoutError` instead of
        starving in ``_pending`` while its caller times out anyway —
        load shedding under sustained overload."""
        timeout = self.queue_timeout_s
        if not timeout or not self._pending:
            return
        now = time.perf_counter()
        keep: List[GenerationRequest] = []
        for request in self._pending:
            waited = now - getattr(request, "_submit_ts", now)
            if waited < timeout:
                keep.append(request)
            else:
                self._shed(request, waited)
        self._pending = keep

    def _shed(self, request: GenerationRequest, waited: float) -> None:
        shed = self.stats["requests_shed"]
        shed["queue_timeout"] = shed.get("queue_timeout", 0) + 1
        self.stats["requests"] += 1
        # Retry-After ≈ when a slot plausibly frees: the backlog this
        # request would wait behind × the EWMA decode-step time (a
        # coarse lower bound — better than a constant, cheap to compute)
        step_s = self._step_ewma if self._step_ewma else 0.05
        retry_after = max(1.0, len(self._pending) * step_s)
        flight.record(
            "request_shed",
            reason="queue_timeout",
            waited_s=round(waited, 3),
            queue_depth=len(self._pending),
            retry_after_s=round(retry_after, 3),
            trace_id=request.trace_id or "",
        )
        fail_request_future(request, api_errors.QueueTimeoutError(
            f"request waited {waited:.2f}s in the admission queue "
            f"(queue timeout {self.queue_timeout_s}s); shed before "
            "admission — retry later",
            retry_after_s=retry_after,
        ))

    def _admit(self) -> None:
        """Move pending requests into slots. Cold requests sharing a prompt
        bucket are prefilled in ONE batched device call, and warm-session
        follow-ups sharing a suffix bucket likewise batch into one
        prefill-at-offset dispatch (batches split into power-of-two group
        sizes so compilations stay bounded)."""
        if self.mixed:
            return self._admit_mixed()
        if self.paged:
            return self._admit_paged()
        self._shed_expired()
        self._drop_cancelled()
        while self._pending:
            cold: List[Tuple[int, GenerationRequest]] = []
            cold_bucket: Optional[int] = None
            # suffix bucket -> [(slot index, request, reused prefix len)]
            warm: Dict[int, List[Tuple[int, GenerationRequest, int]]] = {}
            # cross-slot prefix copies this round: (src, dst, lcp).
            # When copies exist, round-end dispatch order is cold batch
            # -> copies -> long-warm -> warm suffix prefills, so a copy
            # always reads rows whose writes are already dispatched and
            # never rows a warm prefill is about to overwrite. Without
            # copies the old warm-first order is kept (better warm TTFT).
            copies: List[Tuple[int, int, int]] = []
            # session follow-ups with chunked (long) suffixes; deferred
            # to round end for the same reason — an inline dispatch
            # could overwrite a source's rows before a queued copy reads
            # them
            long_warm: List[Tuple[int, GenerationRequest, int]] = []
            sources: set = set()        # slots protected from eviction
            cold_reserved: set = set()  # this round's cold slot indices
            warm_reserved: set = set()  # this round's warm slot indices
            progressed = False
            while self._pending:
                # admit warm-eligible requests FIRST: a strictly-FIFO
                # admission lets a burst of cold requests evict pinned
                # sessions whose follow-ups sit right behind them in the
                # same queue (measured: zero reuse at 2× slot pressure).
                # Bounded both ways: the scan looks at most 2×slots deep
                # (deeper entries are nowhere near admission), and a head
                # request skipped MAX_HEAD_SKIPS times is force-admitted
                # so sustained warm traffic cannot starve cold arrivals.
                position, index, reused = 0, None, None
                head = self._pending[0]
                if getattr(head, "_skipped", 0) < self.MAX_HEAD_SKIPS:
                    depth = max(2 * self.max_slots, 8)
                    for p, queued in enumerate(self._pending[:depth]):
                        warm_index = self._find_warm_slot(queued)
                        if warm_index is None:
                            continue
                        lcp = self._session_warm(warm_index, queued)
                        if lcp is not None:
                            position, index, reused = p, warm_index, lcp
                            break
                request = self._pending[position]
                if index is None:
                    index = self._find_slot(request, frozenset(sources))
                    if index is not None:
                        reused = self._session_warm(index, request)
                if index is None:
                    break
                if position > 0:
                    head._skipped = getattr(head, "_skipped", 0) + 1
                largest = self.prefill_buckets[-1]
                if reused is not None:
                    slot = self.slots[index]
                    suffix = len(request.prompt_tokens) - reused
                    suffix_bucket = _bucket(suffix, self.prefill_buckets)
                    self._pending.pop(position)
                    slot.request = request  # reserve the slot
                    self.stats["session_hits"] += 1
                    warm_reserved.add(index)
                    if (
                        suffix > largest
                        or reused + suffix_bucket > self.max_seq_len
                    ):
                        # too big for one batched window, or a window at
                        # the reused offset would clamp past max_seq_len
                        # — the chunked path's overlap-shifted tail
                        # handles both (dispatched at round end)
                        long_warm.append((index, request, reused))
                        continue
                    warm.setdefault(suffix_bucket, []).append(
                        (index, request, reused)
                    )
                    continue
                prompt_len = len(request.prompt_tokens)
                if self.prefix_cache:
                    found = self._find_prefix_source(
                        request,
                        frozenset(cold_reserved),
                        frozenset(warm_reserved),
                    )
                else:
                    found = None
                if found is not None:
                    src, lcp, in_round = found
                    suffix = prompt_len - lcp
                    suffix_bucket = _bucket(suffix, self.prefill_buckets)
                    needs_long = (
                        suffix > largest
                        or lcp + suffix_bucket > self.max_seq_len
                    )
                    if src == index:
                        # the chosen slot itself holds the prefix (e.g.
                        # an evicted session's cache salvaged by a new
                        # request with the same template): rows already
                        # in place, no copy
                        self._pending.pop(position)
                        self.slots[index].request = request
                        self.stats["prefix_hits"] += 1
                        self.stats["prefix_tokens_reused"] += lcp
                        if needs_long:
                            self._prefill_long(index, request, lcp)
                            progressed = True
                        else:
                            warm.setdefault(suffix_bucket, []).append(
                                (index, request, lcp)
                            )
                            warm_reserved.add(index)
                        continue
                    if needs_long and not in_round:
                        # chunked suffix dispatches inline, so the copy
                        # must too (the source's rows are all from
                        # already-dispatched work — safe to read now)
                        self._pending.pop(position)
                        self.slots[index].request = request
                        self._dispatch_prefix_copy(src, index, lcp)
                        self._prefill_long(index, request, lcp)
                        progressed = True
                        continue
                    if not needs_long:
                        self._pending.pop(position)
                        self.slots[index].request = request
                        copies.append((src, index, lcp))
                        sources.add(src)
                        warm.setdefault(suffix_bucket, []).append(
                            (index, request, lcp)
                        )
                        warm_reserved.add(index)
                        continue
                    # needs_long with an in-round source: the source's
                    # prefill hasn't dispatched yet — fall through cold
                if prompt_len > largest:
                    self._pending.pop(position)
                    self.slots[index].request = request  # reserve the slot
                    self._prefill_long(index, request, 0)
                    progressed = True
                    continue
                bucket = _bucket(prompt_len, self.prefill_buckets)
                if cold_bucket is None:
                    cold_bucket = bucket
                elif bucket != cold_bucket:
                    break  # different bucket: next outer round
                self._pending.pop(position)
                self.slots[index].request = request  # reserve the slot
                cold.append((index, request))
                cold_reserved.add(index)
                # batch caps at the largest power of two ≤ max_slots
                if len(cold) >= self.max_slots:
                    break
            if copies:
                # cold batch FIRST so same-round copies can source from
                # it, then the copies, then every warm suffix prefill
                # (which overwrites rows past each slot's reused point —
                # including, for long_warm, rows a copy may have read)
                if cold:
                    self._prefill_batch(cold, cold_bucket)
                    progressed = True
                for src, dst, lcp in copies:
                    self._dispatch_prefix_copy(src, dst, lcp)
                for index, request, reused in long_warm:
                    self._prefill_long(index, request, reused)
                    progressed = True
                for suffix_bucket, batch in warm.items():
                    self._prefill_warm_batch(batch, suffix_bucket)
                    progressed = True
            else:
                # no ordering constraint: keep warm-first (lower warm
                # TTFT — a warm suffix is much cheaper than a cold batch)
                for index, request, reused in long_warm:
                    self._prefill_long(index, request, reused)
                    progressed = True
                for suffix_bucket, batch in warm.items():
                    self._prefill_warm_batch(batch, suffix_bucket)
                    progressed = True
                if cold:
                    self._prefill_batch(cold, cold_bucket)
                    progressed = True
            if not progressed:
                return

    def _admit_paged(self) -> None:
        """Paged-layout admission. Block-granular matching against the
        persistent prefix cache replaces the dense path's slot-resident
        LCP scan (and its copy-ordering machinery — shared blocks are
        REFERENCED through the table, never copied), so a shared RAG or
        system prefix survives any slot turnover. Every request reserves
        its worst case (prompt + max_new, capped at max_seq_len) up
        front, so the decode path never allocates and cannot stall on
        pool pressure mid-flight; when the pool (after LRU eviction)
        cannot cover a reservation, the request simply stays pending
        until running requests release blocks.

        Round dispatch order is cold batch → long prefills → warm
        suffixes: a suffix admitted onto blocks published this round
        always reads rows whose writes are already dispatched."""
        self._shed_expired()
        self._drop_cancelled()
        self._import_pending_handoffs()
        largest = self.prefill_buckets[-1]
        while self._pending:
            cold: List[Tuple[int, GenerationRequest]] = []
            cold_bucket: Optional[int] = None
            # suffix bucket -> [(slot, request, resume offset)]
            warm: Dict[int, List[Tuple[int, GenerationRequest, int]]] = {}
            long_entries: List[Tuple[int, GenerationRequest, int]] = []
            progressed = False
            while self._pending:
                # warm-first session scan (shared with _admit_mixed)
                position, index, session_lcp = self._scan_admission()
                request = self._pending[position]
                if index is None:
                    break
                # probe the resume offset WITHOUT committing, so the
                # cold-bucket grouping check can end the round before
                # any blocks move (match() only touches LRU ticks); the
                # probe's match is handed to _paged_reserve so the
                # O(prompt_len) chain walk runs once per admission
                prompt_len = len(request.prompt_tokens)
                probe_match = None
                host_probe: List[Any] = []
                if session_lcp is not None:
                    probe = session_lcp
                elif self.prefix_cache:
                    probe_match = self.kv_manager.match(
                        request.prompt_tokens
                    )
                    # host-tier continuation after the HBM prefix scan:
                    # demoted chain entries extend the probe exactly as
                    # resident blocks would (reserve promotes them)
                    host_probe = self._host_probe(
                        request.prompt_tokens, probe_match
                    )
                    probe = (
                        probe_match[1] + len(host_probe) * self.block_size
                    )
                    while probe >= prompt_len:
                        if host_probe:
                            host_probe.pop()
                        probe -= self.block_size
                else:
                    probe = 0
                suffix = prompt_len - probe
                needs_long = suffix > largest or (
                    probe > 0
                    and probe + _bucket(suffix, self.prefill_buckets)
                    > self.max_seq_len
                )
                if probe == 0 and not needs_long:
                    bucket = _bucket(prompt_len, self.prefill_buckets)
                    if cold_bucket is None:
                        cold_bucket = bucket
                    elif bucket != cold_bucket:
                        break  # different bucket: next outer round
                resume = self._paged_reserve(
                    index, request, session_lcp, probe_match,
                    host_entries=host_probe,
                )
                if resume is None:
                    # pool exhausted even after eviction: every block is
                    # referenced by running work — wait for releases
                    break
                if position > 0:
                    head = self._pending[0]
                    head._skipped = getattr(head, "_skipped", 0) + 1
                self._pending.pop(position)
                self.slots[index].request = request  # reserve the slot
                if session_lcp is not None:
                    self.stats["session_hits"] += 1
                if resume < probe:
                    # a torn promotion fell back toward cold: the
                    # probe-based cold/warm grouping above no longer
                    # holds, so route through the long path — it
                    # handles ANY resume offset without disturbing the
                    # round's cold-bucket invariant
                    long_entries.append((index, request, resume))
                elif needs_long:
                    long_entries.append((index, request, resume))
                elif resume == 0:
                    cold.append((index, request))
                    if len(cold) >= self.max_slots:
                        break
                else:
                    warm.setdefault(
                        _bucket(prompt_len - resume, self.prefill_buckets),
                        [],
                    ).append((index, request, resume))
            if cold:
                self._prefill_batch(cold, cold_bucket)
                progressed = True
            for index, request, resume in long_entries:
                self._prefill_long(index, request, resume)
                progressed = True
            for suffix_bucket, batch in warm.items():
                self._prefill_warm_batch(batch, suffix_bucket)
                progressed = True
            if not progressed:
                return

    def _scan_admission(self):
        """Warm-first admission selection shared by the paged admission
        paths: prefer a pending request with a warm session slot (scan
        bounded to 2×slots deep; a head skipped MAX_HEAD_SKIPS times is
        force-admitted so warm traffic can't starve cold arrivals),
        else the queue head into any free/evictable slot. Returns
        (position in _pending, slot index or None, session lcp or
        None) — ONE policy, so mixed- and split-mode admission
        ordering can never diverge under identical traffic (the A/B's
        equal-traffic premise)."""
        position, index, session_lcp = 0, None, None
        head = self._pending[0]
        if getattr(head, "_skipped", 0) < self.MAX_HEAD_SKIPS:
            depth = max(2 * self.max_slots, 8)
            for p, queued in enumerate(self._pending[:depth]):
                warm_index = self._find_warm_slot(queued)
                if warm_index is None:
                    continue
                lcp = self._session_warm(warm_index, queued)
                if lcp is not None:
                    position, index, session_lcp = p, warm_index, lcp
                    break
        request = self._pending[position]
        if index is None:
            index = self._find_slot(request)
            if index is not None:
                session_lcp = self._session_warm(index, request)
        return position, index, session_lcp

    def _admit_mixed(self) -> None:
        """Token-budget admission (``prefill_mode: mixed``): a request
        claims a slot and its worst-case block reservation exactly like
        the split paged path, but NO prefill dispatch happens here —
        the slot parks as ADMITTING (``prefill_pos`` watermark) and
        successive mixed decode steps carry ``prefill_chunk``-token
        windows of its prompt alongside the decoding rows
        (:meth:`_dispatch_mixed`), so no stream ever stalls behind a
        monolithic bucket-sized prefill. Cold prompts are NOT published
        at admission: their blocks fill across several dispatches, and
        a duplicate matching the chain early would attend over rows not
        yet written (the split path's cold-batch-before-warm-suffix
        dispatch ordering does not exist here) — they publish at finish
        like every partially-matched prompt."""
        self._shed_expired()
        self._drop_cancelled()
        self._import_pending_handoffs()
        while self._pending:
            position, index, session_lcp = self._scan_admission()
            request = self._pending[position]
            if index is None:
                return
            probe_match = None
            host_probe: List[Any] = []
            if session_lcp is None and self.prefix_cache:
                probe_match = self.kv_manager.match(request.prompt_tokens)
                host_probe = self._host_probe(
                    request.prompt_tokens, probe_match
                )
            resume = self._paged_reserve(
                index, request, session_lcp, probe_match,
                publish_cold=False, host_entries=host_probe,
            )
            if resume is None:
                # pool exhausted even after eviction: every block is
                # referenced by running work — wait for releases
                return
            if position > 0:
                head = self._pending[0]
                head._skipped = getattr(head, "_skipped", 0) + 1
            self._pending.pop(position)
            slot = self.slots[index]
            slot.request = request
            if session_lcp is not None:
                self.stats["session_hits"] += 1
            self._assign_slot(index, request, reused=resume)
            slot.prefilling = True
            slot.prefill_pos = resume
            slot.prefill_reused = resume
            self._admit_seq += 1
            slot.prefill_seq = self._admit_seq
            slot.prefill_t0 = time.perf_counter()
            flight.record(
                "mixed_admit",
                slot=index,
                prompt_tokens=len(request.prompt_tokens),
                reused_tokens=resume,
                queue_depth=len(self._pending),
            )

    def _paged_reserve(
        self,
        index: int,
        request: GenerationRequest,
        session_lcp: Optional[int],
        match: Optional[Tuple[List[int], int]] = None,
        publish_cold: bool = True,
        host_entries: Optional[List[Any]] = None,
    ) -> Optional[int]:
        """Commit pool blocks for a request before it is admitted.
        Returns the resume offset — tokens already resident for this
        slot (session continuation, prefix-cache hit, or host-tier
        promotion) — or None when the pool cannot cover the
        reservation.

        ``host_entries`` is the host-tier continuation of ``match``
        (``_host_probe``): after the worst-case fresh allocation, those
        entries are scattered H2D into the first fresh blocks and
        published (publish-at-commit); a torn promotion aborts before
        anything publishes and the admission degrades to cold prefill.

        Copy-on-write happens here: a session follow-up that diverges
        mid-block gets a private copy of the boundary block, and shared
        blocks in the overwrite region are swapped for fresh ones (a
        full overwrite needs no copy) — published chains are never
        written after publication."""
        slot = self.slots[index]
        manager = self.kv_manager
        size = self.block_size
        prompt = request.prompt_tokens
        need_tokens = min(
            len(prompt) + request.sampling.max_new_tokens, self.max_seq_len
        )
        need_blocks = -(-need_tokens // size)
        if session_lcp is not None and slot.blocks:
            blocks = list(slot.blocks)
            keep_full, partial = divmod(session_lcp, size)
            replace: List[int] = []
            cow: Optional[int] = None
            if (
                partial
                and keep_full < len(blocks)
                and manager.is_shared(blocks[keep_full])
            ):
                cow = keep_full
                replace.append(keep_full)
            start_full = keep_full + (1 if partial else 0)
            for j in range(start_full, min(len(blocks), need_blocks)):
                if manager.is_shared(blocks[j]):
                    replace.append(j)
            extend = max(0, need_blocks - len(blocks))
            fresh = manager.allocate(len(replace) + extend)
            if fresh is None:
                return None
            for j, new in zip(replace, fresh):
                if j == cow:
                    self._dispatch_block_copy(blocks[j], new)
                manager.unref(blocks[j])
                blocks[j] = new
            blocks.extend(fresh[len(replace):])
            for extra in blocks[need_blocks:]:
                manager.unref(extra)  # shrink vs the previous reservation
            slot.blocks = blocks[:need_blocks]
            resume = session_lcp
        else:
            if slot.blocks:
                # evicting a pinned session (or leftover) for a new owner
                if slot.session_id is not None:
                    self._note_eviction(slot.session_id, slot.length)
                manager.release(slot.blocks)
                slot.blocks = None
                slot.session_id = None
                slot.history = None
                slot.length = 0
            matched: List[int] = []
            matched_tokens = 0
            if self.prefix_cache:
                # the admission loop's probe already walked the chain;
                # nothing can change it between probe and commit (same
                # engine-thread iteration, no allocation in between)
                matched, matched_tokens = (
                    (list(match[0]), match[1]) if match is not None
                    else manager.match(prompt)
                )
            promote = list(host_entries or [])
            # re-prefill at least the last prompt token so fresh logits
            # exist for the first sample (same rule as the dense paths).
            # Host-tier entries trim first: they continue the HBM chain,
            # so they are the chain's tail
            total = matched_tokens + size * len(promote)
            while promote and total >= len(prompt):
                promote.pop()
                total -= size
            while matched and matched_tokens >= len(prompt):
                matched.pop()
                matched_tokens -= size
            manager.ref(matched)
            fresh = manager.allocate(need_blocks - len(matched))
            if fresh is None:
                manager.release(matched)
                return None
            promoted = 0
            if promote:
                # worst-case-reserved promotion: the fresh allocation
                # above already covers every non-matched block, so the
                # H2D scatter targets the first len(promote) of them —
                # an abort leaves them private cold blocks (no client
                # error, no publish, no id recycled mid-chain)
                promoted = self._promote_host_chain(
                    prompt, matched, matched_tokens, promote, fresh
                )
            slot.blocks = matched + fresh
            if matched_tokens:
                self.stats["prefix_hits"] += 1
                self.stats["prefix_tokens_reused"] += matched_tokens
                manager.stats["hit_tokens"] += matched_tokens
            if promoted:
                self.stats["prefix_tokens_reused"] += promoted * size
                # journey admit class: the host tier (not cold prefill,
                # not a pure HBM hit) is what served this admission
                if getattr(request, "_jt_admit_class", None) is None:
                    request._jt_admit_class = (  # type: ignore[attr-defined]
                        "host-promote"
                    )
            if (
                self.prefix_cache and publish_cold
                and not matched_tokens and not promoted
            ):
                # publish a fully-cold prompt's blocks NOW so same-round
                # duplicates share them — safe because the cold batch
                # (which writes every one of these blocks) dispatches
                # before any warm suffix this round. Partially-matched
                # prompts publish their divergent tail at finish instead
                # (their suffix prefill dispatches in the warm wave).
                # Mixed admission passes publish_cold=False: its blocks
                # fill across several dispatches, so early publication
                # would let a duplicate read unwritten rows. (A promoted
                # admission already published its promoted chain —
                # publishing the unwritten tail here would expose it.)
                manager.publish(prompt, slot.blocks)
            resume = matched_tokens + promoted * size
        table = self._block_tables[index]
        table[:] = 0
        table[: len(slot.blocks)] = slot.blocks
        return resume

    @staticmethod
    def _pow2_groups(batch: List[Any]) -> List[List[Any]]:
        """Split into power-of-two group sizes (no padding rows — a
        padding row would have to scatter somewhere in the cache) so the
        per-(bucket, batch-size) compilation count stays logarithmic."""
        groups: List[List[Any]] = []
        remaining = batch
        while remaining:
            size = 1
            while size * 2 <= len(remaining):
                size *= 2
            groups.append(remaining[:size])
            remaining = remaining[size:]
        return groups

    MAX_EVICTED_SESSIONS = 512

    def _note_eviction(self, session_id: str, cached_tokens: int) -> None:
        """Remember a pinned session whose warm cache was evicted, so a
        later follow-up's re-prefill is booked as eviction-induced
        recompute in the goodput ledger (bounded FIFO)."""
        if cached_tokens <= 0:
            return
        evicted = self._evicted_sessions
        evicted.pop(session_id, None)
        while len(evicted) >= self.MAX_EVICTED_SESSIONS:
            evicted.pop(next(iter(evicted)))
        evicted[session_id] = cached_tokens

    def _waste(self, reason: str, tokens: int) -> None:
        if tokens > 0:
            wasted = self.stats["tokens_wasted"]
            wasted[reason] = wasted.get(reason, 0) + tokens

    def _assign_slot(
        self, index: int, request: GenerationRequest, reused: int = 0
    ) -> None:
        """Reset a slot's bookkeeping for a newly admitted request.
        ``reused`` = cache tokens this admission did NOT re-prefill
        (session continuation / prefix copy / paged prefix hit)."""
        # journey ledger anchors: the single admission point for every
        # path (cold, mixed, session, handoff) stamps the queue→prefill
        # boundary and the admission class (unless an earlier stage —
        # handoff import, host promotion — already classified it)
        request._admit_wall = time.time()  # type: ignore[attr-defined]
        if getattr(request, "_jt_admit_class", None) is None:
            request._jt_admit_class = (  # type: ignore[attr-defined]
                "hbm-hit" if reused > 0 else "cold"
            )
        slot = self.slots[index]
        if (
            slot.session_id is not None
            and slot.session_id != request.session_id
            and slot.history
        ):
            # a new owner is evicting this pinned session's warm cache
            self._note_eviction(slot.session_id, slot.length)
        if request.session_id is not None:
            cached = self._evicted_sessions.pop(request.session_id, None)
            if cached:
                # tokens the follow-up must re-prefill that its evicted
                # warm cache (or a prefix hit standing in for it) would
                # have served — upper-bounded by the stored history
                self._waste(
                    "evicted_recompute",
                    min(cached, len(request.prompt_tokens)) - reused,
                )
        slot.generated = []
        slot.logprobs = []
        slot.tops = [] if self.logprobs_topk else None
        slot.history = list(request.prompt_tokens)
        slot.session_id = None
        slot.length = len(request.prompt_tokens)
        slot.last_used = time.monotonic()
        slot.epoch += 1
        slot.prefill_pos = None   # mixed admission re-parks it after this
        slot.prefill_reused = 0

    def _request_seed(self, request: GenerationRequest) -> int:
        """The request's sampling seed: explicit (OpenAI `seed`) or a
        fresh auto-seed, fixed for the request's whole lifetime."""
        if request.sampling.seed is not None:
            return request.sampling.seed & 0xFFFFFFFF
        assigned = getattr(request, "_auto_seed", None)
        if assigned is None:
            self._seed_sequence += 1
            assigned = (self.base_seed * 1_000_003 + self._seed_sequence) \
                & 0xFFFFFFFF
            request._auto_seed = assigned  # type: ignore[attr-defined]
        return assigned

    def _sampling_arrays(self, requests: List[GenerationRequest]):
        # numpy on purpose: jit dispatch converts implicitly, and the
        # multi-host mirror can serialize the arrays without a D2H sync
        return (
            np.asarray(
                [r.sampling.temperature for r in requests], dtype=np.float32
            ),
            np.asarray([r.sampling.top_k for r in requests], dtype=np.int32),
            np.asarray(
                [r.sampling.top_p for r in requests], dtype=np.float32
            ),
            np.asarray(
                [self._request_seed(r) for r in requests], dtype=np.uint32
            ),
        )

    def _penalty_arrays(self, slots: List[_Slot]):
        presence = np.zeros((self.max_slots,), dtype=np.float32)
        frequency = np.zeros((self.max_slots,), dtype=np.float32)
        for i, slot in enumerate(slots):
            if slot.active:
                presence[i] = slot.request.sampling.presence_penalty
                frequency[i] = slot.request.sampling.frequency_penalty
        return presence, frequency

    def _bias_rows(self, requests: List[Optional[GenerationRequest]]):
        """[len(requests), MAX_LOGIT_BIAS] (ids, values) for logit_bias;
        rows for None/bias-less requests are all (0, 0.0) — a +0 on
        token 0."""
        k = self.MAX_LOGIT_BIAS
        ids = np.zeros((len(requests), k), dtype=np.int32)
        values = np.zeros((len(requests), k), dtype=np.float32)
        vocab = self.config.vocab_size
        for row, request in enumerate(requests):
            bias = request.sampling.logit_bias if request else None
            if not bias:
                continue
            valid = [
                (int(token), float(value)) for token, value in bias.items()
                if 0 <= int(token) < vocab
            ]
            for column, (token, value) in enumerate(valid[:k]):
                ids[row, column] = token
                values[row, column] = value
        return ids, values

    def _prefill_batch(
        self, batch: List[Tuple[int, GenerationRequest]], bucket: int
    ) -> None:
        """Dispatch cold prefills (first token sampled in-jit) WITHOUT
        blocking — the result is picked up by :meth:`_harvest_prefills`
        while decode chunks for already-running slots continue."""
        faults.check("dispatch_error")
        for group in self._pow2_groups(batch):
            started = time.perf_counter()
            size = len(group)
            tokens = np.zeros((size, bucket), dtype=np.int32)
            lengths = np.zeros((size,), dtype=np.int32)
            slot_ids = np.zeros((size,), dtype=np.int32)
            for row, (index, request) in enumerate(group):
                prompt = request.prompt_tokens
                tokens[row, : len(prompt)] = prompt
                lengths[row] = len(prompt)
                slot_ids[row] = index
                self._assign_slot(index, request)
                self.slots[index].prefilling = True
            run = self._get_prefill(bucket)
            temperature, top_k, top_p, seeds = self._sampling_arrays(
                [request for _, request in group]
            )
            bias_ids, bias_vals = self._bias_rows(
                [request for _, request in group]
            )
            # ONE host-args list feeds both the mirror record and the
            # dispatch, so the replayed argument order cannot drift
            host_args = [
                tokens, lengths, slot_ids,
                temperature, top_k, top_p, seeds, bias_ids, bias_vals,
            ]
            paged_args = (
                (self._block_tables[slot_ids],) if self.paged else ()
            )
            if self.mirror is not None:
                self._check_mirror_layout()
                # paged dispatches ship their block-table rows in
                # dispatch-arg position (small int32 host metadata — no
                # D2H of pool data); the follower's replay rebuilds the
                # exact argument tuple from engine.paged
                self.mirror.publish(
                    "prefill", {"bucket": bucket},
                    [*host_args[:3], *paged_args, *host_args[3:]],
                )
            self.cache, self._counts, sampled, lps, tops = run(
                self.params, self.cache, *host_args[:3], *paged_args,
                self._counts, *host_args[3:],
            )
            self.stats["prefill_calls"] += 1
            self.stats["prefill_time"] += time.perf_counter() - started
            # modeled prefill work (cumulative prefill MFU denominator
            # is prefill_time, which also absorbs the harvest wait)
            dispatch_flops = sum(
                self.cost_model.prefill_flops(len(r.prompt_tokens))
                for _, r in group
            )
            self.stats["prefill_flops"] += dispatch_flops
            # goodput ledger: bucket-rounding ghosts — positions the
            # padded [size, bucket] dispatch computes past each prompt's
            # end (up to ~2x a prompt's FLOPs at the worst bucket edge;
            # the mixed path caps the same waste at width−1 per window)
            live = sum(len(r.prompt_tokens) for _, r in group)
            self._waste("prefill_padding", size * bucket - live)
            self._log_dispatch(
                "prefill", tokens=live, rows=size, wall=0.0,
                prefill_tokens=live,
            )
            flight.record(
                "prefill",
                bucket=bucket,
                batch=size,
                warm=False,
                reused_tokens=0,
                wall_ms=round((time.perf_counter() - started) * 1e3, 3),
                queue_depth=len(self._pending),
                flops=dispatch_flops,
            )
            self._prefill_inflight.append({
                "group": [(index, request) for index, request in group],
                "sampled": sampled,
                "lps": lps,
                "tops": tops,
                "reused": {},
                "started": started,
            })

    def _prefill_warm_batch(
        self,
        batch: List[Tuple[int, GenerationRequest, int]],
        bucket: int,
    ) -> None:
        """Warm-session admissions sharing a suffix bucket: the cache
        already holds each slot's shared prefix; ONE bucketed
        prefill-at-offset dispatch writes every suffix (chunked prefill —
        no per-token forcing, no per-request dispatch). Groups split to
        power-of-two sizes to bound compilations, like cold prefill.
        Non-blocking, like :meth:`_prefill_batch`."""
        faults.check("dispatch_error")
        for group in self._pow2_groups(batch):
            started = time.perf_counter()
            size = len(group)
            tokens = np.zeros((size, bucket), dtype=np.int32)
            lengths = np.zeros((size,), dtype=np.int32)
            offsets = np.zeros((size,), dtype=np.int32)
            slot_ids = np.zeros((size,), dtype=np.int32)
            for row, (index, request, reused) in enumerate(group):
                suffix = request.prompt_tokens[reused:]
                tokens[row, : len(suffix)] = suffix
                lengths[row] = len(suffix)
                offsets[row] = reused
                slot_ids[row] = index
                self._assign_slot(index, request, reused)
                self.slots[index].prefilling = True
            run = self._get_prefill_offset(bucket)
            temperature, top_k, top_p, seeds = self._sampling_arrays(
                [request for _, request, _ in group]
            )
            bias_ids, bias_vals = self._bias_rows(
                [request for _, request, _ in group]
            )
            host_args = [
                tokens, lengths, offsets, slot_ids,
                temperature, top_k, top_p, seeds, bias_ids, bias_vals,
            ]
            paged_args = (
                (self._block_tables[slot_ids],) if self.paged else ()
            )
            if self.mirror is not None:
                self._check_mirror_layout()
                self.mirror.publish(
                    "prefill_offset", {"bucket": bucket},
                    [*host_args[:4], *paged_args, *host_args[4:]],
                )
            self.cache, self._counts, sampled, lps, tops = run(
                self.params, self.cache, *host_args[:4], *paged_args,
                self._counts, *host_args[4:],
            )
            self.stats["warm_prefill_calls"] += 1
            self.stats["prefill_time"] += time.perf_counter() - started
            dispatch_flops = sum(
                self.cost_model.prefill_flops(
                    len(r.prompt_tokens) - reused, offset=reused
                )
                for _, r, reused in group
            )
            self.stats["prefill_flops"] += dispatch_flops
            live = sum(
                len(r.prompt_tokens) - reused for _, r, reused in group
            )
            self._waste("prefill_padding", size * bucket - live)
            self._log_dispatch(
                "prefill", tokens=live, rows=size, wall=0.0,
                prefill_tokens=live,
            )
            flight.record(
                "prefill",
                bucket=bucket,
                batch=size,
                warm=True,
                reused_tokens=int(sum(r for _, _, r in group)),
                wall_ms=round((time.perf_counter() - started) * 1e3, 3),
                queue_depth=len(self._pending),
                flops=dispatch_flops,
            )
            self._prefill_inflight.append({
                "group": [(index, request) for index, request, _ in group],
                "sampled": sampled,
                "lps": lps,
                "tops": tops,
                "reused": {index: reused for index, _, reused in group},
                "started": started,
            })

    def _prefill_long(
        self, index: int, request: GenerationRequest, reused: int
    ) -> None:
        """Chunked prefill for a prompt (or warm-session suffix) longer
        than the largest bucket: write it in bucket-sized windows, left to
        right, each one a prefill-at-offset dispatch (non-blocking, like
        the batched paths). The FINAL window is shifted left to end
        exactly at the prompt's last token — re-teaching a few
        already-written positions (identical tokens → identical KV) is
        cheaper than a dedicated ragged-tail compilation, and it
        guarantees the window never writes past ``max_seq_len``. This is
        what lets long-context prompts (ring/Ulysses scale) enter the
        slot cache without a giant single-dispatch bucket."""
        faults.check("dispatch_error")
        prompt = request.prompt_tokens
        total = len(prompt)
        largest = self.prefill_buckets[-1]
        self._assign_slot(index, request, reused)
        self.slots[index].prefilling = True
        windows: List[Tuple[int, int]] = []  # (offset, bucket)
        position = reused
        while total - position > largest:
            windows.append((position, largest))
            position += largest
        tail_bucket = _bucket(total - position, self.prefill_buckets)
        # shift the tail window left so offset + bucket == total
        windows.append((max(0, total - tail_bucket), tail_bucket))
        started = time.perf_counter()
        temperature, top_k, top_p, seeds = self._sampling_arrays([request])
        bias_ids, bias_vals = self._bias_rows([request])
        for step, (offset, bucket) in enumerate(windows):
            chunk = prompt[offset:offset + bucket]
            tokens = np.zeros((1, bucket), dtype=np.int32)
            tokens[0, : len(chunk)] = chunk
            lengths = np.asarray([len(chunk)], dtype=np.int32)
            offsets = np.asarray([offset], dtype=np.int32)
            slot_ids = np.asarray([index], dtype=np.int32)
            run = self._get_prefill_offset(bucket)
            host_args = [
                tokens, lengths, offsets, slot_ids,
                temperature, top_k, top_p, seeds, bias_ids, bias_vals,
            ]
            paged_args = (
                (self._block_tables[slot_ids],) if self.paged else ()
            )
            if self.mirror is not None:
                self._check_mirror_layout()
                self.mirror.publish(
                    "prefill_offset", {"bucket": bucket},
                    [*host_args[:4], *paged_args, *host_args[4:]],
                )
            self.cache, self._counts, sampled, lps, tops = run(
                self.params, self.cache, *host_args[:4], *paged_args,
                self._counts, *host_args[4:],
            )
            if step == len(windows) - 1:
                # only the final window's sampled token is the real first
                # token; intermediate windows' samples are discarded
                self._prefill_inflight.append({
                    "group": [(index, request)],
                    "sampled": sampled,
                    "lps": lps,
                    "tops": tops,
                    "reused": {index: reused} if reused else {},
                    "started": started,
                })
        self.stats["warm_prefill_calls" if reused else "prefill_calls"] += 1
        self.stats["prefill_time"] += time.perf_counter() - started
        # chunked windows re-teach overlapped tail positions; modeling
        # each window at its own offset keeps the count exact anyway
        self.stats["prefill_flops"] += sum(
            self.cost_model.prefill_flops(
                min(bucket, total - offset), offset=offset
            )
            for offset, bucket in windows
        )
        # goodput: window positions beyond the new suffix — the shifted
        # tail's re-taught overlap (identical KV, wasted FLOPs)
        self._waste(
            "prefill_padding",
            sum(bucket for _, bucket in windows) - (total - reused),
        )
        for offset, bucket in windows:
            taught = min(bucket, total - offset)
            self._log_dispatch(
                "prefill", tokens=taught, rows=1,
                wall=0.0, prefill_tokens=taught,
            )

    def _check_mirror_layout(self) -> None:
        """Engine features the follower replay protocol cannot speak
        yet. Paged IS spoken: dispatch records carry the block-table
        rows (host-local int32 metadata) and COW block copies publish
        their own ``block_copy`` records, so a follower replays the
        identical device-side pool mutations without running the block
        allocator itself. Fail loudly on the rest instead of silently
        diverging shards."""
        if self.spec:
            # spec dispatches carry the device token-history operand and
            # return variable-width outputs the follower replay protocol
            # does not speak yet
            raise NotImplementedError(
                "multi-host mirror does not support spec_decode yet"
            )

    def _harvest_prefills(self, block: bool = False) -> None:
        """Emit first tokens of completed prefill dispatches (FIFO — the
        device runs dispatches in order, so if the oldest isn't done the
        younger ones aren't either). ``block`` waits for the oldest one;
        used only when decode has no ready slots, so waiting IS the
        fastest path to progress."""
        while self._prefill_inflight:
            record = self._prefill_inflight[0]
            sampled = record["sampled"]
            if not block:
                is_ready = getattr(sampled, "is_ready", None)
                if is_ready is not None and not is_ready():
                    return
            wait_started = time.perf_counter()
            firsts = np.asarray(sampled)
            lps = np.asarray(record["lps"])
            tops = record.get("tops")
            if tops is not None:
                tops = (np.asarray(tops[0]), np.asarray(tops[1]))
            self.stats["prefill_time"] += time.perf_counter() - wait_started
            age = time.perf_counter() - record["started"]
            if self.tracer.enabled:
                now_pc = time.perf_counter()
                for index, request in record["group"]:
                    submit_ts = getattr(
                        request, "_submit_ts", record["started"]
                    )
                    submit_wall = getattr(
                        request, "_submit_wall", time.time()
                    )
                    dispatch_wall = submit_wall + (
                        record["started"] - submit_ts
                    )
                    tid = request.trace_id or ""
                    self.tracer.event(
                        "engine.admission",
                        max(0.0, record["started"] - submit_ts),
                        trace_id=tid,
                        start_wall=submit_wall,
                        slot=index,
                    )
                    reused = record.get("reused", {}).get(index, 0)
                    self.tracer.event(
                        "engine.prefill",
                        max(0.0, now_pc - record["started"]),
                        trace_id=tid,
                        start_wall=dispatch_wall,
                        slot=index,
                        prompt_tokens=len(request.prompt_tokens),
                        # cache-served prefix vs actually-prefilled span:
                        # the acceptance evidence that a prefix-cache hit
                        # shrank this request's prefill work
                        reused_tokens=reused,
                        prefill_tokens=len(request.prompt_tokens) - reused,
                        ttft_ms=round((now_pc - submit_ts) * 1e3, 3),
                    )
            for row, (index, request) in enumerate(record["group"]):
                self.slots[index].prefilling = False
                if request.replay_tokens:
                    # resurrected session: fast-forward through the
                    # accepted history instead of emitting the prefill's
                    # own sample (see _resume_replay)
                    self._resume_replay(
                        index, request,
                        reused=record.get("reused", {}).get(index, 0),
                    )
                else:
                    self._emit_token(
                        index, int(firsts[row]), float(lps[row]),
                        top=(
                            (tops[0][row].tolist(), tops[1][row].tolist())
                            if tops is not None else None
                        ),
                    )
                request._prefill_time = age  # type: ignore[attr-defined]
            self._prefill_inflight.pop(0)
            block = False  # only the oldest is worth waiting for

    def _resume_replay(
        self, index: int, request: GenerationRequest, reused: int = 0
    ) -> None:
        """Fast-forward a resurrected session (supervisor rebuild).

        The prefill that just harvested taught the cache
        ``prompt + replay[:-1]``; this seeds the slot's bookkeeping with
        the accepted tokens and teacher-forces ``replay[-1]`` as the
        pending token — its KV row is written by the next decode step,
        exactly like a freshly sampled first token, so the continuation
        samples at cache position ``len(prompt) + len(replay)`` with the
        key the uncrashed oracle would have used. The prefill's OWN
        sampled token is discarded: its logits were computed without the
        restored penalty state, and the caller already holds the real
        token for that position. Penalty counts are restored
        position-exactly (:meth:`_restore_counts`), so greedy AND seeded
        stochastic continuations — penalties included — are bitwise
        identical to an uncrashed run. Replayed tokens are NOT re-emitted
        (the caller's stream already has them); they re-enter the final
        result through ``slot.generated``."""
        slot = self.slots[index]
        replay = list(request.replay_tokens)
        slot.generated = replay
        lps = list(request.replay_logprobs or [])
        slot.logprobs = lps + [0.0] * (len(replay) - len(lps))
        if slot.tops is not None:
            tops = list(request.replay_tops or [])
            slot.tops = tops + [([], [])] * (len(replay) - len(tops))
        slot.history.append(replay[-1])
        self._restore_counts(index, replay)
        # TTFT anchor for the resumed span: the next emitted token is
        # the first the NEW engine produces for this request
        request._first_token_ts = (  # type: ignore[attr-defined]
            time.perf_counter()
        )
        # goodput ledger: every token this admission re-prefilled is
        # crash-replay recompute the uncrashed oracle never paid for
        # (the paged prefix cache shrinks it via `reused`)
        self._waste(
            "crash_replay", len(request.prompt_tokens) - reused
        )
        flight.record(
            "session_resume",
            slot=index,
            replayed=len(replay),
            reused_tokens=reused,
            trace_id=request.trace_id or "",
        )
        if request.cancelled:
            self._finish(index, "cancelled")
        elif (
            len(replay) >= request.sampling.max_new_tokens
            or slot.length + 1 >= self.max_seq_len
        ):
            # the crash raced the finish: the session was already at its
            # budget/context boundary — close it out like the oracle did
            self._finish(index, "length")

    def _get_counts_restore(self):
        """Jitted single-row overwrite of the penalty-count array: the
        replay prefill reset the slot's row and counted its (discarded)
        sample; this puts back the exact multiset of tokens the crashed
        engine had accumulated, so the first resumed sample sees the
        same penalty adjustments the oracle's would."""
        fn = self._counts_restore_fn
        if fn is None:

            @jax.jit
            def run(counts, index, row):
                return (
                    jax.lax.dynamic_update_slice(
                        counts, row[None, :], (index, jnp.int32(0))
                    ),
                )

            fn = run
            self._counts_restore_fn = fn
        return fn

    def _restore_counts(self, index: int, tokens: List[int]) -> None:
        row = np.zeros((self.config.vocab_size,), dtype=np.int32)
        for token in tokens:
            if 0 <= token < self.config.vocab_size:
                row[token] += 1
        run = self._get_counts_restore()
        (self._counts,) = run(self._counts, np.int32(index), row)

    def _can_chain(self, inflight: Dict[str, Any]) -> bool:
        """A chunk may be pre-dispatched off the in-flight carry only when
        no admission is waiting and every active slot has ≥2 chunks of
        budget and context left (so the blind chunk can't overrun)."""
        if self._pending or self._prefill_inflight or self._any_admitting():
            # harvested prefill slots should join the NEXT chunk, not wait
            # out a blind pre-dispatched one (and mixed admitting slots
            # need every next dispatch to be a fresh mixed step)
            return False
        # worst-case tokens a chunk can emit per slot: each spec step
        # may accept every draft plus the bonus token
        budget = inflight["steps"] * self.spec_block
        for i, slot in enumerate(self.slots):
            if not inflight["active"][i]:
                continue
            if not slot.active or slot.epoch != inflight["epochs"][i]:
                return False
            request = slot.request
            if len(slot.generated) + 2 * budget > request.sampling.max_new_tokens:
                return False
            if slot.length + 1 + 2 * budget >= self.max_seq_len:
                return False
        return True

    def _dispatch_decode(
        self, carry: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """Dispatch one decode chunk. With ``carry`` (a previous chunk's
        record), tokens/lengths chain on-device — no host round trip.
        In mixed mode, while any slot is admitting, the dispatch is a
        single mixed step instead (:meth:`_dispatch_mixed`)."""
        if carry is None and self._any_admitting():
            return self._dispatch_mixed()
        faults.check("dispatch_error")
        # chaos: a dispatch that WEDGES instead of erroring (stuck_step
        # sleeps `dur` seconds here) — the watchdog/escalation test shape
        faults.maybe_sleep("stuck_step")
        started = time.perf_counter()
        # summed (block-padded, for paged) context length of the chunk's
        # riders at dispatch — the roofline's attention/KV-read term
        kv_tokens = 0
        if carry is not None:
            steps = carry["steps"]
            active = carry["active"]
            # approximation: the carry chunk advanced every rider by its
            # step count. Unpadded for paged (block crossings unknown
            # without slot state, slight undercount), a rider that
            # hit a stop token mid-carry still counts (slight overcount),
            # and under spec decode a step advances 1..spec_block tokens
            # (reading the accepted counts here would sync on the carry
            # and defeat pipelining — steps is the guaranteed floor)
            # — _can_chain rules out budget/context finishes, so chains
            # stay rare-error-bounded; fresh dispatches are exact.
            kv_tokens = carry["kv_tokens"] + int(active.sum()) * steps
            (
                temperature, top_k, top_p, presence, frequency, seeds,
                bias_ids, bias_vals,
            ) = carry["sampling_arrays"]
            tokens_arg = carry["final_tokens"]
            lengths_arg = carry["final_lengths"]
            active_arg = carry["active_dev"]
            tables_arg = carry["tables_dev"]
            history_arg = carry["final_history"]
            epochs = carry["epochs"]
            if self.mirror is not None:
                # followers chain from their OWN previous decode output
                # (identical values — SPMD determinism), so the record
                # carries no arrays
                self.mirror.publish("decode_chained", {"steps": steps}, [])
        else:
            tokens = np.zeros((self.max_slots,), dtype=np.int32)
            lengths = np.zeros((self.max_slots,), dtype=np.int32)
            active = np.zeros((self.max_slots,), dtype=bool)
            temperature = np.zeros((self.max_slots,), dtype=np.float32)
            top_k = np.zeros((self.max_slots,), dtype=np.int32)
            top_p = np.zeros((self.max_slots,), dtype=np.float32)
            seeds_host = np.zeros((self.max_slots,), dtype=np.uint32)
            epochs = [0] * self.max_slots
            steps = self.decode_chunk
            if self.admission_chunk and (self._pending or self._prefill_inflight):
                # someone is waiting to join: run a short chunk so the
                # next dispatch picks them up (see admission_chunk)
                steps = self.admission_chunk
            history = (
                np.zeros((self.max_slots, self.max_seq_len), dtype=np.int32)
                if self.spec else None
            )
            for i, slot in enumerate(self.slots):
                lengths[i] = slot.length
                epochs[i] = slot.epoch
                if slot.ready:
                    active[i] = True
                    tokens[i] = slot.history[-1]
                    lengths[i] = slot.length + 1
                    kv_tokens += self.cost_model.kv_read_tokens(
                        slot.length + 1
                    )
                    temperature[i] = slot.request.sampling.temperature
                    top_k[i] = slot.request.sampling.top_k
                    top_p[i] = slot.request.sampling.top_p
                    seeds_host[i] = self._request_seed(slot.request)
                    if history is not None:
                        # drafting source: the slot's full token history
                        # (prompt + generated incl. the pending token —
                        # h[t] = token at cache position t)
                        history[i, : len(slot.history)] = slot.history
                    # a chunk writes cache positions up to
                    # length + steps·block − 1 (block = 1 + spec_k when
                    # speculating); drop to single-step near the context
                    # boundary — the in-jit draft clamp keeps even a
                    # single spec step inside the cache
                    if (
                        self.max_seq_len - slot.length - 1
                        < steps * self.spec_block
                    ):
                        steps = 1
            bias_ids, bias_vals = self._bias_rows(
                [slot.request if slot.ready else None for slot in self.slots]
            )
            presence, frequency = self._penalty_arrays(self.slots)
            if self.mirror is not None:
                self._check_mirror_layout()
                # paged: the full [S, M] tables ride the record (they
                # are the dispatch's 7th argument); chained chunks carry
                # nothing — followers reuse the tables from their carry,
                # exactly like the leader's device-resident carry
                table_args = (
                    (self._block_tables,) if self.paged else ()
                )
                self.mirror.publish("decode", {"steps": steps}, [
                    tokens, lengths, active, *table_args,
                    temperature, top_k, top_p, presence, frequency,
                    seeds_host, bias_ids, bias_vals,
                ])
            # device-resident args: chained chunks reuse the carry's
            # arrays with ZERO host->device transfers — re-uploading
            # per chunk serializes the engine thread on the tunnel RTT
            # (measured: e2e 1299 -> 717 tok/s when these were numpy)
            seeds = jnp.asarray(seeds_host)
            temperature = jnp.asarray(temperature)
            top_k = jnp.asarray(top_k)
            top_p = jnp.asarray(top_p)
            presence = jnp.asarray(presence)
            frequency = jnp.asarray(frequency)
            bias_ids = jnp.asarray(bias_ids)
            bias_vals = jnp.asarray(bias_vals)
            tokens_arg = jnp.asarray(tokens)
            lengths_arg = jnp.asarray(lengths)
            active_arg = jnp.asarray(active)
            history_arg = jnp.asarray(history) if self.spec else None
            # block tables are device-resident in the carry like every
            # other chained operand (tables of active riders cannot
            # change while _can_chain holds)
            tables_arg = (
                jnp.asarray(self._block_tables) if self.paged else None
            )
        # telemetry snapshot AT DISPATCH: by processing time a rider may
        # have finished and its slot been recycled to a new request, so
        # live-slot reads would mis-attribute the chunk. Chained chunks
        # inherit the carry's snapshot — _can_chain guarantees the rider
        # set is unchanged
        trace_ids, queue_depth, kv_frac = "", 0, 0.0
        kv_blocks, prefix_hit_tokens = 0, 0
        if carry is not None:
            trace_ids = carry["trace_ids"]
            queue_depth = carry["queue_depth"]
            kv_frac = carry["kv_frac"]
            kv_blocks = carry["kv_blocks"]
            prefix_hit_tokens = carry["prefix_hit_tokens"]
        elif self.tracer.enabled or flight.RECORDER.enabled:
            if self.paged:
                kv_blocks = self.kv_manager.blocks_in_use
                prefix_hit_tokens = self.kv_manager.stats["hit_tokens"]
            trace_ids = ",".join(
                slot.request.trace_id
                for i, slot in enumerate(self.slots)
                if active[i] and slot.active and slot.request.trace_id
            )
            queue_depth = len(self._pending)
            if self.paged:
                kv_frac = round(
                    self.kv_manager.blocks_in_use / float(self.num_blocks), 4
                )
            else:
                kv_frac = round(
                    sum(slot.length for slot in self.slots if slot.active)
                    / float(self.max_slots * self.max_seq_len),
                    4,
                )
        run = self._get_decode(steps)
        paged_args = (tables_arg,) if self.paged else ()
        out_valid = out_drafted = final_history = None
        if self.spec:
            (
                self.cache, self._counts, out_tokens, out_lps, out_valid,
                out_drafted, out_tops, final_tokens, final_lengths,
                final_history,
            ) = run(
                self.params, self.cache, tokens_arg, lengths_arg,
                active_arg, active_arg, history_arg, *paged_args,
                self._counts, temperature, top_k, top_p, presence,
                frequency, seeds, bias_ids, bias_vals,
            )
        else:
            (
                self.cache, self._counts, out_tokens, out_lps, out_tops,
                final_tokens, final_lengths,
            ) = run(
                self.params, self.cache, tokens_arg, lengths_arg,
                active_arg, active_arg, *paged_args, self._counts,
                temperature, top_k, top_p, presence, frequency, seeds,
                bias_ids, bias_vals,
            )  # arg order mirrored by FollowerExecutor._decode — keep in sync
        return {
            "out_tokens": out_tokens,
            "out_lps": out_lps,
            "out_tops": out_tops,
            "out_valid": out_valid,
            "out_drafted": out_drafted,
            "final_tokens": final_tokens,
            "final_lengths": final_lengths,
            "final_history": final_history,
            "active": active,
            "active_dev": active_arg,
            "tables_dev": tables_arg,
            "sampling_arrays": (
                temperature, top_k, top_p, presence, frequency, seeds,
                bias_ids, bias_vals,
            ),
            "epochs": list(epochs),
            "steps": steps,
            "started": started,
            "kv_tokens": kv_tokens,
            "trace_ids": trace_ids,
            "queue_depth": queue_depth,
            "kv_frac": kv_frac,
            "kv_blocks": kv_blocks,
            "prefix_hit_tokens": prefix_hit_tokens,
        }

    def _log_dispatch(
        self, kind: str, *, tokens: int, rows: int, wall: float,
        steps: int = 0, prefill_tokens: int = 0,
    ) -> None:
        """Token-denominated dispatch log (every device dispatch, prefill
        included): the interference-bound evidence the mixed A/B and the
        regression test read — ``prefill_tokens`` is the prompt work a
        single dispatch serializes in front of every running stream.
        ``wall`` is the dispatch-to-harvest time for SYNCHRONOUS entries
        (decode chunks, mixed steps) and 0.0 for the split path's
        non-blocking prefill dispatches (their device time overlaps
        decode and is unobservable at dispatch) — token counts, not
        walls, are the cross-kind comparison this log exists for."""
        if len(self.dispatch_log) < 65536:
            self.dispatch_log.append({
                "kind": kind,
                "tokens": int(tokens),
                "rows": int(rows),
                "steps": int(steps),
                "prefill_tokens": int(prefill_tokens),
                "wall": wall,
            })

    def _plan_mixed_chain(self, inflight: Dict[str, Any]):
        """Two-step window plan (mixed-step carry): decide whether the
        NEXT mixed step is host-predictable from the in-flight one and,
        if so, name its rows. Window content for step N+1 is derivable
        at plan time — watermarks advanced deterministically when N was
        dispatched and ``completes`` is part of N's plan — so the only
        host-unknown input is N's sampled tokens, which stay on device
        (:meth:`_get_mixed`'s ``prev_sampled`` operand). Returns a plan
        dict (``riders`` = rows chained off N's device sample,
        ``windows`` = prompt windows, ``width``) or the invalidation
        reason that forces the next dispatch back to host-built:

        - ``admission``: queued/admitted work N's carried sampling
          arrays don't cover;
        - ``replay``: a resurrected session completes at N — its next
          token is teacher-forced, not N's speculated sample;
        - ``budget``: a rider could finish by length during N;
        - ``width``: the window ladder changes width at N+1;
        - ``condemned``: the supervisor condemned this engine;
        - ``drained``: no windows remain — the mixed phase is over and
          plain (decode-carry-chainable) chunks take back over;
        - ``epoch``: a carried row's slot was recycled (paranoia guard).
        """
        if not self._running or self._crashed is not None:
            return "condemned"
        if self._pending or self._prefill_inflight:
            return "admission"
        prev_plan = inflight["plan"]
        prev_completes = inflight["completes"]
        prev_decode = inflight["decode_mask"]
        prev_offsets = inflight["offsets"]
        prev_num = inflight["num_tokens"]
        epochs = inflight["epochs"]
        riders: List[int] = []
        admitting: List[int] = []
        for i, slot in enumerate(self.slots):
            carried = prev_decode[i] or (i in prev_plan)
            if slot.request is None:
                if carried:
                    return "epoch"
                continue
            if slot.epoch != epochs[i]:
                # the slot acquired a request AFTER the in-flight step
                # was planned — its sampling params are not in the
                # carried device arrays
                return "admission" if not carried else "epoch"
            if prev_decode[i] or (i in prev_plan and prev_completes[i]):
                if i in prev_plan and slot.request.replay_tokens:
                    return "replay"
                riders.append(i)
            elif slot.prefill_pos is not None:
                admitting.append(i)
        for i in riders:
            slot = self.slots[i]
            # the speculated step emits one more token per rider on top
            # of the in-flight one: require room for both, so a rider
            # can only ever finish mid-chain by a (host-unpredictable)
            # stop/cancel — never by length (the _can_chain rule)
            generated = len(slot.generated) if slot.generated else 0
            if generated + 2 > slot.request.sampling.max_new_tokens:
                return "budget"
            if int(prev_offsets[i]) + int(prev_num[i]) + 2 >= self.max_seq_len:
                return "budget"
        windows, width = self._plan_windows(admitting)
        if not windows:
            return "drained"
        if width != inflight["width"]:
            # chain only across equal-width steps: the speculative
            # dispatch reuses the in-flight step's exact compiled
            # variant, and a ladder transition costs one host round
            # trip instead of a mid-stream variant swap
            return "width"
        return {"riders": riders, "windows": windows, "width": width}

    def _plan_windows(
        self, admitting: List[int]
    ) -> Tuple[Dict[int, Tuple[int, int]], int]:
        """FIFO token-budget window plan over admitting slot indices:
        ``{slot: (pos, n)}`` plus the pow2 dispatch width. ONE
        implementation serves the fresh dispatch AND the two-step chain
        plan — the chained ≡ unchained bitwise contract depends on the
        two schedules never diverging, so there must be nothing to keep
        in lockstep."""
        budget = self.prefill_chunk
        windows: Dict[int, Tuple[int, int]] = {}
        max_n = 1
        for i in sorted(admitting, key=lambda i: self.slots[i].prefill_seq):
            if budget <= 0:
                break
            slot = self.slots[i]
            remaining = len(slot.request.prompt_tokens) - slot.prefill_pos
            n = min(remaining, budget)
            if n <= 0:
                continue
            windows[i] = (slot.prefill_pos, n)
            budget -= n
            max_n = max(max_n, n)
        width = next(w for w in self._mixed_widths if w >= max_n)
        return windows, width

    def _note_carry_invalidation(self, reason: str, events: int = 1) -> None:
        invalidations = self.stats["mixed_carry_invalidations"]
        invalidations[reason] = invalidations.get(reason, 0) + events

    def _dispatch_mixed(
        self,
        carry: Optional[Dict[str, Any]] = None,
        plan_next: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Dispatch ONE mixed step: every ready slot rides as a Tq=1
        decode row, and up to ``prefill_chunk`` prompt tokens from
        admitting slots ride alongside as prefill windows — one fused
        token-ragged launch, one weight pass, one bounded dispatch. The
        budget is shared FIFO by admission order, so an early prompt is
        never starved by a later burst; a window that reaches its
        prompt's end samples the request's first token in the same
        dispatch (no separate harvest).

        With ``carry`` (the in-flight previous step's record) and
        ``plan_next`` (from :meth:`_plan_mixed_chain`), the step chains
        on-device: riders take the previous step's device-resident
        sample as their pending token, tables and sampling arrays are
        reused from the carry, and only the small prompt-window token
        delta uploads — no host round trip between consecutive mixed
        steps, exactly like ``_dispatch_decode(carry=...)``."""
        faults.check("dispatch_error")
        faults.maybe_sleep("stuck_step")
        started = time.perf_counter()
        slots_n = self.max_slots
        chained = carry is not None
        if chained:
            plan = plan_next["windows"]
            riders = plan_next["riders"]
            width = plan_next["width"]
        else:
            plan, width = self._plan_windows([
                i for i, s in enumerate(self.slots)
                if s.prefill_pos is not None and s.request is not None
            ])
            riders = [i for i, s in enumerate(self.slots) if s.ready]

        tokens = np.zeros((slots_n, width), dtype=np.int32)
        offsets = np.zeros((slots_n,), dtype=np.int32)
        num_tokens = np.zeros((slots_n,), dtype=np.int32)
        write_mask = np.zeros((slots_n,), dtype=bool)
        decode_mask = np.zeros((slots_n,), dtype=bool)
        completes = np.zeros((slots_n,), dtype=bool)
        chain_mask = np.zeros((slots_n,), dtype=bool)
        epochs = [slot.epoch for slot in self.slots]
        kv_tokens = 0          # decode rows' (block-padded) context reads
        prefill_kv_tokens = 0  # windows' prefix+window reads
        prefill_tokens = 0
        padding = 0
        for i, (pos, n) in plan.items():
            slot = self.slots[i]
            prompt = slot.request.prompt_tokens
            tokens[i, :n] = prompt[pos:pos + n]
            offsets[i] = pos
            num_tokens[i] = n
            write_mask[i] = True
            completes[i] = pos + n == len(prompt)
            prefill_tokens += n
            padding += width - n
            prefill_kv_tokens += self.cost_model.kv_read_tokens(pos + n)
        for i in riders:
            slot = self.slots[i]
            if chained:
                # pending token = the in-flight step's device-resident
                # sample (spliced in-jit via prev_sampled); next cache
                # position = the in-flight row's offset + its count
                chain_mask[i] = True
                offsets[i] = int(carry["offsets"][i]) + int(
                    carry["num_tokens"][i]
                )
            else:
                tokens[i, 0] = slot.history[-1]
                offsets[i] = slot.length
            num_tokens[i] = 1
            write_mask[i] = True
            decode_mask[i] = True
            kv_tokens += self.cost_model.kv_read_tokens(int(offsets[i]) + 1)
        # advance the taught watermarks NOW: the window content is final
        # once dispatched, and the NEXT step's plan (chained or fresh)
        # derives from the advanced bookkeeping
        for i, (pos, n) in plan.items():
            self.slots[i].prefill_pos = pos + n
        # telemetry snapshot AT DISPATCH (the decode-path rule): with
        # the carry, this step is processed only after the previous
        # one's processing may have finished a rider and recycled its
        # slot — live-slot reads at processing time would attribute the
        # step to a request whose tokens were never in it
        trace_ids = ""
        if self.tracer.enabled or flight.RECORDER.enabled:
            trace_ids = ",".join(
                self.slots[i].request.trace_id
                for i in riders
                if self.slots[i].request is not None
                and self.slots[i].request.trace_id
            )
        # goodput: ghost positions the padded [S, W] grid computes for a
        # short window — the mixed analogue of bucket padding, capped at
        # width−1 per admitting row per step (vs up to ~bucket/2 − 1 per
        # PROMPT on the split path)
        self._waste("prefill_padding", padding)
        if chained:
            # device-resident carry: tables, sampling arrays, and the
            # previous sample never leave the device — only the window
            # token delta above uploads
            tables_dev = carry["tables_dev"]
            sampling_dev = carry["sampling_dev"]
            prev_sampled = carry["sampled"]
        else:
            # sampling params are per-request constants, filled for
            # EVERY live row (planned or not) so a chained step can
            # reuse these device arrays verbatim even when the FIFO
            # budget reaches a row this step skipped
            temperature = np.zeros((slots_n,), dtype=np.float32)
            top_k = np.zeros((slots_n,), dtype=np.int32)
            top_p = np.zeros((slots_n,), dtype=np.float32)
            seeds = np.zeros((slots_n,), dtype=np.uint32)
            requests: List[Optional[GenerationRequest]] = [None] * slots_n
            for i, slot in enumerate(self.slots):
                request = slot.request
                if request is None:
                    continue
                requests[i] = request
                temperature[i] = request.sampling.temperature
                top_k[i] = request.sampling.top_k
                top_p[i] = request.sampling.top_p
                seeds[i] = self._request_seed(request)
            presence, frequency = self._penalty_arrays(self.slots)
            bias_ids, bias_vals = self._bias_rows(requests)
            sampling_dev = tuple(
                jnp.asarray(a) for a in (
                    temperature, top_k, top_p, presence, frequency,
                    seeds, bias_ids, bias_vals,
                )
            )
            tables_dev = jnp.asarray(self._block_tables)
            prev_sampled = np.zeros((slots_n,), dtype=np.int32)
        host_args = [
            tokens, offsets, num_tokens, write_mask, decode_mask,
            completes,
        ]
        if self.mirror is not None:
            self._check_mirror_layout()
            if chained:
                # chained records carry ONLY the window-delta metadata:
                # followers reuse tables/sampling/the previous sample
                # from their own carry — same contract as chained
                # decode, whose records carry nothing at all
                self.mirror.publish(
                    "mixed_chained", {"width": width},
                    [*host_args, chain_mask],
                )
            else:
                # mixed records carry per-row token counts (offsets /
                # num_tokens / the mask trio) in dispatch-arg position —
                # small int32/bool host metadata, like the table rows
                self.mirror.publish(
                    "mixed", {"width": width},
                    [
                        *host_args, self._block_tables, prev_sampled,
                        chain_mask,
                        *(np.asarray(a) for a in sampling_dev),
                    ],
                )
        run = self._get_mixed(width)
        self.cache, self._counts, sampled, lps, tops = run(
            self.params, self.cache, *host_args, tables_dev,
            self._counts, prev_sampled, chain_mask, *sampling_dev,
        )
        return {
            "mixed": True,
            "chained": chained,
            "width": width,
            "plan": plan,
            "sampled": sampled,
            "lps": lps,
            "out_tops": tops,
            "decode_mask": decode_mask,
            "completes": completes,
            "offsets": offsets,
            "num_tokens": num_tokens,
            "sampling_dev": sampling_dev,
            "tables_dev": tables_dev,
            "epochs": epochs,
            "steps": 1,
            "started": started,
            "kv_tokens": kv_tokens,
            "prefill_kv_tokens": prefill_kv_tokens,
            "prefill_tokens": prefill_tokens,
            "n_decode": int(decode_mask.sum()),
            "queue_depth": len(self._pending),
            "trace_ids": trace_ids,
        }

    def _process_mixed(self, inflight: Dict[str, Any]) -> None:
        sampled = np.asarray(inflight["sampled"])
        lps = np.asarray(inflight["lps"])
        tops = inflight.get("out_tops")
        if tops is not None:
            tops = (np.asarray(tops[0]), np.asarray(tops[1]))
        ended = time.perf_counter()
        wall = ended - inflight["started"]
        decode_mask = inflight["decode_mask"]
        completes = inflight["completes"]
        plan = inflight["plan"]
        n_decode = inflight["n_decode"]
        prefill_toks = inflight["prefill_tokens"]
        # the mixed step IS a decode step for its riders; its whole wall
        # is decode time — there is no separate prefill dispatch or
        # harvest stall to bill, which is the point of the fusion
        self.stats["decode_steps"] += 1
        self.stats["decode_chunks"] += 1
        self.stats["decode_token_steps"] += 1.0
        self.stats["mixed_steps"] += 1
        if inflight.get("chained"):
            self.stats["mixed_steps_chained"] += 1
        # host-gap evidence: device idle between the previous mixed
        # step's host processing and this step's dispatch — ~0 for
        # chained steps (dispatched before the previous harvest), the
        # per-step host tax for unchained ones (what the carry hides)
        gap_ms = (
            max(0.0, inflight["started"] - self._last_mixed_end) * 1e3
            if self._last_mixed_end else 0.0
        )
        self._last_mixed_end = ended
        self.stats["mixed_gap_time"] += gap_ms / 1e3
        self.stats["active_slot_steps"] += n_decode
        self.stats["decode_time"] += max(
            0.0, ended - max(inflight["started"], self._decode_busy_until)
        )
        self._decode_busy_until = max(self._decode_busy_until, ended)
        if len(self.chunk_log) < 65536:
            self.chunk_log.append((1, n_decode, wall))
        self._log_dispatch(
            "mixed", tokens=n_decode + prefill_toks,
            rows=n_decode + len(plan), wall=wall, steps=1,
            prefill_tokens=prefill_toks,
        )
        self._step_ewma = (
            wall if self._step_ewma is None
            else 0.8 * self._step_ewma + 0.2 * wall
        )
        DECODE_STEP_SECONDS.observe(wall)
        windows = list(plan.values())
        chunk_flops = self.cost_model.mixed_step_flops(
            n_decode, inflight["kv_tokens"], windows
        )
        chunk_bytes = self.cost_model.mixed_step_bytes(
            inflight["kv_tokens"] + inflight["prefill_kv_tokens"],
            n_decode + prefill_toks,
        )
        self.stats["decode_flops"] += chunk_flops
        self.stats["decode_bytes"] += chunk_bytes
        mfu = accounting.CostModel.mfu(chunk_flops, wall, self.peaks)
        mbu = accounting.CostModel.mbu(chunk_bytes, wall, self.peaks)
        if n_decode or plan:
            MFU_PER_CHUNK.observe(mfu)
            MBU_PER_CHUNK.observe(mbu)
        if self.tracer.enabled or flight.RECORDER.enabled:
            self.tracer.event(
                "engine.decode_chunk",
                wall,
                start_wall=time.time() - wall,
                trace_ids=inflight["trace_ids"],
                steps=1,
                active=n_decode,
                step_ms=round(wall * 1e3, 3),
                mfu=round(mfu, 6),
                mbu=round(mbu, 6),
            )
            flight.record(
                "decode_chunk",
                steps=1,
                active=n_decode,
                slots=self.max_slots,
                step_ms=round(wall * 1e3, 3),
                queue_depth=inflight["queue_depth"],
                kv_frac=round(
                    self.kv_manager.blocks_in_use / float(self.num_blocks),
                    4,
                ),
                tokens=self.stats["tokens_generated"],
                mfu=round(mfu, 6),
                mbu=round(mbu, 6),
                tokens_useful=self.stats["tokens_useful"],
                tokens_wasted=sum(self.stats["tokens_wasted"].values()),
                kv_blocks_in_use=self.kv_manager.blocks_in_use,
                kv_blocks_total=self.num_blocks,
                prefix_hit_tokens=self.kv_manager.stats["hit_tokens"],
                # mixed-dispatch series: how much prompt work rode this
                # step (ab_analyze reads these next to step_ms — the
                # stall-free-batching evidence); `chained`/`gap_ms` are
                # the carry's pipelining proof (chained steps overlap
                # the previous harvest, so their gap collapses to ~0)
                mixed=1,
                width=inflight["width"],
                prefill_rows=len(plan),
                prefill_tokens=prefill_toks,
                chained=1 if inflight.get("chained") else 0,
                gap_ms=round(gap_ms, 3),
            )
        emit_started = time.perf_counter()
        stale_rows = 0
        for i, slot in enumerate(self.slots):
            if slot.epoch != inflight["epochs"][i] or not slot.active:
                if inflight.get("chained") and (
                    decode_mask[i] or (i in plan and completes[i])
                ):
                    # the speculated step sampled for a row whose
                    # request stopped/was cancelled while it was in
                    # flight — bill the discarded work to the ledger
                    stale_rows += 1
                continue
            top = (
                (tops[0][i].tolist(), tops[1][i].tolist())
                if tops is not None else None
            )
            if decode_mask[i]:
                slot.length += 1
                self._emit_token(i, int(sampled[i]), float(lps[i]), top=top)
            elif i in plan and completes[i]:
                request = slot.request
                slot.prefilling = False
                slot.prefill_pos = None
                request._prefill_time = (  # type: ignore[attr-defined]
                    ended - slot.prefill_t0
                )
                self.stats[
                    "warm_prefill_calls" if slot.prefill_reused
                    else "prefill_calls"
                ] += 1
                if self.tracer.enabled:
                    submit_ts = getattr(
                        request, "_submit_ts", slot.prefill_t0
                    )
                    self.tracer.event(
                        "engine.prefill",
                        max(0.0, ended - slot.prefill_t0),
                        trace_id=request.trace_id or "",
                        start_wall=time.time() - (ended - slot.prefill_t0),
                        slot=i,
                        prompt_tokens=len(request.prompt_tokens),
                        reused_tokens=slot.prefill_reused,
                        prefill_tokens=(
                            len(request.prompt_tokens)
                            - slot.prefill_reused
                        ),
                        ttft_ms=round((ended - submit_ts) * 1e3, 3),
                    )
                if request.replay_tokens:
                    # resurrected session: fast-forward through the
                    # accepted history instead of emitting the window's
                    # own sample (see _resume_replay)
                    self._resume_replay(
                        i, request, reused=slot.prefill_reused
                    )
                else:
                    self._emit_token(
                        i, int(sampled[i]), float(lps[i]), top=top
                    )
        if stale_rows:
            self._waste("carry_invalidated", stale_rows)
            self._note_carry_invalidation("stale_row", stale_rows)
        self.stats["emit_time"] += time.perf_counter() - emit_started
        # chaos: deterministic engine-thread death AFTER this step's
        # tokens reached their callers (same point as _process_decode)
        faults.check("engine_thread_crash")

    def _process_decode(self, inflight: Dict[str, Any]) -> None:
        if inflight.get("mixed"):
            return self._process_mixed(inflight)
        # a plain chunk ends any contiguous mixed phase: the next mixed
        # step's gap should not span the decode chunks in between
        self._last_mixed_end = 0.0
        steps = inflight["steps"]
        active = inflight["active"]
        spec = self.spec
        # plain: [S, steps]; spec: [S, steps, B] with a True-prefix
        # valid mask per (slot, step) — 1..B tokens per step
        out_host = np.asarray(inflight["out_tokens"])
        lps_host = np.asarray(inflight["out_lps"])
        tops = inflight.get("out_tops")
        if tops is not None:  # ([S, steps, K] ids, [S, steps, K] lps)
            tops = (np.asarray(tops[0]), np.asarray(tops[1]))
        ended = time.perf_counter()
        wall = ended - inflight["started"]
        n_active = int(active.sum())
        drafted_total = accepted_total = 0
        if spec:
            valid_host = np.asarray(inflight["out_valid"])      # [S, steps, B]
            drafted_host = np.asarray(inflight["out_drafted"])  # [S, steps]
            emitted_total = int(valid_host[active].sum())
            drafted_total = int(drafted_host[active].sum())
            # per (slot, step) the block emits 1 + (leading accepted
            # drafts) tokens — the +1 is the bonus/fallback token the
            # verify logits fund either way
            accepted_total = emitted_total - n_active * steps
            self.stats["tokens_drafted"] += drafted_total
            self.stats["tokens_draft_accepted"] += accepted_total
            # rejected drafts burned verify FLOPs/bandwidth for tokens
            # nobody receives: a first-class wasted reason in the
            # goodput ledger, NOT silently folded into useful work
            self._waste("draft_rejected", drafted_total - accepted_total)
            token_steps = emitted_total / n_active if n_active else float(steps)
        else:
            token_steps = float(steps)
        # per-accepted-token wall-time normalizer (watchdog baseline):
        # equals `steps` for plain decode; under speculation a step
        # legitimately takes longer but yields 1..spec_k+1 tokens
        self.stats["decode_token_steps"] += token_steps
        self.stats["decode_steps"] += steps
        self.stats["decode_chunks"] += 1
        # pipelined chunks overlap in wall time (chunk N+1 is dispatched
        # before N is processed): account the UNION of busy intervals, or
        # decode_time would double-count overlap and the derived raw
        # capability (tokens / decode_time) would mismeasure
        self.stats["decode_time"] += max(
            0.0,
            ended - max(inflight["started"], self._decode_busy_until),
        )
        self._decode_busy_until = max(self._decode_busy_until, ended)
        self.stats["active_slot_steps"] += n_active * steps
        if len(self.chunk_log) < 65536:
            self.chunk_log.append((steps, n_active, wall))
        self._log_dispatch(
            "decode",
            tokens=(
                emitted_total if spec else steps * n_active
            ),
            rows=n_active, wall=wall, steps=steps,
        )
        step_s = wall / max(steps, 1)
        # EWMA step time: the Retry-After estimator for shed requests
        # and degraded-mode 503s (coarse but self-calibrating)
        self._step_ewma = (
            step_s if self._step_ewma is None
            else 0.8 * self._step_ewma + 0.2 * step_s
        )
        DECODE_STEP_SECONDS.observe(step_s)
        # per-chunk roofline: modeled FLOPs/HBM bytes over measured wall
        # → MFU/MBU vs the per-chip peak. A chunk overlapped by
        # pipelining shares wall time with its neighbour, so per-chunk
        # values can read slightly high; the cumulative gauges divide by
        # the busy-time union and stay honest.
        chunk_flops = self.cost_model.decode_chunk_flops(
            steps, n_active, inflight["kv_tokens"], block=self.spec_block
        )
        chunk_bytes = self.cost_model.decode_chunk_bytes(
            steps, n_active, inflight["kv_tokens"], block=self.spec_block
        )
        self.stats["decode_flops"] += chunk_flops
        self.stats["decode_bytes"] += chunk_bytes
        mfu = accounting.CostModel.mfu(chunk_flops, wall, self.peaks)
        mbu = accounting.CostModel.mbu(chunk_bytes, wall, self.peaks)
        if n_active:
            MFU_PER_CHUNK.observe(mfu)
            MBU_PER_CHUNK.observe(mbu)
        if self.tracer.enabled or flight.RECORDER.enabled:
            step_ms = round(wall / max(steps, 1) * 1e3, 3)
            # one span per chunk, tagged with every rider's trace id so
            # the merge tool can pull a request's device chunks into its
            # timeline without per-slot span spam; rider ids / queue
            # depth / KV pressure were snapshotted at DISPATCH (a slot
            # may have been recycled to a new request since)
            self.tracer.event(
                "engine.decode_chunk",
                wall,
                start_wall=time.time() - wall,
                trace_ids=inflight["trace_ids"],
                steps=steps,
                active=n_active,
                step_ms=step_ms,
                mfu=round(mfu, 6),
                mbu=round(mbu, 6),
            )
            kv_fields = {}
            if self.paged:
                # A/B-able pool pressure series (tools/ab_analyze.py):
                # blocks resident vs total, cumulative prefix-hit tokens
                kv_fields = dict(
                    kv_blocks_in_use=inflight["kv_blocks"],
                    kv_blocks_total=self.num_blocks,
                    prefix_hit_tokens=inflight["prefix_hit_tokens"],
                )
            if spec:
                # speculation gain series: drafted vs verify-accepted
                # candidates this chunk — ab_analyze digests the
                # acceptance rate and dispatches-per-token from these
                kv_fields.update(
                    drafted=drafted_total, accepted=accepted_total,
                )
            flight.record(
                "decode_chunk",
                steps=steps,
                active=n_active,
                slots=self.max_slots,
                step_ms=step_ms,
                queue_depth=inflight["queue_depth"],
                kv_frac=inflight["kv_frac"],
                tokens=self.stats["tokens_generated"],
                # efficiency series: per-chunk roofline utilization +
                # cumulative goodput ledger (ab_analyze digests these
                # into per-leg efficiency columns)
                mfu=round(mfu, 6),
                mbu=round(mbu, 6),
                tokens_useful=self.stats["tokens_useful"],
                tokens_wasted=sum(
                    self.stats["tokens_wasted"].values()
                ),
                **kv_fields,
            )
        emit_started = time.perf_counter()
        for i, slot in enumerate(self.slots):
            if not active[i]:
                continue
            if slot.epoch != inflight["epochs"][i]:
                # the slot was recycled while this chunk was in flight —
                # its sampled tokens belong to the finished request
                continue
            for j in range(steps):
                if not slot.active:
                    # finished mid-chunk: surplus sampled tokens discarded;
                    # the length pointer stopped where the stop hit, so the
                    # garbage cache rows beyond it are dead
                    break
                if spec:
                    # variable tokens per step: the valid mask is a
                    # True-prefix over the block; a stop landing
                    # mid-block discards the accepted suffix the same
                    # way a mid-chunk stop discards surplus steps
                    # (length rewind — rows past the stop are dead)
                    for b in range(self.spec_block):
                        if not valid_host[i, j, b] or not slot.active:
                            break
                        slot.length += 1
                        self._emit_token(
                            i, int(out_host[i, j, b]),
                            float(lps_host[i, j, b]),
                            top=(
                                (
                                    tops[0][i, j, b].tolist(),
                                    tops[1][i, j, b].tolist(),
                                )
                                if tops is not None else None
                            ),
                        )
                    continue
                slot.length += 1
                self._emit_token(
                    i, int(out_host[i, j]), float(lps_host[i, j]),
                    top=(
                        (tops[0][i, j].tolist(), tops[1][i, j].tolist())
                        if tops is not None else None
                    ),
                )
        self.stats["emit_time"] += time.perf_counter() - emit_started
        # chaos: deterministic engine-thread death AFTER this chunk's
        # tokens reached their callers — the supervisor must resurrect
        # every live session from exactly this point, and the resumed
        # continuation must match the uncrashed oracle bitwise
        faults.check("engine_thread_crash")

    def _emit_token(
        self, index: int, token: int, logprob: float = 0.0, top=None
    ) -> None:
        """Record a newly generated token for a slot; finish if stopping."""
        slot = self.slots[index]
        request = slot.request
        if not slot.generated:
            # first token: TTFT anchor for the request span / flight log
            # (wall twin anchors the journey ledger's prefill→decode
            # stage boundary on the cross-replica timeline)
            request._first_token_ts = (  # type: ignore[attr-defined]
                time.perf_counter()
            )
            request._first_token_wall = (  # type: ignore[attr-defined]
                time.time()
            )
        slot.generated.append(token)
        slot.logprobs.append(logprob)
        if slot.tops is not None:
            slot.tops.append(top if top is not None else ([], []))
        hit_stop = token in request.stop_tokens
        if not hit_stop:
            # stop tokens stay out of the history so a session follow-up
            # prompt (which re-renders the answer without the stop marker)
            # still prefix-matches the warm cache
            slot.history.append(token)
        self.stats["tokens_generated"] += 1
        done = (
            hit_stop
            or request.cancelled
            or len(slot.generated) >= request.sampling.max_new_tokens
            or slot.length + 1 >= self.max_seq_len
        )
        if request.on_token is not None and not hit_stop:
            self._post(request, request.on_token, token, done)
        if done:
            if hit_stop:
                reason = "stop"
            elif request.cancelled:
                reason = "cancelled"
            else:
                reason = "length"
            self._finish(index, reason)

    def _finish(self, index: int, reason: str) -> None:
        slot = self.slots[index]
        request = slot.request
        generated = list(slot.generated)
        logprobs = list(slot.logprobs)
        tops = list(slot.tops) if slot.tops is not None else None
        if generated and generated[-1] in request.stop_tokens:
            generated = generated[:-1]
            logprobs = logprobs[:-1]
            if tops is not None:
                tops = tops[:-1]
        # resurrected sessions carry prompt + replay[:-1] in
        # prompt_tokens; usage accounting must report the ORIGINAL
        # prompt length, not the teacher-forced replay prefill's
        prompt_tokens = (
            request.prompt_len if request.prompt_len is not None
            else len(request.prompt_tokens)
        )
        result = GenerationResult(
            tokens=generated,
            prompt_tokens=prompt_tokens,
            finish_reason=reason,
            prefill_time=getattr(request, "_prefill_time", 0.0),
            logprobs=logprobs,
            top_logprobs=tops,
        )
        self.stats["requests"] += 1
        # goodput ledger: a cancelled request's tokens were decoded for
        # a caller that stopped listening (client disconnect / stop
        # string landed); everything else reached a live consumer
        if reason == "cancelled":
            self._waste("cancelled", len(generated))
        else:
            self.stats["tokens_useful"] += len(generated)
        # per-request latency attribution: TTFT (submit → first token) +
        # TPOT (mean inter-token gap after the first). Always computed —
        # the SLO histograms/burn rates must not depend on tracing being
        # enabled (one subtraction + histogram insert per request)
        now_pc = time.perf_counter()
        submit_ts = getattr(request, "_submit_ts", now_pc)
        first_ts = getattr(request, "_first_token_ts", now_pc)
        ttft_ms = round((first_ts - submit_ts) * 1e3, 3)
        tpot_ms = (
            round((now_pc - first_ts) / (len(generated) - 1) * 1e3, 3)
            if len(generated) > 1 else 0.0
        )
        TTFT_SECONDS.observe(max(0.0, ttft_ms / 1e3))
        if len(generated) > 1:
            TPOT_SECONDS.observe(max(0.0, tpot_ms / 1e3))
        REQUEST_SECONDS.observe(max(0.0, now_pc - submit_ts))
        if self.slo is not None:
            self.slo.tick()
        if self.tracer.enabled or flight.RECORDER.enabled:
            submit_wall = getattr(request, "_submit_wall", time.time())
            tid = request.trace_id or ""
            self.tracer.event(
                "engine.request",
                max(0.0, now_pc - submit_ts),
                trace_id=tid,
                start_wall=submit_wall,
                slot=index,
                prompt_tokens=len(request.prompt_tokens),
                tokens=len(generated),
                finish_reason=reason,
                ttft_ms=ttft_ms,
                tpot_ms=tpot_ms,
            )
            flight.record(
                "request",
                trace_id=tid,
                prompt_tokens=len(request.prompt_tokens),
                tokens=len(generated),
                finish_reason=reason,
                ttft_ms=ttft_ms,
                tpot_ms=tpot_ms,
            )
        # pin the slot for session reuse; otherwise free it fully
        slot.request = None
        slot.epoch += 1
        slot.generated = None
        slot.logprobs = None
        slot.tops = None
        if self.paged and slot.blocks is not None:
            if self.prefix_cache:
                # publish the completed prefix (prompt + generated) —
                # only rows actually IN the cache (the final sampled
                # token is never written before finish), full blocks
                # only. This is what makes the prefix persistent: the
                # chain outlives the slot, refcounted by the map.
                self.kv_manager.publish(
                    slot.history[: slot.length], slot.blocks
                )
                if request.export_handoff and reason != "cancelled":
                    # disaggregation prefill leg: serialize the chain
                    # just published, while the slot's refs still pin
                    # it (no eviction race inside this finish)
                    export_start = time.time()
                    result.kv_handoff = self._export_handoff(
                        slot, request
                    )
                    if result.kv_handoff is not None:
                        request._jt_export = (  # type: ignore[attr-defined]
                            export_start, time.time()
                        )
            if request.session_id is not None:
                slot.session_id = request.session_id
                slot.last_used = time.monotonic()
                slot.history = slot.history[: slot.length]
                # trim the worst-case reservation down to what the
                # session actually holds: an idle pinned session must
                # not sit on never-written tail blocks the allocator
                # can neither use nor evict (refcount pins them)
                keep = -(-slot.length // self.block_size)
                for extra in slot.blocks[keep:]:
                    self.kv_manager.unref(extra)
                slot.blocks = slot.blocks[:keep]
                self._block_tables[index, keep:] = 0
            else:
                # sessionless: drop the slot's references — uncached
                # blocks free immediately, published ones stay matchable
                # until LRU eviction needs them
                self.kv_manager.release(slot.blocks)
                slot.blocks = None
                slot.session_id = None
                slot.history = None
                slot.length = 0
                self._block_tables[index, :] = 0
        elif request.session_id is not None:
            slot.session_id = request.session_id
            slot.last_used = time.monotonic()
            # keep only the history that is actually IN the cache (the
            # final sampled token is never written before finish)
            slot.history = slot.history[: slot.length]
        elif self.prefix_cache:
            # sessionless: the slot is fully free, but keep the (trimmed)
            # token history so later traffic sharing a template prefix
            # can cross-slot copy the rows instead of re-prefilling
            slot.session_id = None
            slot.history = slot.history[: slot.length]
        else:
            slot.session_id = None
            slot.history = None
            slot.length = 0
        self._emit_journey(
            index, request, reason, len(generated), ttft_ms
        )
        if request.future is not None:
            self._post_future(request, result)

    def _emit_journey(
        self,
        index: int,
        request: GenerationRequest,
        reason: str,
        tokens: int,
        ttft_ms: float,
    ) -> None:
        """Assemble this leg's journey stages (wall clock, tiled by
        StageBuilder construction), feed the per-stage histograms and
        SLO blame — always — and emit the ``journey`` flight record +
        per-stage trace events when those sinks are enabled."""
        now_wall = time.time()
        submit_wall = getattr(request, "_submit_wall", now_wall)
        admit_wall = getattr(request, "_admit_wall", submit_wall)
        first_wall = getattr(request, "_first_token_wall", None)
        import_window = getattr(request, "_jt_import", None)
        export_window = getattr(request, "_jt_export", None)
        admit_class = getattr(request, "_jt_admit_class", None) or "cold"
        builder = journey_ledger.StageBuilder()
        if request.handoff_export_ts is not None:
            # decode leg of a disaggregated request: the prefill
            # replica's export stamp (off the chunk-0 manifest) anchors
            # transit — fabric + assembly time until our submit
            builder.add(
                "handoff_transit", request.handoff_export_ts, submit_wall
            )
        builder.add(
            "queue",
            submit_wall,
            import_window[0] if import_window else admit_wall,
        )
        if import_window:
            builder.add(
                "handoff_import", import_window[0], import_window[1]
            )
        builder.add(
            "admit", admit_wall, admit_wall, admit_class=admit_class
        )
        builder.add(
            "prefill",
            admit_wall,
            first_wall if first_wall is not None else admit_wall,
        )
        decode_end = export_window[0] if export_window else now_wall
        builder.add(
            "decode",
            first_wall if first_wall is not None else admit_wall,
            decode_end,
        )
        if export_window:
            builder.add(
                "handoff_export", export_window[0], export_window[1]
            )
        builder.add(
            "finish",
            export_window[1] if export_window else decode_end,
            now_wall,
            finish_reason=reason,
        )
        stages = builder.stages
        journey_ledger.observe_stages(stages)
        first_ref = first_wall
        if self.slo is not None and self.slo.targets_s:
            ttft_target = self.slo.targets_s.get("ttft")
            if (
                ttft_target is not None
                and ttft_ms / 1e3 > ttft_target
            ):
                self.slo.attribute(
                    "ttft",
                    journey_ledger.blame_stage(stages, first_ref, "ttft"),
                )
            tpot_target = self.slo.targets_s.get("tpot")
            if (
                tpot_target is not None
                and tokens > 1
                and first_wall is not None
                and (decode_end - first_wall) / (tokens - 1) > tpot_target
            ):
                self.slo.attribute(
                    "tpot",
                    journey_ledger.blame_stage(stages, first_ref, "tpot"),
                )
        if not (self.tracer.enabled or flight.RECORDER.enabled):
            return
        tid = request.trace_id or ""
        flight.record(
            "journey",
            trace_id=tid,
            session_id=request.session_id or "",
            slot=index,
            finish_reason=reason,
            tokens=tokens,
            admit_class=admit_class,
            first_token=first_wall,
            ttft_ms=ttft_ms,
            e2e_ms=round((now_wall - stages[0]["start"]) * 1e3, 3),
            stages=stages,
        )
        if self.tracer.enabled and tid:
            replica = flight.get_identity().get("replica", "")
            for stage in stages:
                self.tracer.event(
                    f"engine.journey.{stage['stage']}",
                    stage["end"] - stage["start"],
                    trace_id=tid,
                    start_wall=stage["start"],
                    slot=index,
                    replica=replica,
                )

    def _resolve_cancelled(self, request: GenerationRequest) -> None:
        """Resolve a request cancelled before it ever reached a slot."""
        self.stats["requests"] += 1
        if request.future is not None:
            self._post_future(
                request,
                GenerationResult(
                    # a resurrected request cancelled before re-admission
                    # still owes its caller the already-delivered tokens
                    tokens=list(request.replay_tokens or []),
                    prompt_tokens=(
                        request.prompt_len
                        if request.prompt_len is not None
                        else len(request.prompt_tokens)
                    ),
                    finish_reason="cancelled",
                    logprobs=list(request.replay_logprobs or []),
                ),
            )

    def _post(self, request: GenerationRequest, fn, *args) -> None:
        if request.loop is not None:
            request.loop.call_soon_threadsafe(fn, *args)
        else:
            fn(*args)

    def _post_future(self, request: GenerationRequest, result) -> None:
        def resolve():
            if not request.future.done():
                request.future.set_result(result)

        if request.loop is not None:
            request.loop.call_soon_threadsafe(resolve)
        else:
            request.future.set_result(result)

    def _fail_all_pending(self) -> None:
        """Fail EVERY waiter promptly: queued, pending, and in-flight.
        A crashed engine must never leave a caller hanging (the future is
        the contract streaming callers await on — see
        JaxCompletionsService.get_chat_completions)."""
        error = RuntimeError("decode engine crashed; see logs")

        def fail(request: GenerationRequest) -> None:
            fail_request_future(request, error)

        # drain anything submitted but not yet picked up by the loop
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                self._pending.append(item)
        for request in self._pending:
            fail(request)
        self._pending = []
        self._prefill_inflight = []
        for slot in self.slots:
            if slot.active:
                fail(slot.request)
                slot.request = None
                slot.prefilling = False

    def _fail_stragglers(self) -> None:
        """Fail (with the typed retryable error) any request sitting in
        this retired engine's queue: the recovery drain already swept it
        once, so nothing will ever read these again. Futures the drain
        DID capture are untouched — they ride the resurrection."""
        error = api_errors.EngineRebuildingError(
            "engine is rebuilding after a crash; retry shortly",
            retry_after_s=2.0,
        )
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                fail_request_future(item, error)

    # ------------------------------------------------------------------ #
    # supervisor takeover (runtime/supervisor.py)
    # ------------------------------------------------------------------ #
    # lint: allow(owned-by-violation) -- supervisor heal arc: runs only
    #   after the device thread has exited (crash hook fires on the
    #   dying thread itself) or was condemned + joined (request_restart);
    #   slot neutralization here fences any wedged zombie that survives
    #   the join timeout
    def drain_for_recovery(self) -> List[GenerationRequest]:
        """Turn every live session of this (dead or condemned) engine
        into a request the supervisor can resubmit to a rebuilt one.

        Active slots become REPLAY requests: ``prompt_tokens`` is
        rewritten to ``prompt + generated[:-1]`` (a normal prefill
        teaches it back into the cache — block-granular prefix hits make
        it cheap on paged engines) and the accepted tokens ride
        ``replay_tokens`` so :meth:`_resume_replay` fast-forwards the
        slot bitwise. Queued / pending / still-prefilling requests (no
        token ever reached their caller) resubmit untouched. Slots are
        neutralized FIRST, so a wedged engine thread that wakes up after
        an escalation takeover can never emit into a resurrected
        caller's stream."""
        requests: List[GenerationRequest] = []
        # flag FIRST, then sweep: any submit whose put lands after this
        # point either gets collected below or fails itself in submit()
        # (_fail_stragglers) — no interleaving leaves a caller hanging
        self._recovery_drained = True
        # drain anything submitted but never picked up by the dead loop
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                self._pending.append(item)
        for slot in self.slots:
            if not slot.active:
                continue
            request = slot.request
            generated = list(slot.generated or [])
            logprobs = list(slot.logprobs or [])
            tops = list(slot.tops) if slot.tops is not None else None
            # neutralize before snapshotting anything else: a zombie
            # thread finds the slot inactive and skips emission
            slot.request = None
            slot.prefilling = False
            slot.prefill_pos = None
            slot.epoch += 1
            original = (
                request.prompt_len if request.prompt_len is not None
                else len(request.prompt_tokens)
            )
            if generated:
                prompt = request.prompt_tokens[:original]
                request.prompt_len = original
                request.prompt_tokens = prompt + generated[:-1]
                request.replay_tokens = generated
                request.replay_logprobs = logprobs
                request.replay_tops = tops
            requests.append(request)
        requests.extend(self._pending)
        self._pending = []
        self._prefill_inflight = []
        return requests

    def retire(self) -> None:
        """Drop this engine from the /metrics aggregation immediately
        (a superseded engine must not double-count against its
        replacement while awaiting GC)."""
        _LIVE_ENGINES.discard(self)

    # lint: allow(owned-by-violation) -- supervisor heal arc: runs on
    #   the rebuilt engine BEFORE start(), so its device thread does not
    #   exist yet (no concurrent mutator)
    def absorb_stats(self, previous: Dict[str, Any]) -> None:
        """Carry a crashed predecessor's cumulative counters into this
        engine so every /metrics series stays monotonic across a
        supervisor rebuild (a token counter dropping to zero reads as a
        counter reset mid-incident — exactly when dashboards matter)."""
        for key, value in previous.items():
            if isinstance(value, dict):
                mine = self.stats.setdefault(key, {})
                for reason, count in value.items():
                    mine[reason] = mine.get(reason, 0) + count
            elif isinstance(value, (int, float)):
                self.stats[key] = self.stats.get(key, 0) + value


def _sampling_keys(
    seeds: jnp.ndarray,       # [S] uint32 per-request seeds
    positions: jnp.ndarray,   # [S] cache positions (monotonic per step)
) -> jnp.ndarray:
    """One PRNG key per slot, derived from (seed, position) — sampling
    is a pure function of the request, never of its batch neighbours."""
    def derive(seed, position):
        return jax.random.fold_in(jax.random.PRNGKey(seed), position)

    return jax.vmap(derive)(seeds, positions)


def _rowwise_categorical(keys: jnp.ndarray, scaled: jnp.ndarray) -> jnp.ndarray:
    return jax.vmap(
        lambda key, row: jax.random.categorical(key, row)
    )(keys, scaled)


def _sample(
    logits: jnp.ndarray,      # [S, V] f32
    temperature: jnp.ndarray, # [S]
    top_k: jnp.ndarray,       # [S] (0 = disabled)
    keys: jnp.ndarray,        # [S] per-slot PRNG keys (_sampling_keys)
    top_p: Optional[jnp.ndarray] = None,  # [S] (0 = disabled)
    *,
    masked: Optional[jnp.ndarray] = None,  # precomputed _truncation_mask
) -> jnp.ndarray:
    """Per-slot sampling on device: greedy when temperature==0, else
    temperature softmax with optional top-k and/or top-p truncation.

    Tiered via ``lax.cond`` so the expensive paths only execute when a
    slot actually asks for them — the full [S, V] descending sort costs
    a large share of a decode step's wall time at a 128k vocab, and
    greedy/plain-categorical traffic (the common case) doesn't need it.
    A caller that already holds the truncation mask for these logits
    (the speculative acceptance pass needs it for its probabilities)
    passes it as ``masked`` so the truncated tier skips the re-sort."""
    slots, vocab = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    def plain(_):
        # temperature softmax, no truncation: categorical needs no sort
        scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
        return _rowwise_categorical(keys, scaled)

    def truncated(_):
        m = _truncation_mask(logits, top_k, top_p) if masked is None else masked
        scaled = m / jnp.maximum(temperature, 1e-6)[:, None]
        return _rowwise_categorical(keys, scaled)

    any_truncation = jnp.any(top_k > 0)
    if top_p is not None:
        any_truncation = any_truncation | jnp.any(top_p > 0)

    def stochastic(_):
        return jax.lax.cond(any_truncation, truncated, plain, None)

    sampled = jax.lax.cond(
        jnp.any(temperature > 0),
        stochastic,
        lambda _: greedy,
        None,
    )
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


def _truncation_mask(
    logits: jnp.ndarray,      # [S, V]
    top_k: jnp.ndarray,       # [S] (0 = disabled)
    top_p: Optional[jnp.ndarray],  # [S] (0 = disabled)
) -> jnp.ndarray:
    """Top-k/top-p truncation as a -inf mask over the logits — the sort-
    based masking ``_sample``'s truncated tier applies before scaling.
    Shared with the speculative acceptance pass
    (``spec_decode._accept_or_fallback``), which needs the truncated
    distribution's probabilities rather than a sample, so the two paths
    cannot drift."""
    vocab = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]  # descending
    # top-k mask: keep logits >= k-th largest (k clamped to [1, V])
    k = jnp.clip(top_k, 0, vocab)
    kth_index = jnp.clip(k - 1, 0, vocab - 1)
    kth_value = jnp.take_along_axis(
        sorted_logits, kth_index[:, None], axis=1
    )
    masked = jnp.where(
        (k[:, None] > 0) & (logits < kth_value), -jnp.inf, logits
    )
    if top_p is not None:
        # nucleus: keep the smallest set of tokens whose mass >= p
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # threshold = smallest sorted logit still inside the nucleus
        inside = cumulative - probs < top_p[:, None]
        cut = jnp.where(inside, sorted_logits, jnp.inf).min(axis=-1)
        masked = jnp.where(
            (top_p[:, None] > 0) & (masked < cut[:, None]),
            -jnp.inf, masked,
        )
    return masked


def _sample_with_logprob(
    logits: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    keys: jnp.ndarray,
    top_p: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sample and also return each sampled token's log-probability under
    the UNTRUNCATED distribution (the model's own confidence — what the
    FLARE controller consumes; reference: OpenAI-style logprobs)."""
    token = _sample(logits, temperature, top_k, keys, top_p)
    return token, _token_logprob(logits, token)


def _token_logprob(logits: jnp.ndarray, token: jnp.ndarray) -> jnp.ndarray:
    """lp = logits[token] - logsumexp(logits): same value as a full
    log_softmax gather without materializing a second [S, V] array."""
    logits32 = logits.astype(jnp.float32)
    picked = jnp.take_along_axis(logits32, token[:, None], axis=-1)[:, 0]
    return picked - jax.scipy.special.logsumexp(logits32, axis=-1)


def _top_logprobs(logits: jnp.ndarray, k: int):
    """Top-k alternative tokens + logprobs under the RAW untruncated
    distribution (OpenAI ``top_logprobs``): top_k commutes with the
    monotonic log_softmax, so rank on logits and normalize the k
    winners only."""
    logits32 = logits.astype(jnp.float32)
    vals, ids = jax.lax.top_k(logits32, k)
    lse = jax.scipy.special.logsumexp(logits32, axis=-1, keepdims=True)
    return ids.astype(jnp.int32), vals - lse
