"""Host-side accounting for the paged KV cache (``kv_layout: paged``).

The device side is a global block pool ``[layers, num_blocks, block_size,
kv_heads, head_dim]`` (``model.init_paged_cache``) addressed through
per-slot block tables; THIS module owns everything about which block
holds what:

- **Free-list allocation** with per-block refcounts (block 0 is the null
  block — padding rows and masked writes are routed there and its
  content is never read through a live length mask).
- **Prefix cache**: a persistent token-chunk → block map. Keys are
  ``(parent_block, chunk_tokens)`` — chaining through the parent block
  id makes the key collision-free without hashing the whole prefix
  (a chunk's KV depends on the entire token prefix, which the parent
  chain uniquely identifies), which is the AIBrix/vLLM hash-chain idea
  with Python dict identity instead of digests.
- **Refcounted sharing**: a published block may be referenced by any
  number of slot tables at once; it is freed only when its refcount is
  zero AND it has been evicted from the map.
- **LRU eviction**: when allocation runs dry, least-recently-touched
  cached blocks with refcount 0 are unpublished, leaf-first (a block
  with cached children is never evicted before them — a recycled parent
  id would otherwise let a *different* chain's key resolve to a stale
  child whose KV belongs to the old prefix).

Copy-on-write is decided here (:meth:`is_shared`) and executed by the
engine's jitted block-copy: writes into a block that the map or another
slot still references first get a private copy (session follow-ups that
diverge mid-block), so shared prefixes are immutable once published.

**Two tiers** (ISSUE 18): when a :class:`HostKVArena` is attached,
eviction *demotes* victim chains into bounded pinned host RAM instead
of dropping them, and admission can *promote* them back (see
:meth:`PagedKVManager.host_match` and the engine's promotion scatter).
The host tier is keyed by the rolling chain digest (``fleet/router.py``)
rather than ``(parent_block, chunk)``: pool block ids recycle the moment
a chain is evicted, so a block-keyed host entry could resolve a recycled
id to another chain's rows — the digest encodes the whole token prefix
and never recycles.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

# the reserved null block: block tables point padding / masked writes
# here; attention never reads it through a live length mask
NULL_BLOCK = 0


class HostKVEntry:
    """One demoted block's worth of chain, keyed by the rolling chain
    digest of the token prefix it completes. ``data`` is the per-leaf
    host copy of the block's pool rows (``leaf -> [layers, block_size,
    kv_heads, head_dim]``, int8 pools carry their scale leaves too) —
    or None in accounting-only arenas (the fleet sim)."""

    __slots__ = ("digest", "parent_digest", "chunk", "data", "nbytes")

    def __init__(
        self,
        digest: str,
        parent_digest: str,
        chunk: Tuple[int, ...],
        data: Optional[Dict[str, object]],
        nbytes: int,
    ) -> None:
        self.digest = digest
        self.parent_digest = parent_digest  # "" = chain root
        self.chunk = chunk
        self.data = data
        self.nbytes = int(nbytes)


class HostKVArena:
    """Bounded pinned-host-RAM demotion tier below the HBM pool.

    Same LRU discipline as the HBM prefix cache, leaf-first by design:
    a parent entry is never evicted while a demoted child is resident,
    so the host tier's digest set stays ancestry-complete *within the
    tier* (an entry's missing ancestors are, by leaf-first HBM
    demotion order, still published in HBM) — the invariant heartbeat
    gossip relies on for leading-prefix scoring.

    Unlike :class:`PagedKVManager` (engine-thread-owned), this class IS
    thread-safe: the engine thread demotes/promotes while the gossip
    task snapshots :meth:`digests` for heartbeats, so every access
    holds ``_lock``.
    """

    def __init__(self, capacity_blocks: int) -> None:
        if capacity_blocks < 1:
            raise ValueError("host arena needs at least 1 block")
        self.capacity_blocks = int(capacity_blocks)
        self._lock = threading.Lock()
        self._entries: Dict[str, HostKVEntry] = {}  # guarded-by: _lock
        # digest -> count of RESIDENT children (incremented at child
        # put, decremented at child removal — a digest forest always
        # has a leaf, so eviction always progresses)
        self._children: Dict[str, int] = {}  # guarded-by: _lock
        self._lru: Dict[str, int] = {}  # guarded-by: _lock
        self._tick = 0  # guarded-by: _lock
        self.stats: Dict[str, int] = {  # guarded-by: _lock
            "demoted_blocks": 0,   # entries accepted from the HBM tier
            "promoted_blocks": 0,  # entries scattered back to HBM
            "evictions": 0,        # entries dropped by host-tier LRU
            "demoted_bytes": 0,    # host bytes written by demotions
        }

    # requires-lock: _lock
    def _touch_locked(self, digest: str) -> None:
        self._tick += 1
        self._lru[digest] = self._tick

    # requires-lock: _lock
    def _remove_locked(self, digest: str) -> None:
        entry = self._entries.pop(digest)
        self._lru.pop(digest, None)
        self._children.pop(digest, None)
        parent = entry.parent_digest
        if parent:
            left = self._children.get(parent, 0) - 1
            if left > 0:
                self._children[parent] = left
            else:
                self._children.pop(parent, None)

    # requires-lock: _lock
    def _evict_locked(self) -> bool:
        """Drop the least-recently-used LEAF entry (no resident
        children). Leaf-first mirrors the HBM pool's discipline and
        keeps resident chains ancestry-complete."""
        for digest, _ in sorted(self._lru.items(), key=lambda kv: kv[1]):
            if self._children.get(digest, 0) == 0:
                self._remove_locked(digest)
                self.stats["evictions"] += 1
                return True
        return False

    @property
    def blocks_in_use(self) -> int:
        with self._lock:
            return len(self._entries)

    def has(self, digest: str) -> bool:
        with self._lock:
            return digest in self._entries

    def touch(self, digest: str) -> None:
        with self._lock:
            if digest in self._entries:
                self._touch_locked(digest)

    def lookup(self, digest: str) -> Optional[HostKVEntry]:
        """The resident entry for ``digest`` (LRU-touched), or None."""
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                self._touch_locked(digest)
            return entry

    def put(
        self,
        digest: str,
        parent_digest: str,
        chunk: Sequence[int],
        data: Optional[Dict[str, object]],
        nbytes: int,
    ) -> bool:
        """Admit one demoted block; capacity pressure evicts LRU leaves
        first. Idempotent per digest (a re-demotion of a promoted chain
        only refreshes the LRU tick). False when the arena refused the
        entry (already resident, or nothing evictable)."""
        with self._lock:
            if digest in self._entries:
                self._touch_locked(digest)
                return False
            while len(self._entries) >= self.capacity_blocks:
                if not self._evict_locked():
                    return False
            self._entries[digest] = HostKVEntry(
                digest, parent_digest, tuple(chunk), data, nbytes
            )
            if parent_digest:
                self._children[parent_digest] = (
                    self._children.get(parent_digest, 0) + 1
                )
            self._touch_locked(digest)
            self.stats["demoted_blocks"] += 1
            self.stats["demoted_bytes"] += int(nbytes)
            return True

    def note_promoted(self, blocks: int) -> None:
        with self._lock:
            self.stats["promoted_blocks"] += int(blocks)

    def digests(self) -> Set[str]:
        """Snapshot of resident digests — heartbeat gossip's host-tier
        tag (``host_chain_digests``); safe from any thread."""
        with self._lock:
            return set(self._entries)

    def snapshot_stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.stats)
            out["blocks_in_use"] = len(self._entries)
            return out


class PagedKVManager:
    """Block accounting for one engine's pool. NOT thread-safe by
    design: every call happens on the engine thread, like the slot
    bookkeeping it extends."""

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks < 2:
            raise ValueError("paged pool needs at least 2 blocks")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: deque = deque(range(1, num_blocks))
        self._refcount = [0] * num_blocks
        # prefix map: (parent block id | -1, tuple(chunk tokens)) -> block
        self._map: Dict[Tuple[int, Tuple[int, ...]], int] = {}
        self._key_of: Dict[int, Tuple[int, Tuple[int, ...]]] = {}
        self._parent: Dict[int, int] = {}
        self._children: Dict[int, int] = {}
        self._lru: Dict[int, int] = {}  # cached block -> last-touch tick
        self._tick = 0
        # opaque per-published-block scratch for external digesters
        # (fleet/router.py:digests_from_keys memoizes its hash chains
        # here): entries live as long as the block stays published —
        # popped in _unpublish, and each entry additionally carries the
        # chain key it was computed for, so even a write-back racing an
        # eviction on another thread can never serve a recycled id a
        # stale digest (the key mismatch forces a recompute)
        self.digest_memo: Dict[int, object] = {}
        # host-DRAM demotion tier (ISSUE 18): when attached, _evict
        # demotes victim chains into the arena instead of dropping
        # them; _demote_data is the optional data-plane hook (the
        # engine's D2H gather — None keeps the arena accounting-only,
        # the fleet sim's mode)
        self.host: Optional[HostKVArena] = None
        self._demote_data: Optional[
            Callable[[int], Optional[Tuple[Dict[str, object], int]]]
        ] = None
        self.stats: Dict[str, int] = {
            "hit_tokens": 0,       # prompt tokens served from cached blocks
            "evictions": 0,        # cached blocks unpublished under pressure
            "cow_copies": 0,       # private copies made before a shared write
            "published_blocks": 0,
            "demotions": 0,        # victim blocks demoted to the host tier
        }

    # ------------------------------------------------------------------ #
    # pool state
    # ------------------------------------------------------------------ #
    @property
    def blocks_in_use(self) -> int:
        """Blocks either referenced by a slot table or held by the
        prefix cache (everything not on the free list, minus null)."""
        return self.num_blocks - 1 - len(self._free)

    @property
    def blocks_cached(self) -> int:
        return len(self._key_of)

    def refcount(self, block: int) -> int:
        return self._refcount[block]

    def is_shared(self, block: int) -> bool:
        """True when writing this block in place would be visible to
        someone else: another slot's table, or the prefix map."""
        return self._refcount[block] > 1 or block in self._key_of

    # ------------------------------------------------------------------ #
    # allocation / refcounts
    # ------------------------------------------------------------------ #
    def allocate(self, n: int) -> Optional[List[int]]:
        """Take ``n`` fresh blocks (refcount 1 each), evicting LRU
        cached chains if the free list is short. None when the pool
        genuinely cannot satisfy the request (every block referenced)."""
        if n <= 0:
            return []
        from langstream_tpu.runtime import faults

        if faults.fire("pool_exhausted") is not None:
            # chaos (LANGSTREAM_FAULTS=pool_exhausted@...): report an
            # exhausted pool without touching real state — admission
            # backpressure / livelock handling on demand, CPU-testable
            return None
        if len(self._free) < n:
            self._evict(n - len(self._free))
        if len(self._free) < n:
            return None
        out = [self._free.popleft() for _ in range(n)]
        for block in out:
            self._refcount[block] = 1
        return out

    def ref(self, blocks: Sequence[int]) -> None:
        for block in blocks:
            self._refcount[block] += 1

    def unref(self, block: int) -> None:
        self._refcount[block] -= 1
        assert self._refcount[block] >= 0, f"refcount underflow on {block}"
        if self._refcount[block] == 0 and block not in self._key_of:
            self._free.append(block)

    def release(self, blocks: Sequence[int]) -> None:
        for block in blocks:
            self.unref(block)

    # ------------------------------------------------------------------ #
    # prefix cache
    # ------------------------------------------------------------------ #
    def _touch(self, block: int) -> None:
        self._tick += 1
        self._lru[block] = self._tick

    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached block chain covering a prefix of ``tokens``
        (block-granular — partial blocks never match). Returns
        (block ids, matched token count); refcounts are NOT taken —
        callers :meth:`ref` the chain once they commit to it."""
        size = self.block_size
        parent, chain = -1, []
        for i in range(len(tokens) // size):
            chunk = tuple(tokens[i * size:(i + 1) * size])
            block = self._map.get((parent, chunk))
            if block is None:
                break
            chain.append(block)
            parent = block
        for block in chain:
            self._touch(block)
        return chain, len(chain) * size

    def publish(self, tokens: Sequence[int], blocks: Sequence[int]) -> None:
        """Make the full blocks of ``tokens`` (held in ``blocks``)
        matchable by future admissions. Idempotent; an existing entry
        for a chunk wins (the canonical chain continues through it, so
        duplicates produced by concurrent identical prompts stay
        private and free normally)."""
        size = self.block_size
        parent = -1
        for i in range(len(tokens) // size):
            if i >= len(blocks):
                break
            block = blocks[i]
            chunk = tuple(tokens[i * size:(i + 1) * size])
            key = (parent, chunk)
            existing = self._map.get(key)
            if existing is not None:
                self._touch(existing)
                parent = existing
                continue
            if block in self._key_of:
                # already published (e.g. re-publish at finish of a
                # chain published at admission) — just walk through it
                parent = block
                continue
            self._map[key] = block
            self._key_of[block] = key
            self._parent[block] = parent
            if parent >= 0:
                self._children[parent] = self._children.get(parent, 0) + 1
            self._touch(block)
            self.stats["published_blocks"] += 1
            parent = block

    def published_keys(
        self, limit: Optional[int] = None
    ) -> Dict[int, Tuple[int, Tuple[int, ...]]]:
        """Snapshot of the published chain map ``block ->
        (parent_block, chunk_tokens)`` — the fleet router's raw
        material (``fleet/router.py:digests_from_keys`` turns it into
        pool-free hash-chain digests for heartbeat gossip).

        ``limit`` caps the snapshot for gossip budgets: the
        most-recently-touched blocks win, with their ancestor chains
        included (publish order + leaf-first eviction guarantee every
        published block's ancestors are published, and a digest set
        missing an ancestor could never match the chain below it)."""
        if limit is None or len(self._key_of) <= limit:
            return dict(self._key_of)
        out: Dict[int, Tuple[int, Tuple[int, ...]]] = {}
        by_recency = sorted(
            self._key_of, key=lambda b: self._lru.get(b, 0), reverse=True
        )
        for block in by_recency:
            if len(out) >= limit:
                break
            walk = block
            chain = []
            while walk >= 0 and walk not in out:
                key = self._key_of.get(walk)
                if key is None:
                    break
                chain.append((walk, key))
                walk = key[0]
            for b, key in chain:
                out[b] = key
        return out

    # ------------------------------------------------------------------ #
    # host-DRAM tier (ISSUE 18)
    # ------------------------------------------------------------------ #
    def attach_host(
        self,
        arena: HostKVArena,
        demote_data: Optional[
            Callable[[int], Optional[Tuple[Dict[str, object], int]]]
        ] = None,
    ) -> None:
        """Attach the host-DRAM demotion tier. ``demote_data(block)``
        is the data-plane hook — the engine's jitted D2H gather of one
        block's pool rows, returning ``(leaf tree, nbytes)`` or None
        when the rows cannot be captured (the chain then drops exactly
        as an un-tiered eviction would). None keeps the arena
        accounting-only: entries carry no rows but matching, LRU and
        capacity backpressure behave identically (the fleet sim's
        mode)."""
        self.host = arena
        self._demote_data = demote_data

    def chain_digest(self, block: int) -> Optional[str]:
        """Rolling chain digest (``fleet/router.py``) of the token
        prefix ending at published ``block``, memoized into
        ``digest_memo`` under the same ``(key, digest)`` format the
        heartbeat digester writes — demotion-time digests and gossip
        digests can never disagree. None when the block (or an
        ancestor) is not published."""
        from langstream_tpu.fleet.router import _chunk_digest

        stack: List[Tuple[int, Tuple[int, Tuple[int, ...]]]] = []
        digest = b""
        walk = block
        while walk >= 0:
            key = self._key_of.get(walk)
            if key is None:
                return None
            memo = self.digest_memo.get(walk)
            if (
                isinstance(memo, tuple) and len(memo) == 2
                and memo[0] == key and isinstance(memo[1], bytes)
                and memo[1]
            ):
                digest = memo[1]
                break
            stack.append((walk, key))
            walk = key[0]
        for b, key in reversed(stack):
            digest = _chunk_digest(digest, key[1])
            self.digest_memo[b] = (key, digest)
        return digest.hex()

    def _demote(self, block: int) -> None:
        """Move a victim chain block into the host tier before it is
        unpublished. Digest-keyed on purpose: the HBM block id recycles
        the moment :meth:`_evict` frees it, so a host entry keyed by
        ``(parent_block, chunk)`` could later resolve a recycled id to
        another chain's rows — the digest encodes the whole token
        prefix and never recycles. Leaf-first eviction order means the
        victim's ancestors are still published here, so the digest walk
        always completes."""
        host = self.host
        if host is None:
            return
        key = self._key_of.get(block)
        if key is None:
            return
        digest = self.chain_digest(block)
        if digest is None:
            return
        if host.has(digest):
            # promoted-then-re-evicted chain: the host copy is bitwise
            # identical (published blocks are immutable), so refresh
            # the LRU tick and skip the D2H gather
            host.touch(digest)
            return
        parent_digest = ""
        if key[0] >= 0:
            parent_digest = self.chain_digest(key[0]) or ""
            if not parent_digest:
                return
        data: Optional[Dict[str, object]] = None
        nbytes = 0
        if self._demote_data is not None:
            fetched = self._demote_data(block)
            if fetched is None:
                return  # data plane unavailable: drop like an eviction
            data, nbytes = fetched
        if host.put(digest, parent_digest, key[1], data, nbytes):
            self.stats["demotions"] += 1

    def host_match(self, tokens: Sequence[int], start_block: int) -> List[HostKVEntry]:
        """Consecutive host-tier entries continuing the HBM chain from
        full-block index ``start_block`` of ``tokens``. Digest-keyed, so
        a match proves the ENTIRE token prefix across both tiers; the
        caller promotes the returned entries (engine: H2D scatter +
        publish-at-commit) or treats them as accounting hits (sim)."""
        host = self.host
        if host is None:
            return []
        size = self.block_size
        full = len(tokens) // size
        if start_block >= full:
            return []
        from langstream_tpu.fleet.router import prompt_digests

        digests = prompt_digests(tokens, size, limit=full)
        out: List[HostKVEntry] = []
        for i in range(start_block, full):
            entry = host.lookup(digests[i])
            if entry is None:
                break
            out.append(entry)
        return out

    # ------------------------------------------------------------------ #
    # KV handoff (prefill/decode disaggregation, fleet/handoff.py)
    # ------------------------------------------------------------------ #
    def export_session(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """The handoff export set: the longest published chain covering
        full blocks of ``tokens``. Unlike :meth:`match`, the chain IS
        refcounted — it must survive concurrent LRU eviction while the
        engine serializes the pool data behind it — so the caller
        :meth:`release`\\ s it once the chunks are on the wire."""
        chain, matched = self.match(tokens)
        self.ref(chain)
        return chain, matched

    def import_session(
        self, tokens: Sequence[int]
    ) -> Optional[Tuple[List[int], List[int]]]:
        """Worst-case reservation at import-admission: returns
        ``(local_chain, fresh_blocks)`` — the locally-published prefix
        (refcounted, its rows need no write) plus freshly allocated
        blocks for every remaining full block of ``tokens`` — or None
        when the pool cannot cover the import even after eviction (the
        caller aborts the handoff and falls back to recompute).

        Fresh blocks stay UNPUBLISHED (refcount 1) until
        :meth:`commit_import`: an aborted partial import releases them
        straight back to the free list, so a handoff torn mid-transfer
        can never leave half-written rows matchable under live chain
        keys before the block ids recycle."""
        size = self.block_size
        full = len(tokens) // size
        chain, matched = self.match(tokens)
        self.ref(chain)
        fresh = self.allocate(full - len(chain))
        if fresh is None:
            self.release(chain)
            return None
        return chain, fresh

    def commit_import(
        self, tokens: Sequence[int], blocks: Sequence[int]
    ) -> None:
        """Publish a completed import under the same collision-free
        ``(parent_block, chunk)`` chain keys a locally-built prefix
        gets — the imported chain gossips as affinity digests and
        matches future admissions like any other — then drop the import
        refs (cache-held, evictable under pressure like any published
        chain)."""
        size = self.block_size
        self.publish(tokens[: (len(tokens) // size) * size], blocks)
        self.release(blocks)

    def abort_import(self, blocks: Sequence[int]) -> None:
        """Unwind a torn import BEFORE any block id recycles: nothing
        was published, so releasing the refs frees the fresh blocks
        (and un-pins any locally-matched prefix) with no stale-chain
        hazard."""
        self.release(blocks)

    def _unpublish(self, block: int) -> None:
        key = self._key_of.pop(block)
        del self._map[key]
        self.digest_memo.pop(block, None)
        parent = self._parent.pop(block)
        if parent >= 0:
            self._children[parent] -= 1
        self._lru.pop(block, None)
        self._children.pop(block, None)

    def _evict(self, count: int) -> int:
        """Unpublish up to ``count`` least-recently-used cached blocks
        that no slot references and that have no cached children
        (leaf-first keeps parent ids from being recycled under live
        chain keys). One LRU-ordered pass per chain depth — evicting a
        leaf can turn its parent into a leaf, so passes repeat only
        while they make progress (NOT one full sort per block)."""
        evicted = 0
        while evicted < count:
            progress = False
            for block, _ in sorted(self._lru.items(), key=lambda kv: kv[1]):
                if evicted >= count:
                    break
                if (
                    self._refcount[block] == 0
                    and not self._children.get(block)
                ):
                    if self.host is not None:
                        self._demote(block)
                    self._unpublish(block)
                    self._free.append(block)
                    self.stats["evictions"] += 1
                    evicted += 1
                    progress = True
            if not progress:
                break
        return evicted

    def _evict_one(self) -> bool:
        return self._evict(1) == 1
