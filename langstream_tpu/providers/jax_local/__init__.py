"""``jax-local``: in-process TPU inference — the flagship service provider.

Replaces the reference's outbound-HTTPS model providers
(``OpenAICompletionService.java:52`` etc.) with JAX/XLA running on the TPU
attached to the agent pod: a Llama-family decoder served by a
continuous-batching engine with slot-based KV cache, plus a BERT-style
encoder for embeddings. Model parallelism (tp/fsdp/sp) is provider config,
not pipeline YAML — one `jax.sharding.Mesh` per process.
"""
