"""Direct safetensors → stacked-params loader for HF checkpoint dirs
(Llama, Mixtral, Qwen-2, Gemma-2).

Unlike :func:`model.load_hf_checkpoint` (which instantiates the torch
model — fine for small models, prohibitive for 8B+ since the whole
float32 state dict must fit host RAM), this reads tensors lazily out of
the ``*.safetensors`` shards one at a time, casts each to the target
dtype immediately, and never holds more than one float32 tensor
transient. This is the loader the serving engine uses for real
checkpoints.

Reference parity: the reference downloads model code archives via its
CodeStorage SPI (langstream-api/src/main/java/ai/langstream/api/codestorage/
CodeStorage.java:22) but never loads model *weights* — models live behind
provider HTTPS APIs. Weight loading is net-new for the in-process TPU
backend.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from langstream_tpu.providers.jax_local.model import LlamaConfig


class SafetensorsDir:
    """Lazy tensor access over a HF checkpoint directory (handles both
    single-file and sharded ``model-0000x-of-0000y.safetensors``
    layouts)."""

    def __init__(self, path: str) -> None:
        self.path = path
        index_path = os.path.join(path, "model.safetensors.index.json")
        self._name_to_file: Dict[str, str] = {}
        if os.path.exists(index_path):
            with open(index_path) as fh:
                index = json.load(fh)
            self._name_to_file = dict(index["weight_map"])
        else:
            for fname in sorted(os.listdir(path)):
                if fname.endswith(".safetensors"):
                    from safetensors import safe_open

                    with safe_open(
                        os.path.join(path, fname), framework="numpy"
                    ) as fh:
                        for key in fh.keys():
                            self._name_to_file[key] = fname
        if not self._name_to_file:
            raise FileNotFoundError(f"no *.safetensors under {path}")
        self._open_files: Dict[str, Any] = {}

    def keys(self) -> Iterator[str]:
        return iter(self._name_to_file)

    def get(self, name: str) -> np.ndarray:
        from safetensors import safe_open

        fname = self._name_to_file[name]
        fh = self._open_files.get(fname)
        if fh is None:
            fh = safe_open(
                os.path.join(self.path, fname), framework="numpy"
            ).__enter__()
            self._open_files[fname] = fh
        tensor = fh.get_tensor(name)
        # bf16 safetensors load as ml_dtypes.bfloat16 numpy arrays —
        # upcast so the transpose/cast below is exact
        if tensor.dtype != np.float32:
            tensor = tensor.astype(np.float32)
        return tensor

    def close(self) -> None:
        for fh in self._open_files.values():
            try:
                fh.__exit__(None, None, None)
            except Exception:
                pass
        self._open_files.clear()


def load_config(path: str) -> LlamaConfig:
    """Build a LlamaConfig from a HF ``config.json`` (delegates to the
    single field mapping in ``model.config_from_hf``)."""
    import types

    from langstream_tpu.providers.jax_local.model import config_from_hf

    with open(os.path.join(path, "config.json")) as fh:
        hf = json.load(fh)
    hf.setdefault("rms_norm_eps", 1e-5)
    hf.setdefault("max_position_embeddings", 4096)
    # save_pretrained omits keys equal to the ARCHITECTURE default, so a
    # raw-JSON load must re-apply the per-family defaults transformers
    # would (Gemma ties embeddings by default; Llama/Qwen do not)
    hf.setdefault(
        "tie_word_embeddings", hf.get("model_type") in ("gemma", "gemma2")
    )
    return config_from_hf(types.SimpleNamespace(**hf))


def load_safetensors_checkpoint(
    path: str,
    dtype: Any = jnp.bfloat16,
    config: Optional[LlamaConfig] = None,
) -> Tuple[LlamaConfig, Dict[str, jnp.ndarray]]:
    """Load (config, stacked-params) straight from safetensors shards.

    Tensor-name mapping mirrors ``model.load_hf_checkpoint``: per-layer
    torch [out, in] matrices transpose to [in, out] and stack along a
    leading layer axis for the lax.scan layout.
    """
    import dataclasses

    if config is None:
        config = load_config(path)
    config = dataclasses.replace(config, dtype=dtype)
    store = SafetensorsDir(path)
    try:
        def get(name, cast_dtype=dtype, transpose=False):
            tensor = store.get(name)
            return jnp.asarray(tensor.T if transpose else tensor, dtype=cast_dtype)

        def stack(pattern, transpose=True):
            return jnp.stack([
                get(pattern.format(layer), transpose=transpose)
                for layer in range(config.num_layers)
            ])

        if config.num_experts:
            def stack_experts(weight):
                return jnp.stack([
                    jnp.stack([
                        get(
                            f"model.layers.{layer}.block_sparse_moe"
                            f".experts.{e}.{weight}.weight",
                            transpose=True,
                        )
                        for e in range(config.num_experts)
                    ])
                    for layer in range(config.num_layers)
                ])

            mlp_weights = {
                "w_gate": stack_experts("w1"),
                "w_up": stack_experts("w3"),
                "w_down": stack_experts("w2"),
                "router": stack("model.layers.{}.block_sparse_moe.gate.weight"),
            }
        else:
            mlp_weights = {
                "w_gate": stack("model.layers.{}.mlp.gate_proj.weight"),
                "w_up": stack("model.layers.{}.mlp.up_proj.weight"),
                "w_down": stack("model.layers.{}.mlp.down_proj.weight"),
            }

        def stack_f32(pattern):
            return jnp.stack([
                get(pattern.format(i), cast_dtype=jnp.float32)
                for i in range(config.num_layers)
            ])

        if config.post_norms:
            # Gemma-2 sandwich layout: post_attention_layernorm is the
            # POST-attn norm, the feedforward pair wraps the MLP (same
            # mapping as model.load_hf_checkpoint)
            norms = {
                "attn_norm": stack_f32(
                    "model.layers.{}.input_layernorm.weight"
                ),
                "post_attn_norm": stack_f32(
                    "model.layers.{}.post_attention_layernorm.weight"
                ),
                "mlp_norm": stack_f32(
                    "model.layers.{}.pre_feedforward_layernorm.weight"
                ),
                "post_mlp_norm": stack_f32(
                    "model.layers.{}.post_feedforward_layernorm.weight"
                ),
            }
        else:
            norms = {
                "attn_norm": stack_f32(
                    "model.layers.{}.input_layernorm.weight"
                ),
                "mlp_norm": stack_f32(
                    "model.layers.{}.post_attention_layernorm.weight"
                ),
            }

        params = {
            "embedding": get("model.embed_tokens.weight"),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            **mlp_weights,
            **norms,
            "final_norm": get("model.norm.weight", cast_dtype=jnp.float32),
        }
        if config.qkv_bias:
            # Qwen-2 q/k/v projection biases
            params["bq"] = stack_f32("model.layers.{}.self_attn.q_proj.bias")
            params["bk"] = stack_f32("model.layers.{}.self_attn.k_proj.bias")
            params["bv"] = stack_f32("model.layers.{}.self_attn.v_proj.bias")
        if not config.tie_embeddings:
            params["lm_head"] = get("lm_head.weight", transpose=True)
        return config, params
    finally:
        store.close()
