"""Tokenizers for the jax-local provider.

- :class:`ByteTokenizer` — dependency-free byte-level tokenizer (vocab 259)
  used by tests and random-weight benchmarks.
- :class:`HFTokenizer` — wraps a local HuggingFace tokenizer (Llama-3 etc.),
  including its chat template.

Both expose the same minimal surface: ``encode``, ``decode``,
``apply_chat_template``, ``bos_id`` / ``eos_ids``, ``vocab_size`` and an
incremental :class:`StreamDecoder` that buffers partial UTF-8 so streamed
chunks never split a multibyte character.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence


class StreamDecoder:
    """Incremental detokenizer: feed token ids, get printable text deltas."""

    def __init__(self, tokenizer: "ByteTokenizer") -> None:
        self.tokenizer = tokenizer
        self._pending: List[int] = []
        self._emitted = 0
        self._all: List[int] = []

    def push(self, token_id: int) -> str:
        self._all.append(token_id)
        text = self.tokenizer.decode(self._all)
        # only emit the complete (non-replacement-suffix) prefix
        if text.endswith("�"):
            stripped = text.rstrip("�")
        else:
            stripped = text
        delta = stripped[self._emitted:]
        self._emitted = len(stripped)
        return delta

    def flush(self) -> str:
        text = self.tokenizer.decode(self._all)
        delta = text[self._emitted:]
        self._emitted = len(text)
        return delta


class ByteTokenizer:
    """Bytes + BOS/EOS/PAD specials. Token i<256 is byte i."""

    BOS = 256
    EOS = 257
    PAD = 258

    def __init__(self, config: Optional[Dict[str, Any]] = None) -> None:
        self.vocab_size = 259

    @property
    def bos_id(self) -> int:
        return self.BOS

    @property
    def eos_ids(self) -> List[int]:
        return [self.EOS]

    @property
    def pad_id(self) -> int:
        return self.PAD

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        tokens = list(text.encode("utf-8"))
        return ([self.BOS] + tokens) if add_bos else tokens

    def decode(self, tokens: Sequence[int]) -> str:
        data = bytes(t for t in tokens if t < 256)
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: List[Dict[str, str]]) -> List[int]:
        parts = []
        for message in messages:
            parts.append(f"<|{message['role']}|>\n{message['content']}\n")
        parts.append("<|assistant|>\n")
        return self.encode("".join(parts))

    def stream_decoder(self) -> StreamDecoder:
        return StreamDecoder(self)


class HFTokenizer:
    """Local HuggingFace tokenizer (no network: local_files_only)."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self._tk = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tk)

    @property
    def bos_id(self) -> int:
        return self._tk.bos_token_id

    @property
    def eos_ids(self) -> List[int]:
        ids = [self._tk.eos_token_id]
        # Llama-3 also stops on <|eot_id|>
        eot = self._tk.convert_tokens_to_ids("<|eot_id|>")
        if isinstance(eot, int) and eot >= 0 and eot != ids[0]:
            ids.append(eot)
        return [i for i in ids if i is not None]

    @property
    def pad_id(self) -> int:
        return self._tk.pad_token_id or 0

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tk.encode(text, add_special_tokens=add_bos)

    def decode(self, tokens: Sequence[int]) -> str:
        return self._tk.decode(tokens, skip_special_tokens=True)

    def apply_chat_template(self, messages: List[Dict[str, str]]) -> List[int]:
        return self._tk.apply_chat_template(messages, add_generation_prompt=True)

    def stream_decoder(self) -> StreamDecoder:
        return StreamDecoder(self)


def get_tokenizer(config: Optional[Dict[str, Any]]) -> Any:
    config = config or {}
    kind = config.get("type", "byte")
    if kind == "byte":
        return ByteTokenizer(config)
    if kind in ("huggingface", "hf"):
        return HFTokenizer(config["path"])
    raise ValueError(f"unknown tokenizer type {kind!r}")
