"""The ``jax-local`` ServiceProvider: completions + embeddings on the TPU.

Owns ``resources:`` entries of type ``jax-local``. Example:

.. code-block:: yaml

    configuration:
      resources:
        - type: "jax-local"
          name: "tpu-llm"
          configuration:
            model:
              preset: "llama-3-8b"        # or explicit dims
            checkpoint: "/models/llama-3-8b"   # HF dir; omit = random init
            tokenizer: {type: "hf", path: "/models/llama-3-8b"}
            mesh: {tp: 8}                  # jax.sharding axes
            engine: {max-slots: 16, max-seq-len: 4096}
            embeddings-model:
              preset: "minilm-l6"
              checkpoint: "/models/all-MiniLM-L6-v2"

One engine (and one embedder) is built per resource entry and shared by
every agent in the process (the runner loop batches into it). This is the
in-process replacement for the reference's HTTPS providers — the
ServiceProvider SPI surface is identical
(``services/ServiceProvider.java:24``).
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, Dict, List, Optional

from langstream_tpu.api.service import (
    ChatChunk,
    ChatCompletionResult,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    StreamingChunksConsumer,
)
from langstream_tpu.parallel.mesh import MeshConfig

logger = logging.getLogger(__name__)


class JaxCompletionsService(CompletionsService):
    def __init__(self, config: Dict[str, Any]) -> None:
        from langstream_tpu.providers.jax_local import model as model_lib
        from langstream_tpu.providers.jax_local.engine import DecodeEngine
        from langstream_tpu.providers.jax_local.tokenizer import get_tokenizer

        import os

        model_config = model_lib.LlamaConfig.from_dict(config.get("model", {"preset": "tiny"}))
        checkpoint = config.get("checkpoint")
        if checkpoint and any(
            f.endswith(".safetensors") or f == "model.safetensors.index.json"
            for f in (
                os.listdir(checkpoint) if os.path.isdir(checkpoint) else []
            )
        ):
            # direct safetensors load: one fp32 tensor transient at a time
            from langstream_tpu.providers.jax_local.weights import (
                load_safetensors_checkpoint,
            )

            model_config, params = load_safetensors_checkpoint(checkpoint)
            logger.info(
                "loaded safetensors %s (%d params)",
                checkpoint, model_config.num_params(),
            )
        elif checkpoint and os.path.isdir(checkpoint) and any(
            entry.isdigit() and os.path.isdir(os.path.join(checkpoint, entry))
            for entry in os.listdir(checkpoint)
        ):
            # orbax checkpoint (save_model export or Trainer save dir —
            # numeric step subdirs); load_model restores the latest step
            from langstream_tpu.training.checkpoint import load_model

            model_config, params = load_model(checkpoint)
            logger.info(
                "loaded orbax checkpoint %s (%d params)",
                checkpoint, model_config.num_params(),
            )
        elif checkpoint:
            model_config, params = model_lib.load_hf_checkpoint(checkpoint)
            logger.info("loaded checkpoint %s (%d params)", checkpoint, model_config.num_params())
        elif config.get("quantization") == "int8":
            # random weights + int8: init directly in int8 on device — an
            # 8B model inits in ~9 GB instead of peaking at 24 GB
            from langstream_tpu.providers.jax_local.quant import (
                init_quantized_params_cached,
            )

            params = init_quantized_params_cached(
                model_config, seed=int(config.get("seed", 0))
            )
            logger.warning(
                "jax-local: no checkpoint configured — RANDOM int8 weights "
                "(%.2fB params, benchmarking only)",
                model_config.num_params() / 1e9,
            )
        else:
            params = model_lib.init_params(model_config, seed=int(config.get("seed", 0)))
            logger.warning(
                "jax-local: no checkpoint configured — RANDOM weights "
                "(%.2fB params, benchmarking only)", model_config.num_params() / 1e9
            )
        self.tokenizer = get_tokenizer(config.get("tokenizer"))
        engine_config = config.get("engine", {}) or {}
        mesh_config = (
            MeshConfig.from_config(config.get("mesh")) if config.get("mesh") else None
        )
        buckets = engine_config.get("prefill-buckets")
        if isinstance(buckets, str):
            # allow "128" / "128,256" spellings from globals
            buckets = [
                int(b) for b in buckets.replace(",", " ").split()
            ] or None
        elif isinstance(buckets, int):
            buckets = [buckets]
        elif buckets:
            buckets = [int(b) for b in buckets]
        else:
            buckets = None
        if engine_config.get("sampling-seed") is not None:
            sampling_seed = int(engine_config["sampling-seed"])
        else:
            # real entropy by default: without it, every restart/replica
            # would hand unseeded requests the SAME auto-seed sequence,
            # making "random" sampling repeat across processes. Tests
            # constructing DecodeEngine directly keep the deterministic
            # seed=0 default.
            import secrets as _secrets

            sampling_seed = _secrets.randbits(32)
        engine_kwargs = dict(
            mesh_config=mesh_config,
            max_slots=int(engine_config.get("max-slots", 8)),
            # coerce like every other engine knob: placeholder defaults
            # (`${globals.x:-4096}`) arrive as STRINGS
            max_seq_len=(
                int(engine_config["max-seq-len"])
                if engine_config.get("max-seq-len") is not None
                else None
            ),
            prefill_buckets=buckets,
            decode_chunk=int(engine_config.get("decode-chunk", 8)),
            admission_chunk=(
                int(engine_config["admission-chunk"])
                if engine_config.get("admission-chunk")
                else None
            ),
            seed=sampling_seed,
            quantize=config.get("quantization"),
            kv_quant=engine_config.get("kv-quant") or None,
            # paged KV cache + persistent prefix-block pool (dense stays
            # the default); placeholder defaults arrive as STRINGS like
            # every other engine knob
            kv_layout=str(
                engine_config.get("kv-layout") or "dense"
            ).lower(),
            kv_block_size=int(engine_config.get("kv-block-size") or 16),
            kv_blocks=(
                int(engine_config["kv-blocks"])
                if engine_config.get("kv-blocks")
                else None
            ),
            # host-DRAM demotion tier capacity (0 = HBM-only pool):
            # evicted chains demote to a pinned host arena and promote
            # back on a digest hit instead of recomputing
            kv_host_blocks=int(engine_config.get("kv-host-blocks") or 0),
            # paged attention kernel: fused ragged Pallas launch over
            # the block tables (default) vs the gather/scatter reference
            # oracle — the ROADMAP-item-1 A/B knob
            paged_kernel=str(
                engine_config.get("paged-kernel") or "fused"
            ).lower(),
            # speculative decoding (ROADMAP item 2): off (oracle scan,
            # default) | ngram (self-drafting prompt-lookup, spec-k
            # drafts verified per step) — threaded exactly like
            # paged-kernel so serve/bench/globals all speak one knob
            spec_decode=str(
                engine_config.get("spec-decode") or "off"
            ).lower(),
            spec_k=int(engine_config.get("spec-k") or 4),
            spec_ngram=int(engine_config.get("spec-ngram") or 2),
            # mixed prefill+decode dispatch (paged only): chunked
            # prefill windows fused into the decode step — the
            # tail-TPOT A/B knob, threaded exactly like paged-kernel
            prefill_mode=str(
                engine_config.get("prefill-mode") or "split"
            ).lower(),
            prefill_chunk=int(engine_config.get("prefill-chunk") or 64),
            # mixed-step carry: pipeline consecutive mixed steps off the
            # previous step's device-resident outputs (on by default —
            # bitwise-neutral; the A/B knob isolates its contribution)
            mixed_carry=str(
                engine_config.get("mixed-carry", "on")
            ).lower() not in ("0", "false", "no", "off"),
            pipeline_decode=str(
                engine_config.get("pipeline-decode", "")
            ).lower() in ("1", "true", "yes"),
            prefix_cache=str(
                engine_config.get("prefix-cache", "true")
            ).lower() not in ("0", "false", "no"),
            # OpenAI `top_logprobs`: static K per engine (shapes the jit
            # outputs); requests may ask for any n <= K
            logprobs_topk=int(engine_config.get("logprobs-top-k", 0) or 0),
            # SLO targets (`slo: {ttft-ms-p95: 200, tpot-ms-p95: 30}`):
            # feed the multi-window burn-rate gauges on every /metrics
            # surface and the `top` SLO panel
            slo=(
                {
                    str(k).replace("-", "_"): float(v)
                    for k, v in (config.get("slo") or {}).items()
                    if v
                }
                or None
            ),
            # admission deadline (serve --queue-timeout-s): pending
            # requests older than this shed with a typed 503 instead of
            # starving in the engine queue
            queue_timeout_s=(
                float(engine_config["queue-timeout-s"])
                if engine_config.get("queue-timeout-s")
                else None
            ),
        )
        precompile = str(engine_config.get("precompile", "")).lower() in (
            "1", "true", "yes",
        )

        def build_engine() -> DecodeEngine:
            # the supervisor's rebuild path runs this exact closure:
            # config + ALREADY-LOADED weights are captured, so healing
            # never reloads a checkpoint, and precompiled variants come
            # back through the persistent XLA compile cache
            engine = DecodeEngine(model_config, params, **engine_kwargs)
            if precompile:
                # compile every prefill/decode variant before the first
                # request so no jit compile ever stalls live traffic
                engine.precompile()
            return engine

        # decode-stall watchdog: opt-in (`serve` turns it on; pods via
        # engine config or LANGSTREAM_WATCHDOG=1) — a degraded/wedged
        # engine flushes flight evidence and bumps watchdog_trips_total
        # instead of waiting for a human to notice
        self.watchdog = None
        watchdog_flag = str(
            engine_config.get(
                "watchdog", os.environ.get("LANGSTREAM_WATCHDOG", "")
            )
        ).lower()
        watchdog_on = watchdog_flag in ("1", "true", "yes", "on")

        def build_watchdog(engine: DecodeEngine):
            from langstream_tpu.runtime.watchdog import EngineWatchdog

            return EngineWatchdog(engine)

        # engine supervisor (self-healing serving): on by default — a
        # crashed device thread snapshots every live session, rebuilds
        # the engine, and resumes each stream bitwise instead of mass-
        # 500ing. Opt out via engine config `supervisor: false`,
        # LANGSTREAM_SUPERVISOR=0, or `serve --no-supervisor` (the
        # multi-host mirror path disables it — a rebuilt leader cannot
        # resynchronize followers yet).
        self._supervisor = None
        self._engine: Optional[DecodeEngine] = None
        supervised = str(
            engine_config.get(
                "supervisor", os.environ.get("LANGSTREAM_SUPERVISOR", "1")
            )
        ).lower() not in ("0", "false", "no", "off")
        if supervised:
            from langstream_tpu.runtime.supervisor import EngineSupervisor

            self._supervisor = EngineSupervisor(
                build_engine,
                max_restarts=int(engine_config.get("max-restarts") or 3),
                restart_window_s=float(
                    engine_config.get("restart-window-s") or 600.0
                ),
                watchdog_factory=build_watchdog if watchdog_on else None,
            )
            self.watchdog = self._supervisor.watchdog
        else:
            self._engine = build_engine()
            self._engine.start()
            if watchdog_on:
                self.watchdog = build_watchdog(self._engine)
                self.watchdog.start()
        self.top_logprobs_limit = self.engine.logprobs_topk

    @property
    def engine(self):
        """The CURRENT engine: the supervisor swaps it on a rebuild, so
        everything downstream (metrics callbacks, the serve wiring, the
        mirror hookup) must read through this property rather than
        caching the instance."""
        if self._supervisor is not None:
            return self._supervisor.engine
        return self._engine

    def available(self) -> Optional[float]:
        """None when accepting work; otherwise the seconds a caller
        should wait (degraded mode: the supervisor is rebuilding a
        crashed engine). The OpenAI surface turns this into
        503 + Retry-After before burning any tokenization work."""
        supervisor = self._supervisor
        if supervisor is not None and supervisor.state == "rebuilding":
            # (a supervisor past its restart budget is "failed", which
            # is terminal — those requests should 500, not retry)
            return supervisor.retry_after()
        return None

    async def get_chat_completions(
        self,
        messages: List[ChatMessage],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        prompt_tokens = self.tokenizer.apply_chat_template(
            [{"role": m.role, "content": m.content} for m in messages]
        )
        return await self._generate(prompt_tokens, options, stream_consumer)

    async def get_text_completions(
        self,
        prompt: List[str],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        """Legacy text completions CONTINUE the prompt verbatim — no chat
        template (OpenAI /v1/completions semantics)."""
        prompt_tokens = self.tokenizer.encode("".join(prompt))
        return await self._generate(prompt_tokens, options, stream_consumer)

    async def _generate(
        self,
        prompt_tokens: List[int],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        from langstream_tpu.providers.jax_local.engine import SamplingParams

        wait = self.available()
        if wait is not None:
            # degraded mode: the supervisor is mid-rebuild — bounce NEW
            # work with a typed retryable error (503 + Retry-After on
            # the HTTP surfaces) before spending any engine work; the
            # engine's own submit() backstops the race
            from langstream_tpu.api import errors as api_errors

            raise api_errors.EngineRebuildingError(
                "engine is rebuilding after a crash; retry shortly",
                retry_after_s=wait,
            )
        sampling = SamplingParams(
            temperature=float(options.get("temperature") or 0.0),
            top_k=int(options.get("top-k") or 0),
            top_p=float(options.get("top-p") or 0.0),
            max_new_tokens=int(options.get("max-tokens") or 256),
            presence_penalty=float(options.get("presence-penalty") or 0.0),
            frequency_penalty=float(options.get("frequency-penalty") or 0.0),
            seed=(
                int(options["seed"]) if options.get("seed") is not None
                else None
            ),
            logit_bias=(
                {int(k): float(v) for k, v in options["logit-bias"].items()}
                if options.get("logit-bias") else None
            ),
        )
        session_id = options.get("session-id")
        # OpenAI-style stop STRINGS (`stop:` agent config): generation is
        # cancelled at the next token boundary once one appears in the
        # decoded text, and the result is trimmed at the match
        # (reference: ChatCompletionsConfig stop list)
        stop = options.get("stop") or []
        if isinstance(stop, str):
            stop_strings = [stop]
        elif isinstance(stop, (list, tuple)):
            # coerce entries: YAML users write bare numbers/bools too
            stop_strings = [str(s) for s in stop if s is not None and s != ""]
        else:
            stop_strings = [str(stop)]
        handle: list = []
        released_parts: list = []
        retained = [""]
        stop_cut: list = []
        holdback = max((len(s) for s in stop_strings), default=1) - 1

        def watch_stop(delta: str, final: bool = False) -> str:
            """Watch the streamed text; on a stop match, cancel the
            request and release only the text BEFORE the match. Withholds
            the last ``len(longest stop) - 1`` chars until cleared so a
            stop string split across two deltas never partially leaks
            into the stream (released at ``final`` if no match). Only the
            retained tail + the new delta are ever scanned — matches
            wholly inside the retained window were ruled out last round
            — so the per-token cost is O(delta), not O(answer)."""
            if not stop_strings:
                return delta
            if stop_cut:
                return ""
            window = retained[0] + delta
            hits = [
                position for position in
                (window.find(s) for s in stop_strings)
                if position != -1
            ]
            if hits:
                release = window[: min(hits)]
                retained[0] = ""
                stop_cut.append(True)
                if handle:
                    handle[0].cancel()
            elif final:
                release = window
                retained[0] = ""
            else:
                keep = min(holdback, len(window))
                release = window[: len(window) - keep]
                retained[0] = window[len(window) - keep:]
            if release:
                released_parts.append(release)
            return release

        answer_id = uuid.uuid4().hex
        on_token = None
        decoder = None
        index_box = [0]
        last_sent = [False]
        if stream_consumer is not None:
            decoder = self.tokenizer.stream_decoder()

            def on_token(token_id: int, is_last: bool) -> None:
                text = decoder.push(token_id)
                if is_last:
                    # deliver any bytes the decoder was withholding as a
                    # possible partial UTF-8 sequence — last chance
                    text += decoder.flush()
                text = watch_stop(text, final=is_last)
                if text or is_last:
                    index = index_box[0]
                    index_box[0] += 1
                    if is_last:
                        last_sent[0] = True
                    stream_consumer.consume_chunk(
                        answer_id, index,
                        ChatChunk(content=text, index=index),
                        last=is_last,
                    )

        elif stop_strings:
            # no streaming: still watch the decoded text so long answers
            # cancel at the stop instead of decoding to max-tokens
            non_stream_decoder = self.tokenizer.stream_decoder()

            def on_token(token_id: int, is_last: bool) -> None:
                watch_stop(non_stream_decoder.push(token_id))

        result = await self.engine.generate(
            prompt_tokens,
            sampling,
            stop_tokens=set(self.tokenizer.eos_ids),
            on_token=on_token,
            session_id=session_id,
            handle=handle,
            trace_id=(
                str(options["trace-id"]) if options.get("trace-id") else None
            ),
        )
        if stop_cut:
            # the stream watcher found the stop: the final content IS the
            # released stream (a batch re-decode can place multi-byte
            # replacement boundaries differently than the incremental
            # decoder, so re-finding the stop there could disagree)
            text = "".join(released_parts)
        else:
            text = self.tokenizer.decode(result.tokens)
        stop_trimmed = False
        if stop_strings and not stop_cut:
            for s in stop_strings:
                cut = text.find(s)
                if cut != -1:
                    text = text[:cut]
                    stop_trimmed = True
        kept_tokens = result.tokens
        kept_logprobs = result.logprobs
        kept_tops = result.top_logprobs
        if stop_cut or stop_trimmed:
            # drop the tokens past the stop so per-token data (logprobs,
            # completion_tokens) aligns with the trimmed content — the
            # engine decodes a few chunk-boundary tokens past the match
            # before the cancel lands
            walker = self.tokenizer.stream_decoder()
            length = 0
            kept = 0
            for token in result.tokens:
                length += len(walker.push(token))
                if length > len(text):
                    break
                kept += 1
            kept_tokens = result.tokens[:kept]
            kept_logprobs = result.logprobs[:kept]
            if kept_tops is not None:
                kept_tops = kept_tops[:kept]
        if stream_consumer is not None and not last_sent[0]:
            # terminal marker for chunk batchers when the stop token arrived
            # without a trailing streamed delta (on_token is not called for
            # stop tokens, so no last=True was emitted yet)
            tail = watch_stop(decoder.flush(), final=True)
            stream_consumer.consume_chunk(
                answer_id, index_box[0],
                ChatChunk(content=tail, index=index_box[0]),
                last=True,
            )
        want_logprobs = bool(options.get("logprobs"))
        finish_reason = result.finish_reason
        if stop_cut or stop_trimmed:
            finish_reason = "stop"  # a stop STRING ended the answer
        return ChatCompletionResult(
            content=text,
            finish_reason=finish_reason,
            prompt_tokens=result.prompt_tokens,
            completion_tokens=len(kept_tokens),
            # per-token decode only when the caller asked for logprobs —
            # N tokenizer round-trips are pure waste on the common path
            tokens=(
                [self.tokenizer.decode([t]) for t in kept_tokens]
                if want_logprobs else None
            ),
            logprobs=list(kept_logprobs) if want_logprobs else None,
            # K × tokens single-token decodes: only when the request
            # actually asked for alternatives (top-logprobs > 0), not
            # for every logprobs:true call on an enabled engine
            top_logprobs=(
                [
                    [
                        (self.tokenizer.decode([int(tid)]), float(tlp))
                        for tid, tlp in zip(ids, lps)
                    ]
                    for ids, lps in kept_tops
                ]
                if want_logprobs and kept_tops is not None
                and int(options.get("top-logprobs") or 0) > 0
                else None
            ),
        )

    async def close(self) -> None:
        if self._supervisor is not None:
            self._supervisor.stop()  # owns its watchdog + engine
            return
        if self.watchdog is not None:
            self.watchdog.stop()
        self.engine.stop()


class JaxEmbeddingsService(EmbeddingsService):
    def __init__(self, config: Dict[str, Any], model: Optional[str]) -> None:
        from langstream_tpu.providers.jax_local.embeddings import (
            EncoderConfig,
            JaxEmbedder,
            init_encoder_params,
            load_hf_bert,
        )
        from langstream_tpu.providers.jax_local.tokenizer import get_tokenizer

        embeddings_config = config.get("embeddings-model", {}) or {}
        checkpoint = embeddings_config.get("checkpoint") or (
            model if model and "/" in str(model) else None
        )
        if checkpoint:
            encoder_config, params = load_hf_bert(checkpoint)
            from langstream_tpu.providers.jax_local.tokenizer import HFTokenizer

            tokenizer = HFTokenizer(checkpoint)
        else:
            encoder_config = EncoderConfig.from_dict(
                embeddings_config if embeddings_config else {"preset": "tiny"}
            )
            params = init_encoder_params(encoder_config)
            tokenizer = get_tokenizer(config.get("tokenizer"))
            if not embeddings_config:
                logger.warning(
                    "jax-local embeddings: no checkpoint — random tiny encoder"
                )
        self.embedder = JaxEmbedder(
            encoder_config, params, tokenizer,
            max_length=int(embeddings_config.get("max-length", 256)),
        )

    async def compute_embeddings(self, texts: List[str]) -> List[List[float]]:
        # run the device call off the event loop
        return await asyncio.get_running_loop().run_in_executor(
            None, self.embedder.embed, texts
        )


class JaxLocalServiceProvider(ServiceProvider):
    """Service instances are cached per resource entry by
    :class:`~langstream_tpu.providers.registry.ServiceProviderRegistry`,
    which is what guarantees one engine per resource."""

    name = "jax-local"

    def supports(self, resource_config: Dict[str, Any]) -> bool:
        return (
            resource_config.get("type") in ("jax-local", "jax")
            or "jax-local" in resource_config
        )

    def get_completions_service(self, resource_config: Dict[str, Any]) -> CompletionsService:
        return JaxCompletionsService(resource_config)

    def get_embeddings_service(
        self, resource_config: Dict[str, Any], model: Optional[str] = None
    ) -> EmbeddingsService:
        return JaxEmbeddingsService(resource_config, model)
