"""Llama-family decoder in functional JAX (stacked layers, lax.scan).

Pure-pytree formulation (no flax module state): parameters are a dict of
stacked per-layer arrays so the layer loop is one ``lax.scan`` — one
compilation for 8 or 80 layers, and the scan carry keeps activations in
registers/VMEM instead of re-reading HBM per layer.

Architecture: pre-norm transformer with RMSNorm, RoPE, GQA attention, and
SwiGLU MLP — Llama 2/3 family (config covers TinyLlama through 70B).
Weights import from a local HuggingFace checkpoint (torch state dict →
stacked jax arrays), or random-init for benchmarks.

Logical sharding axes per parameter feed the mesh rules in
``langstream_tpu.parallel.mesh`` (tp shards heads/mlp, fsdp shards embed).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from langstream_tpu.ops.attention import (
    chunk_attention,
    chunk_attention_quant,
    decode_attention,
    decode_attention_quant,
    paged_chunk_attention,
    paged_chunk_attention_quant,
    paged_decode_attention,
    paged_decode_attention_quant,
    paged_write_rows,
    prefill_attention,
    quantize_kv,
)
from langstream_tpu.ops.flash_attention import flash_prefill_attention, use_flash
from langstream_tpu.ops.norms import rms_norm
from langstream_tpu.ops.rope import apply_rope, rope_frequencies
from langstream_tpu.parallel.mesh import L
from langstream_tpu.providers.jax_local.quant import qeinsum


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_layers: int = 22
    num_heads: int = 32
    num_kv_heads: int = 4
    head_dim: Optional[int] = None
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 4096
    tie_embeddings: bool = False
    # Mixture-of-experts (Mixtral family). 0 = dense SwiGLU MLP.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    capacity_factor: float = 2.0
    # Gemma-2 family extensions — every default is the Llama behavior.
    attn_logit_softcap: Optional[float] = None   # cap·tanh(s/cap) on scores
    final_logit_softcap: Optional[float] = None  # same on output logits
    query_pre_attn_scalar: Optional[float] = None  # attn scale base (None → head_dim)
    sliding_window: int = 0       # 0 = full attention on every layer;
                                  # >0 = Gemma-2 alternating pattern
                                  # (even layers slide, odd layers full)
    norm_plus_one: bool = False   # RMSNorm applies (1 + w) (zero-centered w)
    post_norms: bool = False      # sandwich norms after attn + mlp blocks
    scale_embedding: bool = False  # x *= sqrt(hidden) after the lookup
    act: str = "silu"             # MLP gate activation: silu | gelu_tanh
    qkv_bias: bool = False        # q/k/v projection biases (Qwen-2 family)
    # RoPE frequency scaling as a HASHABLE tuple ("llama3", factor,
    # low_freq_factor, high_freq_factor, original_max_positions) — the
    # Llama-3.1/3.2 long-context recipe (ops/rope.py). None = plain.
    rope_scaling: Optional[Tuple] = None
    dtype: Any = jnp.bfloat16
    # Pallas flash prefill (TPU only; tp-sharded meshes route it through
    # shard_map over the head axis — see _prefill_attn).
    use_flash: bool = True
    # test hook: force the kernel in Pallas interpret mode (CPU parity
    # tests of the flash path; never set in production configs)
    flash_interpret: bool = False

    @property
    def dims_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @classmethod
    def llama3_8b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        return cls(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8,
            rope_theta=500000.0, max_seq_len=max_seq_len,
        )

    @classmethod
    def llama3_70b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        return cls(
            vocab_size=128256, hidden_size=8192, intermediate_size=28672,
            num_layers=80, num_heads=64, num_kv_heads=8,
            rope_theta=500000.0, max_seq_len=max_seq_len,
        )

    @classmethod
    def llama3_1b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        # Llama-3.2-1B shape (incl. its 32x llama3 rope scaling)
        return cls(
            vocab_size=128256, hidden_size=2048, intermediate_size=8192,
            num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
            rope_theta=500000.0, max_seq_len=max_seq_len, tie_embeddings=True,
            rope_scaling=("llama3", 32.0, 1.0, 4.0, 8192.0),
        )

    @classmethod
    def llama31_8b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        """Llama-3.1-8B: the 3.0 shape + llama3 rope scaling (the
        128k-context recipe)."""
        return dataclasses.replace(
            cls.llama3_8b(max_seq_len),
            rope_scaling=("llama3", 8.0, 1.0, 4.0, 8192.0),
        )

    @classmethod
    def mixtral_8x7b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        return cls(
            vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8,
            rope_theta=1e6, max_seq_len=max_seq_len,
            num_experts=8, num_experts_per_tok=2,
        )

    @classmethod
    def gemma2_2b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        """Gemma-2-2B (HF google/gemma-2-2b): GeGLU, sandwich norms,
        zero-centered RMSNorm, logit softcapping, alternating sliding
        window, scaled embeddings, tied head."""
        return cls(
            vocab_size=256000, hidden_size=2304, intermediate_size=9216,
            num_layers=26, num_heads=8, num_kv_heads=4, head_dim=256,
            rope_theta=10000.0, max_seq_len=max_seq_len, norm_eps=1e-6,
            tie_embeddings=True, attn_logit_softcap=50.0,
            final_logit_softcap=30.0, query_pre_attn_scalar=256.0,
            sliding_window=4096, norm_plus_one=True, post_norms=True,
            scale_embedding=True, act="gelu_tanh",
        )

    @classmethod
    def gemma2_9b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        return dataclasses.replace(
            cls.gemma2_2b(max_seq_len), hidden_size=3584,
            intermediate_size=14336, num_layers=42, num_heads=16,
            num_kv_heads=8, head_dim=256,
        )

    @classmethod
    def tiny_gemma2(cls, max_seq_len: int = 256) -> "LlamaConfig":
        """Test-size Gemma-2 shape: every family mechanism on, window
        smaller than typical test prompts so sliding layers actually
        mask."""
        return cls(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
            rope_theta=10000.0, max_seq_len=max_seq_len, norm_eps=1e-6,
            tie_embeddings=True, attn_logit_softcap=50.0,
            final_logit_softcap=30.0, query_pre_attn_scalar=16.0,
            sliding_window=8, norm_plus_one=True, post_norms=True,
            scale_embedding=True, act="gelu_tanh", dtype=jnp.float32,
        )

    @classmethod
    def qwen25_7b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        """Qwen-2.5-7B (HF Qwen/Qwen2.5-7B): Llama architecture plus
        q/k/v projection biases."""
        return cls(
            vocab_size=152064, hidden_size=3584, intermediate_size=18944,
            num_layers=28, num_heads=28, num_kv_heads=4, head_dim=128,
            rope_theta=1e6, max_seq_len=max_seq_len, norm_eps=1e-6,
            qkv_bias=True,
        )

    @classmethod
    def qwen25_0_5b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        return cls(
            vocab_size=151936, hidden_size=896, intermediate_size=4864,
            num_layers=24, num_heads=14, num_kv_heads=2, head_dim=64,
            rope_theta=1e6, max_seq_len=max_seq_len, norm_eps=1e-6,
            qkv_bias=True, tie_embeddings=True,
        )

    @classmethod
    def tiny_qwen2(cls, max_seq_len: int = 256) -> "LlamaConfig":
        """Test-size Qwen-2 shape (qkv biases on)."""
        return dataclasses.replace(cls.tiny(max_seq_len), qkv_bias=True)

    @classmethod
    def tiny(cls, max_seq_len: int = 256) -> "LlamaConfig":
        """Test-size config for CPU runs."""
        return cls(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2,
            max_seq_len=max_seq_len, dtype=jnp.float32,
        )

    @classmethod
    def tiny_moe(cls, max_seq_len: int = 256) -> "LlamaConfig":
        """Test-size MoE config for CPU runs."""
        return dataclasses.replace(
            cls.tiny(max_seq_len), num_experts=4, num_experts_per_tok=2
        )

    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "LlamaConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        clean = {k.replace("-", "_"): v for k, v in config.items()}
        if isinstance(clean.get("dtype"), str):
            # checkpoints serialize the dtype by name ("bfloat16")
            clean["dtype"] = jnp.dtype(clean["dtype"])
        if clean.get("rope_scaling") is not None:
            clean["rope_scaling"] = normalize_rope_scaling(
                clean["rope_scaling"]
            )
        presets = {
            "llama-3-8b": cls.llama3_8b, "llama-3-70b": cls.llama3_70b,
            "llama-3.1-8b": cls.llama31_8b,
            "llama-3-1b": cls.llama3_1b, "tiny": cls.tiny,
            "mixtral-8x7b": cls.mixtral_8x7b, "tiny-moe": cls.tiny_moe,
            "gemma-2-2b": cls.gemma2_2b, "gemma-2-9b": cls.gemma2_9b,
            "tiny-gemma2": cls.tiny_gemma2,
            "qwen-2.5-7b": cls.qwen25_7b, "qwen-2.5-0.5b": cls.qwen25_0_5b,
            "tiny-qwen2": cls.tiny_qwen2,
        }
        preset = clean.pop("preset", None)
        if preset:
            base = presets[preset]()
            return dataclasses.replace(
                base, **{k: v for k, v in clean.items() if k in known}
            )
        return cls(**{k: v for k, v in clean.items() if k in known})

    def num_params(self) -> int:
        head_dim = self.dims_per_head
        attn = self.hidden_size * head_dim * (2 * self.num_heads + 2 * self.num_kv_heads)
        mlp = 3 * self.hidden_size * self.intermediate_size
        if self.num_experts:
            mlp = mlp * self.num_experts + self.hidden_size * self.num_experts
        per_layer = attn + mlp + 2 * self.hidden_size
        emb = self.vocab_size * self.hidden_size * (1 if self.tie_embeddings else 2)
        return self.num_layers * per_layer + emb + self.hidden_size


def init_params(config: LlamaConfig, seed: int = 0) -> Dict[str, jnp.ndarray]:
    """Random-init (scaled normal) parameter pytree with stacked layers."""
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 10)
    h, f, v = config.hidden_size, config.intermediate_size, config.vocab_size
    nh, nkv, hd = config.num_heads, config.num_kv_heads, config.dims_per_head
    layers = config.num_layers
    dtype = config.dtype

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(dtype)

    scale = 1.0 / math.sqrt(h)
    if config.num_experts:
        e = config.num_experts
        mlp_params = {
            "w_gate": normal(keys[5], (layers, e, h, f), scale),
            "w_up": normal(keys[6], (layers, e, h, f), scale),
            "w_down": normal(keys[7], (layers, e, f, h), scale / math.sqrt(2 * layers)),
            "router": normal(keys[9], (layers, h, e), scale),
        }
    else:
        mlp_params = {
            "w_gate": normal(keys[5], (layers, h, f), scale),
            "w_up": normal(keys[6], (layers, h, f), scale),
            "w_down": normal(keys[7], (layers, f, h), scale / math.sqrt(2 * layers)),
        }
    # zero-centered convention (norm applies 1 + w): identity weight is 0
    norm_fill = 0.0 if config.norm_plus_one else 1.0

    def norm_init(shape):
        return jnp.full(shape, norm_fill, dtype=jnp.float32)

    params = {
        "embedding": normal(keys[0], (v, h), 1.0 / math.sqrt(h)),
        "wq": normal(keys[1], (layers, h, nh * hd), scale),
        "wk": normal(keys[2], (layers, h, nkv * hd), scale),
        "wv": normal(keys[3], (layers, h, nkv * hd), scale),
        "wo": normal(keys[4], (layers, nh * hd, h), scale / math.sqrt(2 * layers)),
        **mlp_params,
        "attn_norm": norm_init((layers, h)),
        "mlp_norm": norm_init((layers, h)),
        "final_norm": norm_init((h,)),
    }
    if config.post_norms:
        params["post_attn_norm"] = norm_init((layers, h))
        params["post_mlp_norm"] = norm_init((layers, h))
    if config.qkv_bias:
        params["bq"] = jnp.zeros((layers, nh * hd), dtype=jnp.float32)
        params["bk"] = jnp.zeros((layers, nkv * hd), dtype=jnp.float32)
        params["bv"] = jnp.zeros((layers, nkv * hd), dtype=jnp.float32)
    if not config.tie_embeddings:
        params["lm_head"] = normal(keys[8], (h, v), scale)
    return params


def logical_axes(config: LlamaConfig) -> Dict[str, Any]:
    """Logical sharding axes per parameter (fed to parallel.mesh rules)."""
    if config.num_experts:
        mlp_axes = {
            "w_gate": L("layers", "expert", "embed", "mlp"),
            "w_up": L("layers", "expert", "embed", "mlp"),
            "w_down": L("layers", "expert", "mlp", "embed"),
            "router": L("layers", "embed", None),
        }
    else:
        mlp_axes = {
            "w_gate": L("layers", "embed", "mlp"),
            "w_up": L("layers", "embed", "mlp"),
            "w_down": L("layers", "mlp", "embed"),
        }
    axes = {
        "embedding": L("vocab", "embed"),
        "wq": L("layers", "embed", "heads"),
        "wk": L("layers", "embed", "heads"),
        "wv": L("layers", "embed", "heads"),
        "wo": L("layers", "heads", "embed"),
        **mlp_axes,
        "attn_norm": L("layers", None),
        "mlp_norm": L("layers", None),
        "final_norm": L(None),
    }
    if config.post_norms:
        axes["post_attn_norm"] = L("layers", None)
        axes["post_mlp_norm"] = L("layers", None)
    if config.qkv_bias:
        axes["bq"] = L("layers", "heads")
        axes["bk"] = L("layers", "heads")
        axes["bv"] = L("layers", "heads")
    if not config.tie_embeddings:
        axes["lm_head"] = L("embed", "vocab")
    return axes


def init_cache(
    config: LlamaConfig,
    batch: int,
    max_len: Optional[int] = None,
    kv_quant: bool = False,
) -> Dict[str, jnp.ndarray]:
    """KV cache: [layers, batch, max_len, kv_heads, head_dim].

    ``kv_quant`` stores int8 values plus per-(position, kv-head) f32
    scales — halves the cache's HBM bytes on the weights+cache-bound
    decode path (scales are 1/32 of the int8 bytes at head_dim 128).
    The forward paths detect quantization by the ``k_scale`` key."""
    max_len = max_len or config.max_seq_len
    shape = (config.num_layers, batch, max_len, config.num_kv_heads, config.dims_per_head)
    if kv_quant:
        return {
            "k": jnp.zeros(shape, dtype=jnp.int8),
            "v": jnp.zeros(shape, dtype=jnp.int8),
            "k_scale": jnp.zeros(shape[:-1], dtype=jnp.float32),
            "v_scale": jnp.zeros(shape[:-1], dtype=jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, dtype=config.dtype),
        "v": jnp.zeros(shape, dtype=config.dtype),
    }


def cache_logical_axes(kv_quant: bool = False) -> Dict[str, Any]:
    axes: Dict[str, Any] = {
        "k": L("layers", "cache_batch", "cache_sequence", "kv_heads", None),
        "v": L("layers", "cache_batch", "cache_sequence", "kv_heads", None),
    }
    if kv_quant:
        axes["k_scale"] = L(
            "layers", "cache_batch", "cache_sequence", "kv_heads"
        )
        axes["v_scale"] = L(
            "layers", "cache_batch", "cache_sequence", "kv_heads"
        )
    return axes


def init_paged_cache(
    config: LlamaConfig,
    num_blocks: int,
    block_size: int,
    kv_quant: bool = False,
) -> Dict[str, jnp.ndarray]:
    """Paged KV cache (``kv_layout: paged``): a GLOBAL block pool
    [layers, num_blocks, block_size, kv_heads, head_dim] shared by every
    slot, addressed through per-slot block tables. Unlike
    :func:`init_cache` there is no per-slot max_len region — HBM scales
    with the tokens actually resident, short requests release their
    blocks early, and published prefix chains survive slot turnover
    (engine/paged.py owns the block accounting). Block 0 is the null
    block (padding / masked writes; never read live).

    ``kv_quant`` mirrors the dense layout: int8 values plus
    per-(block, position, kv-head) f32 scales."""
    shape = (
        config.num_layers, num_blocks, block_size,
        config.num_kv_heads, config.dims_per_head,
    )
    if kv_quant:
        return {
            "k": jnp.zeros(shape, dtype=jnp.int8),
            "v": jnp.zeros(shape, dtype=jnp.int8),
            "k_scale": jnp.zeros(shape[:-1], dtype=jnp.float32),
            "v_scale": jnp.zeros(shape[:-1], dtype=jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, dtype=config.dtype),
        "v": jnp.zeros(shape, dtype=config.dtype),
    }


def paged_cache_logical_axes(kv_quant: bool = False) -> Dict[str, Any]:
    """Pool blocks are never sharded (any block may serve any request);
    kv_heads shard under tp like the dense cache."""
    axes: Dict[str, Any] = {
        "k": L("layers", None, None, "kv_heads", None),
        "v": L("layers", None, None, "kv_heads", None),
    }
    if kv_quant:
        axes["k_scale"] = L("layers", None, None, "kv_heads")
        axes["v_scale"] = L("layers", None, None, "kv_heads")
    return axes


def normalize_rope_scaling(value: Any) -> Optional[Tuple]:
    """HF configs carry rope scaling as a dict; the config field is a
    hashable tuple ("llama3", factor, low, high, original_max). Accepts
    either spelling; only the llama3 (3.1/3.2 long-context) type is
    supported — anything else raises rather than silently degrading."""
    if value is None or isinstance(value, tuple):
        return value
    if isinstance(value, (list,)):
        return tuple(value)
    # YAML configs spell keys with dashes; HF JSON with underscores
    value = {k.replace("-", "_"): v for k, v in value.items()}
    kind = value.get("rope_type") or value.get("type")
    if kind == "default":
        return None
    if kind != "llama3":
        raise ValueError(f"unsupported rope scaling type: {kind!r}")
    # all four parameters are REQUIRED (as in HF's validation): assumed
    # defaults would silently build wrong long-context RoPE angles
    missing = [
        key
        for key in (
            "factor", "low_freq_factor", "high_freq_factor",
            "original_max_position_embeddings",
        )
        if key not in value
    ]
    if missing:
        raise ValueError(f"llama3 rope_scaling missing {missing}")
    return (
        "llama3",
        float(value["factor"]),
        float(value["low_freq_factor"]),
        float(value["high_freq_factor"]),
        float(value["original_max_position_embeddings"]),
    )


def model_freqs(config: LlamaConfig, dtype=jnp.float32) -> jnp.ndarray:
    """The ONE way to build this config's RoPE table — theta AND the
    rope-scaling recipe (engine, trainer, forward, and the graft entry
    all route through here so a scaled checkpoint can never silently
    get plain frequencies)."""
    return rope_frequencies(
        config.dims_per_head, config.max_seq_len, config.rope_theta,
        dtype=dtype, scaling=config.rope_scaling,
    )


def validate_family_params(
    config: LlamaConfig, params: Dict[str, Any]
) -> None:
    """Fail fast when a checkpoint/loader dropped family-specific
    tensors: the layer stack's None fallbacks (post norms, qkv biases)
    would otherwise run a qkv_bias/post_norms config silently WITHOUT
    them — wrong logits, no error."""
    required = []
    if config.qkv_bias:
        required += ["bq", "bk", "bv"]
    if config.post_norms:
        required += ["post_attn_norm", "post_mlp_norm"]
    if not config.tie_embeddings:
        required += ["lm_head"]
    if config.num_experts:
        required += ["router"]
    missing = [name for name in required if name not in params]
    if missing:
        raise ValueError(
            f"params missing {missing}, required by the model config — "
            "the checkpoint or loader dropped family-specific tensors"
        )


def _stack_layer_params(params: Dict[str, jnp.ndarray], config=None):
    """Stacked per-layer tuple for the lax.scan layer loop. Post norms
    (Gemma-2 sandwich) and qkv biases (Qwen-2) are None for families
    without them — None is an empty pytree, so scan passes it through
    untouched. With ``config`` given, validates the family tensors are
    actually present first (see :func:`validate_family_params`)."""
    if config is not None:
        validate_family_params(config, params)
    mlp = (params["w_gate"], params["w_up"], params["w_down"])
    if "router" in params:
        mlp = mlp + (params["router"],)
    biases = (
        (params["bq"], params["bk"], params["bv"])
        if "bq" in params else None
    )
    return (
        params["attn_norm"], params["wq"], params["wk"], params["wv"],
        biases, params["wo"], params.get("post_attn_norm"),
        params["mlp_norm"], params.get("post_mlp_norm"), mlp,
    )


def _project_qkv(normed, wq, wk, wv, biases):
    """q/k/v projections with optional biases (Qwen-2); returns flat
    [..., H*D] / [..., KVH*D] arrays — callers reshape to heads."""
    q = qeinsum("...h,hd->...d", normed, wq)
    k = qeinsum("...h,hd->...d", normed, wk)
    v = qeinsum("...h,hd->...d", normed, wv)
    if biases is not None:
        bq, bk, bv = biases
        q = q + bq.astype(q.dtype)
        k = k + bk.astype(k.dtype)
        v = v + bv.astype(v.dtype)
    return q, k, v


def _norm(config: LlamaConfig, x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    return rms_norm(x, w, config.norm_eps, plus_one=config.norm_plus_one)


def _attn_scale(config: LlamaConfig) -> float:
    """Gemma scales scores by query_pre_attn_scalar**-0.5 instead of
    head_dim**-0.5; None keeps the Llama default."""
    return (config.query_pre_attn_scalar or config.dims_per_head) ** -0.5


def layer_windows(config: LlamaConfig) -> Optional[jnp.ndarray]:
    """Per-layer sliding-window sizes [L] (0 = full attention): Gemma-2
    alternates sliding/full starting with sliding at layer 0 (HF
    ``layer_types``). None when the family has no sliding window — the
    attention ops skip the window masking entirely."""
    if not config.sliding_window:
        return None
    return jnp.array(
        [
            config.sliding_window if i % 2 == 0 else 0
            for i in range(config.num_layers)
        ],
        dtype=jnp.int32,
    )


def _embed(config: LlamaConfig, params, tokens: jnp.ndarray) -> jnp.ndarray:
    x = params["embedding"][tokens].astype(config.dtype)
    if config.scale_embedding:
        x = x * jnp.asarray(math.sqrt(config.hidden_size), dtype=x.dtype)
    return x


def _mlp_block(
    config: LlamaConfig,
    normed: jnp.ndarray,
    mlp_weights,
    valid=None,
    dropless: bool = False,
):
    """SwiGLU MLP (dense or MoE) on normed activations [..., H].

    Returns (residual delta, MoE load-balance aux loss — 0 for dense).
    ``valid`` masks padding out of MoE capacity; ``dropless`` selects the
    serving capacity regime (no token ever dropped — required for
    checkpoints trained dropless, e.g. Mixtral)."""
    if config.num_experts:
        from langstream_tpu.ops.moe import moe_mlp

        w_gate, w_up, w_down, router = mlp_weights
        return moe_mlp(
            normed, router, w_gate, w_up, w_down,
            num_selected=config.num_experts_per_tok,
            capacity_factor=None if dropless else config.capacity_factor,
            valid=valid,
        )
    w_gate, w_up, w_down = mlp_weights
    gate = qeinsum("...h,hf->...f", normed, w_gate)
    up = qeinsum("...h,hf->...f", normed, w_up)
    if config.act == "gelu_tanh":  # GeGLU (Gemma): tanh-approx gelu gate
        activated = jax.nn.gelu(gate, approximate=True)
    else:
        activated = jax.nn.silu(gate)
    out = qeinsum("...f,fh->...h", activated * up, w_down)
    return out, jnp.zeros((), dtype=jnp.float32)


def _logits(config: LlamaConfig, params, x):
    if config.tie_embeddings:
        head = params["embedding"].T.astype(x.dtype)
        logits = jnp.einsum("...h,hv->...v", x, head).astype(jnp.float32)
    else:
        logits = qeinsum(
            "...h,hv->...v", x, params["lm_head"]
        ).astype(jnp.float32)
    cap = config.final_logit_softcap
    if cap is not None:
        logits = cap * jnp.tanh(logits / cap)
    return logits


def _flash_path(config, q, mesh):
    """Shared gate for the bf16/int8 prefill twins: (use the flash
    kernel?, dispatch through the tp shard_map wrapper?). One place for
    the MXU-alignment heuristic and the SPMD rule so the two paths
    cannot diverge. Softcap / sliding window (Gemma-2) ride INTO the
    kernels as a static cap and a traced per-layer window scalar."""
    flash_ok = config.use_flash and (
        use_flash(q.shape[1], q.shape[3]) or config.flash_interpret
    )
    tp_sharded = mesh is not None and dict(mesh.shape).get("tp", 1) > 1
    return flash_ok, tp_sharded


def _prefill_attn(config, q, k, v, mask, mesh=None, window=None):
    """Flash kernel on TPU for long MXU-aligned prompts, XLA einsum path
    otherwise (CPU tests, short prompts, odd head dims, softcap/window
    families — see :func:`_flash_path`). Under tensor
    parallelism (``mesh`` with tp>1) the kernel runs through shard_map
    over the head axis — a bare Mosaic call has no SPMD partitioning
    rule (``flash_prefill_attention_sharded``). Only called from the
    serving prefill path: the kernel has no VJP, so the differentiable
    :func:`forward` keeps the XLA formulation. Masks here are always
    right-padded (built from lengths), which is what the kernel's
    lengths-based masking assumes."""
    flash_ok, tp_sharded = _flash_path(config, q, mesh)
    family = dict(
        softcap=config.attn_logit_softcap, window=window,
        scale=_attn_scale(config),
    )
    if flash_ok:
        from langstream_tpu.ops.flash_attention import (
            flash_prefill_attention_sharded,
        )

        if tp_sharded:
            return flash_prefill_attention_sharded(
                q, k, v, mesh, mask=mask, interpret=config.flash_interpret,
                **family,
            )
        return flash_prefill_attention(
            q, k, v, mask=mask, interpret=config.flash_interpret, **family
        )
    return prefill_attention(q, k, v, mask=mask, **family)


def _decode_flash_path(config, q, kc, mesh):
    """Gate + dispatch mode for the flash-decode kernel — the decode
    twin of :func:`_flash_path`, same contract: returns (use the
    kernel?, tp shard_map?). Shape requirements bind even under the
    ``flash_interpret`` test hook; the backend/length policy (incl. the
    ``LS_DECODE_FLASH`` A/B override) only applies outside it."""
    from langstream_tpu.ops.decode_kernel import (
        decode_shapes_ok,
        use_flash_decode,
    )

    heads, dim = q.shape[1], q.shape[2]
    max_len, kv_heads = kc.shape[1], kc.shape[2]
    flash_ok = config.use_flash and (
        use_flash_decode(max_len, dim, heads, kv_heads)
        or (
            config.flash_interpret
            and decode_shapes_ok(max_len, dim, heads, kv_heads)
        )
    )
    tp_sharded = mesh is not None and dict(mesh.shape).get("tp", 1) > 1
    return flash_ok, tp_sharded


def _decode_attn(config, q, kc, vc, lengths, mesh=None, window=None):
    """Decode attention: length-aware Pallas kernel on TPU for long
    allocated caches (HBM traffic ∝ live context — the XLA einsum
    streams the full static buffer), XLA path otherwise. Under tp the
    kernel runs per head shard through shard_map
    (``flash_decode_attention_sharded``). ``window`` is this layer's
    sliding-window size (Gemma-2) and rides into the flash-decode
    kernel as a traced scalar, like softcap and scale — the kernel
    handles windowed layers itself; only non-shape-compatible configs
    gate off to XLA (see ``_decode_flash_path``)."""
    flash_ok, tp_sharded = _decode_flash_path(config, q, kc, mesh)
    family = dict(
        softcap=config.attn_logit_softcap, window=window,
        scale=_attn_scale(config),
    )
    if flash_ok:
        from langstream_tpu.ops.decode_kernel import (
            flash_decode_attention,
            flash_decode_attention_sharded,
        )

        if tp_sharded:
            return flash_decode_attention_sharded(
                q, kc, vc, lengths, mesh, interpret=config.flash_interpret,
                **family,
            )
        return flash_decode_attention(
            q, kc, vc, lengths, interpret=config.flash_interpret, **family
        )
    return decode_attention(q, kc, vc, lengths, **family)


def _decode_attn_quant(config, q, kc, ks, vc, vs, lengths, mesh=None,
                       window=None):
    """Int8-cache twin of :func:`_decode_attn`."""
    flash_ok, tp_sharded = _decode_flash_path(config, q, kc, mesh)
    family = dict(
        softcap=config.attn_logit_softcap, window=window,
        scale=_attn_scale(config),
    )
    if flash_ok:
        from langstream_tpu.ops.decode_kernel import (
            flash_decode_attention_quant,
            flash_decode_attention_sharded,
        )

        if tp_sharded:
            return flash_decode_attention_sharded(
                q, kc, vc, lengths, mesh, k_scale=ks, v_scale=vs,
                interpret=config.flash_interpret, **family,
            )
        return flash_decode_attention_quant(
            q, kc, ks, vc, vs, lengths, interpret=config.flash_interpret,
            **family,
        )
    return decode_attention_quant(q, kc, ks, vc, vs, lengths, **family)


def _prefill_attn_quant(config, q, k_q, k_s, v_q, v_s, lengths, mesh=None,
                        window=None):
    """Quantized-cold-prefill twin of :func:`_prefill_attn`: int8 flash
    kernel on TPU for long MXU-aligned prompts (same scale-folded
    algebra, int8 HBM loads), XLA ``chunk_attention_quant`` otherwise."""
    flash_ok, tp_sharded = _flash_path(config, q, mesh)
    family = dict(
        softcap=config.attn_logit_softcap, window=window,
        scale=_attn_scale(config),
    )
    if flash_ok:
        from langstream_tpu.ops.flash_attention import (
            flash_prefill_attention_quant,
            flash_prefill_attention_quant_sharded,
        )

        if tp_sharded:
            return flash_prefill_attention_quant_sharded(
                q, k_q, k_s, v_q, v_s, mesh, lengths=lengths,
                interpret=config.flash_interpret, **family,
            )
        return flash_prefill_attention_quant(
            q, k_q, k_s, v_q, v_s, lengths=lengths,
            interpret=config.flash_interpret, **family,
        )
    return chunk_attention_quant(
        q, k_q, k_s, v_q, v_s, jnp.zeros_like(lengths), lengths, **family
    )


def _use_fused_paged(config, dim, heads, kv_heads, mesh):
    """Gate for the fused ragged paged-attention kernel
    (``ops/paged_attention.py``) — the paged twin of
    :func:`_flash_path` / :func:`_decode_flash_path`. Under tensor
    parallelism the kernel dispatches through its shard_map twin
    (``ragged_paged_attention_sharded`` — one launch per kv-head shard,
    exactly like the dense flash kernels), so the gate is mesh-blind:
    only shapes (GQA divisibility, MXU head_dim alignment) and backend
    (TPU, or the interpret test hook) decide. ``mesh`` stays a
    parameter so the gate signature keeps matching the dispatch seams
    that pass it."""
    del mesh  # tp no longer downgrades — the sharded twin handles it
    from langstream_tpu.ops.paged_attention import use_fused_paged

    return config.use_flash and use_fused_paged(
        dim, heads, kv_heads, interpret=config.flash_interpret
    )


def _constrain_kv_shard(pool, mesh, *, scale: bool = False):
    """Pin a (possibly layer-stacked) KV pool leaf to its kv-head shard
    under tensor parallelism. Every jitted paged WRITE
    (``paged_write_rows`` scatter) routes its result through here: the
    scatter indexes the replicated block axis, and without an explicit
    constraint the SPMD partitioner is free to resolve it by
    all-gathering the pool — which would silently turn the paged layout
    into tp× HBM. The kv-head axis sits last on scale leaves
    ([..., N, Bs, KVH]) and second-to-last on value leaves
    ([..., N, Bs, KVH, D]). No-op off-mesh and at tp=1 (matching
    ``paged_cache_logical_axes``, whose tp-sized rule this mirrors)."""
    if mesh is None or dict(mesh.shape).get("tp", 1) <= 1:
        return pool
    from jax.sharding import NamedSharding, PartitionSpec

    axes = [None] * pool.ndim
    axes[pool.ndim - (1 if scale else 2)] = "tp"
    return jax.lax.with_sharding_constraint(
        pool, NamedSharding(mesh, PartitionSpec(*axes))
    )


def _mixed_block_q(width: int) -> int:
    """q-tile granularity for the token-ragged mixed dispatch: spans
    are ``width`` tokens per row, so the tile must divide the span —
    power-of-two widths take 8-row tiles, anything smaller (or odd)
    collapses to one tile per row."""
    return 8 if width % 8 == 0 else width


def _paged_attn(config, q, k_pool, v_pool, tables, starts, totals, *,
                window, kernel, mesh=None, q_lens=None):
    """Paged attention dispatch, ONE seam for all the ragged cases:
    decode (q [S, H, D], starts = lengths-1), prefill-at-offset and cold
    paged prefill (q [B, T, H, D]), and — with ``q_lens`` — the MIXED
    prefill+decode dispatch, where every row carries its own new-token
    count (decode rows 1, admitting rows a prefill window, idle rows 0)
    and the fused path runs the token-ragged q formulation
    (:func:`langstream_tpu.ops.paged_attention.ragged_q_paged_attention`
    — flattened q tile + cu_q_lens-style row offsets, dead q tiles
    skipped). ``kernel == "fused"`` (and shapes / backend permitting —
    see :func:`_use_fused_paged`) runs the single fused Pallas launch
    that streams table-addressed pool blocks; under tp>1 that launch
    runs per kv-head shard through the shard_map twin (a bare Mosaic
    call has no SPMD partitioning rule). The gather/scatter composition
    in ``ops/attention.py`` stays as the reference oracle (it already
    speaks per-row starts/totals, so mixed rows need no new reference
    path — positions past a row's count compute discarded garbage)."""
    family = dict(
        softcap=config.attn_logit_softcap, window=window,
        scale=_attn_scale(config),
    )
    decode = q.ndim == 3
    heads, dim = q.shape[-2], q.shape[-1]
    kv_heads = k_pool.shape[2]
    if kernel == "fused" and _use_fused_paged(
        config, dim, heads, kv_heads, mesh
    ):
        from langstream_tpu.ops.paged_attention import (
            ragged_paged_attention,
            ragged_paged_attention_sharded,
            ragged_q_paged_attention,
            ragged_q_paged_attention_sharded,
        )

        tp_sharded = mesh is not None and dict(mesh.shape).get("tp", 1) > 1
        if q_lens is not None and not decode:
            # token-ragged q: rows at uniform stride in the flattened
            # tile (q_offsets = b·W — the cu_q_lens special case the
            # engine's static [S, W] dispatch shape produces)
            batch, width = q.shape[:2]
            q_flat = q.reshape(batch * width, heads, dim)
            qoffs = jnp.arange(batch, dtype=jnp.int32) * width
            block_q = _mixed_block_q(width)
            if tp_sharded:
                out = ragged_q_paged_attention_sharded(
                    q_flat, k_pool, v_pool, tables, starts, totals,
                    qoffs, mesh, max_q_len=width, block_q=block_q,
                    interpret=config.flash_interpret, **family,
                )
            else:
                out = ragged_q_paged_attention(
                    q_flat, k_pool, v_pool, tables, starts, totals,
                    qoffs, max_q_len=width, block_q=block_q,
                    interpret=config.flash_interpret, **family,
                )
            return out.reshape(batch, width, heads, dim)
        q_in = q[:, None] if decode else q
        if tp_sharded:
            out = ragged_paged_attention_sharded(
                q_in, k_pool, v_pool, tables, starts, totals, mesh,
                interpret=config.flash_interpret, **family,
            )
        else:
            out = ragged_paged_attention(
                q_in, k_pool, v_pool, tables, starts, totals,
                interpret=config.flash_interpret, **family,
            )
        return out[:, 0] if decode else out
    if decode:
        return paged_decode_attention(
            q, k_pool, v_pool, tables, totals, **family
        )
    return paged_chunk_attention(
        q, k_pool, v_pool, tables, starts, totals, **family
    )


def _paged_attn_quant(config, q, k_pool, k_scale, v_pool, v_scale, tables,
                      starts, totals, *, window, kernel, mesh=None,
                      q_lens=None):
    """Int8-pool twin of :func:`_paged_attn` (scales stream through the
    same table-addressed index maps; ``q_lens`` selects the token-ragged
    mixed formulation exactly like the bf16 seam)."""
    family = dict(
        softcap=config.attn_logit_softcap, window=window,
        scale=_attn_scale(config),
    )
    decode = q.ndim == 3
    heads, dim = q.shape[-2], q.shape[-1]
    kv_heads = k_pool.shape[2]
    if kernel == "fused" and _use_fused_paged(
        config, dim, heads, kv_heads, mesh
    ):
        from langstream_tpu.ops.paged_attention import (
            ragged_paged_attention_quant,
            ragged_paged_attention_quant_sharded,
            ragged_q_paged_attention_quant,
            ragged_q_paged_attention_sharded,
        )

        tp_sharded = mesh is not None and dict(mesh.shape).get("tp", 1) > 1
        if q_lens is not None and not decode:
            batch, width = q.shape[:2]
            q_flat = q.reshape(batch * width, heads, dim)
            qoffs = jnp.arange(batch, dtype=jnp.int32) * width
            block_q = _mixed_block_q(width)
            if tp_sharded:
                out = ragged_q_paged_attention_sharded(
                    q_flat, k_pool, v_pool, tables, starts, totals,
                    qoffs, mesh, max_q_len=width, block_q=block_q,
                    k_scale=k_scale, v_scale=v_scale,
                    interpret=config.flash_interpret, **family,
                )
            else:
                out = ragged_q_paged_attention_quant(
                    q_flat, k_pool, k_scale, v_pool, v_scale,
                    tables, starts, totals, qoffs,
                    max_q_len=width, block_q=block_q,
                    interpret=config.flash_interpret, **family,
                )
            return out.reshape(batch, width, heads, dim)
        q_in = q[:, None] if decode else q
        if tp_sharded:
            out = ragged_paged_attention_quant_sharded(
                q_in, k_pool, k_scale, v_pool, v_scale,
                tables, starts, totals, mesh,
                interpret=config.flash_interpret, **family,
            )
        else:
            out = ragged_paged_attention_quant(
                q_in, k_pool, k_scale, v_pool, v_scale,
                tables, starts, totals, interpret=config.flash_interpret,
                **family,
            )
        return out[:, 0] if decode else out
    if decode:
        return paged_decode_attention_quant(
            q, k_pool, k_scale, v_pool, v_scale, tables, totals, **family
        )
    return paged_chunk_attention_quant(
        q, k_pool, k_scale, v_pool, v_scale, tables, starts, totals,
        **family,
    )


def _prefill_scan(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,     # [B, T] int32 (right-padded)
    lengths: jnp.ndarray,    # [B] true prompt lengths
    freqs: jnp.ndarray,
    mesh,
    quantized: bool,
) -> Tuple[jnp.ndarray, Tuple]:
    """The cold-prefill layer scan, shared by the dense and paged cache
    layouts (cold prefill's self-attention never reads the cache, so
    only the KV WRITE differs between them). Returns (activations
    [B, T, H] after the final layer, stacked per-layer KV outputs)."""
    batch, seq = tokens.shape
    hd = config.dims_per_head
    positions = jnp.arange(seq)[None, :].repeat(batch, 0)
    mask = positions < lengths[:, None]
    x = _embed(config, params, tokens)  # [B, T, H]

    layer_inputs = _stack_layer_params(params, config)
    windows = layer_windows(config)

    def layer_fn(x, inputs):
        layer, win = inputs
        (attn_norm, wq, wk, wv, biases, wo, post_attn, mlp_norm, post_mlp,
         mlp_weights) = layer
        normed = _norm(config, x, attn_norm)
        q, k, v = _project_qkv(normed, wq, wk, wv, biases)
        q = q.reshape(batch, seq, config.num_heads, hd)
        k = k.reshape(batch, seq, config.num_kv_heads, hd)
        v = v.reshape(batch, seq, config.num_kv_heads, hd)
        q = apply_rope(q, freqs, positions)
        k = apply_rope(k, freqs, positions)
        if quantized:
            # quantize ONCE and run the prompt's self-attention through
            # the SAME f32 scale-folded math the warm/decode dispatches
            # use (the just-written rows as the "cache", starts=0):
            # identical formulas over identical row contents keep
            # cold/warm/prefix-copy paths token-identical. Long
            # MXU-aligned prompts take the int8 flash kernel — identical
            # scale-folded algebra, int8 HBM tile loads — so kv-quant
            # keeps the flash HBM profile on cold prefill; block
            # boundaries reassociate f32 sums exactly like the bf16
            # flash path does.
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            attn = _prefill_attn_quant(
                config, q, k_q, k_s, v_q, v_s, lengths, mesh=mesh,
                window=win,
            )
            layer_kv_out = (k_q, v_q, k_s, v_s)
        else:
            layer_kv_out = (k, v)
            attn = _prefill_attn(config, q, k, v, mask, mesh=mesh,
                                 window=win)
        attn = qeinsum(
            "btd,dh->bth", attn.reshape(batch, seq, config.num_heads * hd), wo
        )
        if post_attn is not None:
            attn = _norm(config, attn, post_attn)
        x = x + attn
        normed = _norm(config, x, mlp_norm)
        delta, _ = _mlp_block(config, normed, mlp_weights, valid=mask, dropless=True)
        if post_mlp is not None:
            delta = _norm(config, delta, post_mlp)
        x = x + delta
        return x, layer_kv_out

    return jax.lax.scan(layer_fn, x, (layer_inputs, windows))


def _last_token_logits(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,          # [B, T, H]
    lengths: jnp.ndarray,    # [B]
) -> jnp.ndarray:
    x = _norm(config, x, params["final_norm"])
    batch = x.shape[0]
    last = x[jnp.arange(batch), (lengths - 1).astype(jnp.int32)]  # [B, H]
    return _logits(config, params, last)


# jit: device-context — runs inside the engine's jitted dispatches
def prefill(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,     # [B, T] int32 (right-padded)
    lengths: jnp.ndarray,    # [B] true prompt lengths
    slot_ids: jnp.ndarray,   # [B] cache slots to write
    freqs: jnp.ndarray,
    mesh=None,               # tp mesh for the sharded flash path
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Run the prompt through the model, write the KV cache at the given
    slots, return logits of each prompt's last real token [B, V]."""
    seq = tokens.shape[1]
    quantized = "k_scale" in cache
    x, layer_kv = _prefill_scan(
        config, params, tokens, lengths, freqs, mesh, quantized
    )
    max_len = cache["k"].shape[2]
    pad = max_len - seq

    def pad_rows(array):
        if pad <= 0:
            return array
        widths = [(0, 0), (0, 0), (0, pad)] + [(0, 0)] * (array.ndim - 3)
        return jnp.pad(array, widths)

    out = dict(cache)
    if quantized:
        # grouped (k, v, k_scale, v_scale) — the ordering every
        # quantized scan in this module uses
        new_k, new_v, k_scale, v_scale = layer_kv
        out["k_scale"] = cache["k_scale"].at[:, slot_ids].set(pad_rows(k_scale))
        out["v_scale"] = cache["v_scale"].at[:, slot_ids].set(pad_rows(v_scale))
    else:
        new_k, new_v = layer_kv
    out["k"] = cache["k"].at[:, slot_ids].set(
        pad_rows(new_k).astype(cache["k"].dtype)
    )
    out["v"] = cache["v"].at[:, slot_ids].set(
        pad_rows(new_v).astype(cache["v"].dtype)
    )
    return out, _last_token_logits(config, params, x, lengths)


# jit: device-context — runs inside the engine's jitted dispatches
def prefill_at_offset(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,     # [B, T] int32 suffix tokens (right-padded)
    lengths: jnp.ndarray,    # [B] true suffix lengths
    offsets: jnp.ndarray,    # [B] existing valid cache length per row
    slot_ids: jnp.ndarray,   # [B] cache slots to extend
    freqs: jnp.ndarray,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Chunked prefill of a *suffix* into warm cache slots: positions are
    offset by the already-cached prefix, new KV is written at
    ``offset..offset+len-1``, and attention runs over prefix + suffix.
    One dispatch replaces the old per-token teacher-forcing path for
    warm-session follow-ups (KV session reuse, BASELINE config #5).
    Caller must guarantee ``offset + T <= cache max_len`` (the engine's
    warm check enforces it — a clamped dynamic_update_slice would
    silently overwrite live prefix rows otherwise).
    Returns (cache, logits of each row's last real suffix token [B, V])."""
    batch, seq = tokens.shape
    hd = config.dims_per_head
    positions = offsets[:, None] + jnp.arange(seq)[None, :]  # [B, T] global
    mask = jnp.arange(seq)[None, :] < lengths[:, None]       # [B, T] valid
    totals = offsets + lengths                               # [B]
    x = _embed(config, params, tokens)                       # [B, T, H]

    layer_inputs = _stack_layer_params(params, config)
    windows = layer_windows(config)
    quantized = "k_scale" in cache

    def write_rows(kc, new, offs):
        # kc: [S, max_len, ...]; new: [B, T, ...] — write each row's
        # suffix window at its offset (rank-agnostic: value leaves carry
        # a head_dim axis, scale leaves don't). Padding positions beyond
        # the suffix length land past ``totals`` where content is dead.
        def body(kc, args):
            row_new, off, slot = args
            row = jax.lax.dynamic_slice(
                kc, (slot,) + (0,) * (kc.ndim - 1), (1,) + kc.shape[1:]
            )[0]
            row = jax.lax.dynamic_update_slice(
                row, row_new.astype(row.dtype),
                (off,) + (0,) * (row.ndim - 1),
            )
            return jax.lax.dynamic_update_slice(
                kc, row[None], (slot,) + (0,) * (kc.ndim - 1)
            ), None

        kc, _ = jax.lax.scan(body, kc, (new, offs, slot_ids))
        return kc

    def layer_fn(carry, inputs):
        x = carry
        if quantized:
            layer, kc, vc, ks, vs, win = inputs
        else:
            layer, kc, vc, win = inputs
        (attn_norm, wq, wk, wv, biases, wo, post_attn, mlp_norm, post_mlp,
         mlp_weights) = layer
        normed = _norm(config, x, attn_norm)
        q, k, v = _project_qkv(normed, wq, wk, wv, biases)
        q = q.reshape(batch, seq, config.num_heads, hd)
        k = k.reshape(batch, seq, config.num_kv_heads, hd)
        v = v.reshape(batch, seq, config.num_kv_heads, hd)
        q = apply_rope(q, freqs, positions)
        k = apply_rope(k, freqs, positions)
        softcap = config.attn_logit_softcap
        scale = _attn_scale(config)
        if quantized:
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            kc = write_rows(kc, k_q, offsets)
            ks = write_rows(ks, k_s, offsets)
            vc = write_rows(vc, v_q, offsets)
            vs = write_rows(vs, v_s, offsets)
            attn = chunk_attention_quant(
                q, kc[slot_ids], ks[slot_ids], vc[slot_ids],
                vs[slot_ids], offsets, totals,
                softcap=softcap, window=win, scale=scale,
            )
            kv_out = (kc, vc, ks, vs)
        else:
            kc = write_rows(kc, k, offsets)
            vc = write_rows(vc, v, offsets)
            attn = chunk_attention(
                q, kc[slot_ids], vc[slot_ids], offsets, totals,
                softcap=softcap, window=win, scale=scale,
            )
            kv_out = (kc, vc)
        attn = qeinsum(
            "btd,dh->bth", attn.reshape(batch, seq, config.num_heads * hd), wo
        )
        if post_attn is not None:
            attn = _norm(config, attn, post_attn)
        x = x + attn
        normed = _norm(config, x, mlp_norm)
        delta, _ = _mlp_block(config, normed, mlp_weights, valid=mask, dropless=True)
        if post_mlp is not None:
            delta = _norm(config, delta, post_mlp)
        x = x + delta
        return x, kv_out

    if quantized:
        xs = (layer_inputs, cache["k"], cache["v"],
              cache["k_scale"], cache["v_scale"], windows)
    else:
        xs = (layer_inputs, cache["k"], cache["v"], windows)
    x, kv_caches = jax.lax.scan(layer_fn, x, xs)
    out = dict(cache)
    if quantized:
        out["k"], out["v"], out["k_scale"], out["v_scale"] = kv_caches
    else:
        out["k"], out["v"] = kv_caches
    x = _norm(config, x, params["final_norm"])
    last = x[jnp.arange(batch), (lengths - 1).astype(jnp.int32)]  # [B, H]
    logits = _logits(config, params, last)
    return out, logits


# jit: device-context — runs inside the engine's jitted dispatches
def paged_prefill(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],   # paged pool (init_paged_cache)
    tokens: jnp.ndarray,             # [B, T] int32 (right-padded)
    lengths: jnp.ndarray,            # [B] true prompt lengths
    block_tables: jnp.ndarray,       # [B, M] pool block per seq block
    freqs: jnp.ndarray,
    mesh=None,                       # tp mesh for the sharded flash path
    kernel: str = "fused",           # paged attention: fused | reference
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Cold prefill into the paged block pool.

    Fused path (``kernel="fused"`` and the gate passes): cold prefill is
    prefill-at-offset with every offset 0 — the SAME fused ragged launch
    the warm and decode paths use, reading the just-written blocks
    through the tables (identical formulas over identical row contents,
    the same trick the quantized cold path has always used). Reference
    path: the dense layer scan (and flash kernel gating) of
    :func:`prefill` — cold self-attention never reads the cache — with
    the KV write scattered through the block tables."""
    batch, seq = tokens.shape
    quantized = "k_scale" in cache
    hd = config.dims_per_head
    if kernel == "fused" and _use_fused_paged(
        config, hd, config.num_heads, config.num_kv_heads, mesh
    ):
        return paged_prefill_at_offset(
            config, params, cache, tokens, lengths,
            jnp.zeros_like(lengths), block_tables, freqs,
            mesh=mesh, kernel=kernel,
        )
    x, layer_kv = _prefill_scan(
        config, params, tokens, lengths, freqs, mesh, quantized
    )
    valid = jnp.arange(seq)[None, :] < lengths[:, None]
    zeros = jnp.zeros((batch,), jnp.int32)

    def write(pool, new, scale=False):
        return _constrain_kv_shard(
            jax.vmap(
                lambda p, n: paged_write_rows(p, n, block_tables, zeros, valid)
            )(pool, new),
            mesh, scale=scale,
        )

    out = dict(cache)
    if quantized:
        new_k, new_v, k_scale, v_scale = layer_kv
        out["k_scale"] = write(cache["k_scale"], k_scale, scale=True)
        out["v_scale"] = write(cache["v_scale"], v_scale, scale=True)
    else:
        new_k, new_v = layer_kv
    out["k"] = write(cache["k"], new_k)
    out["v"] = write(cache["v"], new_v)
    return out, _last_token_logits(config, params, x, lengths)


# jit: device-context — runs inside the engine's jitted dispatches
def paged_prefill_at_offset(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],   # paged pool
    tokens: jnp.ndarray,             # [B, T] suffix tokens (right-padded)
    lengths: jnp.ndarray,            # [B] true suffix lengths
    offsets: jnp.ndarray,            # [B] existing valid length per row
    block_tables: jnp.ndarray,       # [B, M]
    freqs: jnp.ndarray,
    mesh=None,                       # tp mesh (fused kernel runs per
                                     # kv-head shard via shard_map)
    kernel: str = "fused",           # paged attention: fused | reference
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Paged twin of :func:`prefill_at_offset`: suffix KV scatters into
    table-addressed blocks, attention reads prefix + suffix through
    the SAME tables — which is how a request admitted onto a cached
    prefix chain (prefix-cache hit) attends over blocks some other
    request's prefill wrote. Shared blocks are never written here: the
    engine admits suffixes at block-aligned boundaries into private
    blocks (COW for mid-block session divergence happens before the
    dispatch). Attention dispatches through :func:`_paged_attn` — one
    fused table-addressed launch by default, gather/scatter reference
    otherwise."""
    batch, seq = tokens.shape
    hd = config.dims_per_head
    positions = offsets[:, None] + jnp.arange(seq)[None, :]  # [B, T] global
    mask = jnp.arange(seq)[None, :] < lengths[:, None]       # [B, T] valid
    totals = offsets + lengths                               # [B]
    x = _embed(config, params, tokens)                       # [B, T, H]

    layer_inputs = _stack_layer_params(params, config)
    windows = layer_windows(config)
    quantized = "k_scale" in cache

    def write(pool, new, scale=False):
        return _constrain_kv_shard(
            paged_write_rows(pool, new, block_tables, offsets, mask),
            mesh, scale=scale,
        )

    def layer_fn(carry, inputs):
        x = carry
        if quantized:
            layer, kp, vp, ks, vs, win = inputs
        else:
            layer, kp, vp, win = inputs
        (attn_norm, wq, wk, wv, biases, wo, post_attn, mlp_norm, post_mlp,
         mlp_weights) = layer
        normed = _norm(config, x, attn_norm)
        q, k, v = _project_qkv(normed, wq, wk, wv, biases)
        q = q.reshape(batch, seq, config.num_heads, hd)
        k = k.reshape(batch, seq, config.num_kv_heads, hd)
        v = v.reshape(batch, seq, config.num_kv_heads, hd)
        q = apply_rope(q, freqs, positions)
        k = apply_rope(k, freqs, positions)
        if quantized:
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            kp = write(kp, k_q)
            ks = write(ks, k_s, scale=True)
            vp = write(vp, v_q)
            vs = write(vs, v_s, scale=True)
            attn = _paged_attn_quant(
                config, q, kp, ks, vp, vs, block_tables, offsets, totals,
                window=win, kernel=kernel, mesh=mesh,
            )
            kv_out = (kp, vp, ks, vs)
        else:
            kp = write(kp, k)
            vp = write(vp, v)
            attn = _paged_attn(
                config, q, kp, vp, block_tables, offsets, totals,
                window=win, kernel=kernel, mesh=mesh,
            )
            kv_out = (kp, vp)
        attn = qeinsum(
            "btd,dh->bth", attn.reshape(batch, seq, config.num_heads * hd), wo
        )
        if post_attn is not None:
            attn = _norm(config, attn, post_attn)
        x = x + attn
        normed = _norm(config, x, mlp_norm)
        delta, _ = _mlp_block(config, normed, mlp_weights, valid=mask, dropless=True)
        if post_mlp is not None:
            delta = _norm(config, delta, post_mlp)
        x = x + delta
        return x, kv_out

    if quantized:
        xs = (layer_inputs, cache["k"], cache["v"],
              cache["k_scale"], cache["v_scale"], windows)
    else:
        xs = (layer_inputs, cache["k"], cache["v"], windows)
    x, kv_caches = jax.lax.scan(layer_fn, x, xs)
    out = dict(cache)
    if quantized:
        out["k"], out["v"], out["k_scale"], out["v_scale"] = kv_caches
    else:
        out["k"], out["v"] = kv_caches
    return out, _last_token_logits(config, params, x, lengths)


# jit: device-context — runs inside the engine's jitted dispatches
def paged_decode_step(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],   # paged pool
    tokens: jnp.ndarray,             # [S] int32 — one new token per slot
    lengths: jnp.ndarray,            # [S] length INCLUDING the new token
    block_tables: jnp.ndarray,       # [S, M]
    freqs: jnp.ndarray,
    write_mask: Optional[jnp.ndarray] = None,  # [S] bool
    mesh=None,                       # tp mesh (fused kernel runs per
                                     # kv-head shard via shard_map)
    kernel: str = "fused",           # paged attention: fused | reference
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Paged twin of :func:`decode_step`: the new token's KV scatters
    into its slot's current block (masked slots route to the null
    block), attention reads the live context through the tables — the
    decode (Tq=1, start=length-1) case of the :func:`_paged_attn`
    dispatch, so a mixed prefill+decode paged batch runs the same fused
    launch path end to end. Decode never allocates — the engine reserves
    each request's worst case (prompt + max_new_tokens) at admission, so
    this path cannot fail on pool pressure mid-flight."""
    slots = tokens.shape[0]
    hd = config.dims_per_head
    positions = (lengths - 1).astype(jnp.int32)  # [S]
    if write_mask is None:
        write_mask = jnp.ones((slots,), dtype=bool)
    x = _embed(config, params, tokens)  # [S, H]

    layer_inputs = _stack_layer_params(params, config)
    windows = layer_windows(config)
    quantized = "k_scale" in cache

    def write(pool, new, scale=False):
        return _constrain_kv_shard(
            paged_write_rows(
                pool, new[:, None], block_tables, positions,
                write_mask[:, None],
            ),
            mesh, scale=scale,
        )

    def layer_fn(carry, inputs):
        x = carry
        if quantized:
            layer, kp, vp, ks, vs, win = inputs
        else:
            layer, kp, vp, win = inputs
        (attn_norm, wq, wk, wv, biases, wo, post_attn, mlp_norm, post_mlp,
         mlp_weights) = layer
        normed = _norm(config, x, attn_norm)
        q, k, v = _project_qkv(normed, wq, wk, wv, biases)
        q = q.reshape(slots, config.num_heads, hd)
        k = k.reshape(slots, config.num_kv_heads, hd)
        v = v.reshape(slots, config.num_kv_heads, hd)
        q = apply_rope(q[:, None], freqs, positions[:, None])[:, 0]
        k = apply_rope(k[:, None], freqs, positions[:, None])[:, 0]
        if quantized:
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            kp, ks = write(kp, k_q), write(ks, k_s, scale=True)
            vp, vs = write(vp, v_q), write(vs, v_s, scale=True)
            attn = _paged_attn_quant(
                config, q, kp, ks, vp, vs, block_tables, positions,
                lengths, window=win, kernel=kernel, mesh=mesh,
            )
            kv_out = (kp, vp, ks, vs)
        else:
            kp, vp = write(kp, k), write(vp, v)
            attn = _paged_attn(
                config, q, kp, vp, block_tables, positions, lengths,
                window=win, kernel=kernel, mesh=mesh,
            )
            kv_out = (kp, vp)
        attn = qeinsum(
            "sd,dh->sh", attn.reshape(slots, config.num_heads * hd), wo
        )
        if post_attn is not None:
            attn = _norm(config, attn, post_attn)
        x = x + attn
        normed = _norm(config, x, mlp_norm)
        delta, _ = _mlp_block(config, normed, mlp_weights, dropless=True)
        if post_mlp is not None:
            delta = _norm(config, delta, post_mlp)
        x = x + delta
        return x, kv_out

    if quantized:
        xs = (layer_inputs, cache["k"], cache["v"],
              cache["k_scale"], cache["v_scale"], windows)
    else:
        xs = (layer_inputs, cache["k"], cache["v"], windows)
    x, kv_caches = jax.lax.scan(layer_fn, x, xs, unroll=_decode_unroll())
    out = dict(cache)
    if quantized:
        out["k"], out["v"], out["k_scale"], out["v_scale"] = kv_caches
    else:
        out["k"], out["v"] = kv_caches
    x = _norm(config, x, params["final_norm"])
    logits = _logits(config, params, x)
    return out, logits


# jit: device-context — runs inside the engine's jitted dispatches
def decode_step(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,     # [S] int32 — one new token per slot
    lengths: jnp.ndarray,    # [S] current length INCLUDING the new token
    freqs: jnp.ndarray,
    write_mask: Optional[jnp.ndarray] = None,  # [S] bool; False = don't
                                               # touch this slot's cache
    mesh=None,                                 # tp mesh for the sharded
                                               # flash-decode kernel
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """One decode step for every slot: write the new token's KV, attend
    over the cache, return next-token logits [S, V]. Cache is donated by
    the engine's jit wrapper (in-place on device). ``write_mask`` protects
    slots that are merely riding along (inactive, or logits-only reruns)
    from having their cache row clobbered."""
    slots = tokens.shape[0]
    hd = config.dims_per_head
    positions = (lengths - 1).astype(jnp.int32)  # [S]
    if write_mask is None:
        write_mask = jnp.ones((slots,), dtype=bool)
    x = _embed(config, params, tokens)  # [S, H]

    layer_inputs = _stack_layer_params(params, config)
    windows = layer_windows(config)
    quantized = "k_scale" in cache

    def write(c, pos, new, enabled):
        return c.at[pos].set(jnp.where(enabled, new, c[pos]))

    def layer_fn(carry, inputs):
        x = carry
        if quantized:
            layer, kc, vc, ks, vs, win = inputs
        else:
            layer, kc, vc, win = inputs
        (attn_norm, wq, wk, wv, biases, wo, post_attn, mlp_norm, post_mlp,
         mlp_weights) = layer
        normed = _norm(config, x, attn_norm)
        q, k, v = _project_qkv(normed, wq, wk, wv, biases)
        q = q.reshape(slots, config.num_heads, hd)
        k = k.reshape(slots, config.num_kv_heads, hd)
        v = v.reshape(slots, config.num_kv_heads, hd)
        q = apply_rope(q[:, None], freqs, positions[:, None])[:, 0]
        k = apply_rope(k[:, None], freqs, positions[:, None])[:, 0]
        if quantized:
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            kc = jax.vmap(write)(kc, positions, k_q, write_mask)
            ks = jax.vmap(write)(ks, positions, k_s, write_mask)
            vc = jax.vmap(write)(vc, positions, v_q, write_mask)
            vs = jax.vmap(write)(vs, positions, v_s, write_mask)
            attn = _decode_attn_quant(
                config, q, kc, ks, vc, vs, lengths, mesh=mesh, window=win
            )
            kv_out = (kc, vc, ks, vs)
        else:
            kc = jax.vmap(write)(kc, positions, k, write_mask)
            vc = jax.vmap(write)(vc, positions, v, write_mask)
            attn = _decode_attn(
                config, q, kc, vc, lengths, mesh=mesh, window=win
            )
            kv_out = (kc, vc)
        attn = qeinsum(
            "sd,dh->sh", attn.reshape(slots, config.num_heads * hd), wo
        )
        if post_attn is not None:
            attn = _norm(config, attn, post_attn)
        x = x + attn
        normed = _norm(config, x, mlp_norm)
        # decode groups are tiny (S = slots) so dropless capacity is cheap;
        # inactive slots can't evict anyone, so no valid mask is needed
        delta, _ = _mlp_block(config, normed, mlp_weights, dropless=True)
        if post_mlp is not None:
            delta = _norm(config, delta, post_mlp)
        x = x + delta
        return x, kv_out

    if quantized:
        xs = (layer_inputs, cache["k"], cache["v"],
              cache["k_scale"], cache["v_scale"], windows)
    else:
        xs = (layer_inputs, cache["k"], cache["v"], windows)
    # unroll lets XLA software-pipeline the next layer's weight loads
    # against the current layer's compute on the weights-bound decode
    # path (measured via LS_DECODE_UNROLL; 1 = plain scan)
    x, kv_caches = jax.lax.scan(layer_fn, x, xs, unroll=_decode_unroll())
    out = dict(cache)
    if quantized:
        out["k"], out["v"], out["k_scale"], out["v_scale"] = kv_caches
    else:
        out["k"], out["v"] = kv_caches
    x = _norm(config, x, params["final_norm"])
    logits = _logits(config, params, x)
    return out, logits


def _decode_unroll() -> int:
    import os

    return max(1, int(os.environ.get("LS_DECODE_UNROLL", "1")))


# jit: device-context — runs inside the engine's jitted dispatches
def verify_step(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,      # [S, B] int32 — last token + drafted block
    lengths: jnp.ndarray,     # [S] cache length INCLUDING tokens[:, 0]
    valid_lens: jnp.ndarray,  # [S] real tokens in the block (1 + drafted;
                              # 0 = inactive row)
    freqs: jnp.ndarray,
    write_mask: Optional[jnp.ndarray] = None,  # [S] bool
    mesh=None,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Speculative verify: :func:`decode_step` generalized to a [S, B]
    token block per slot. Teacher-forces the block at each slot's
    current position (tokens[:, 0] is the pending token whose KV row a
    plain decode step would write, tokens[:, 1:] are drafted
    candidates), writes KV for every real block position, attends
    causally over prefix + block, and returns logits for EVERY position
    [S, B, V] — the acceptance pass needs the distribution at each
    candidate, not just the last one (which is why this is not
    :func:`prefill_at_offset`). Writes are per-position masked scatters
    (OOB dropped), so rejected-suffix rollback is a pure length rewind:
    positions past the accepted length hold garbage that is causally
    invisible until a later step overwrites them in order."""
    slots, seq = tokens.shape
    hd = config.dims_per_head
    offsets = (lengths - 1).astype(jnp.int32)                # [S]
    positions = offsets[:, None] + jnp.arange(seq)[None, :]  # [S, B] global
    mask = jnp.arange(seq)[None, :] < valid_lens[:, None]    # [S, B] valid
    totals = offsets + valid_lens                            # [S]
    if write_mask is None:
        write_mask = jnp.ones((slots,), dtype=bool)
    wmask = mask & write_mask[:, None]
    x = _embed(config, params, tokens)                       # [S, B, H]

    layer_inputs = _stack_layer_params(params, config)
    windows = layer_windows(config)
    quantized = "k_scale" in cache
    max_len = cache["k"].shape[2]
    rows = jnp.arange(slots)[:, None]
    softcap = config.attn_logit_softcap
    scale = _attn_scale(config)
    # masked rows (inactive slot, padding beyond the drafted count, or a
    # carry that ran past max_seq_len) route out of bounds and drop —
    # a clamped dynamic_update_slice would silently overwrite live rows
    write_pos = jnp.where(wmask, positions, max_len)

    def write_rows(kc, new):
        return kc.at[rows, write_pos].set(
            new.astype(kc.dtype), mode="drop"
        )

    def layer_fn(carry, inputs):
        x = carry
        if quantized:
            layer, kc, vc, ks, vs, win = inputs
        else:
            layer, kc, vc, win = inputs
        (attn_norm, wq, wk, wv, biases, wo, post_attn, mlp_norm, post_mlp,
         mlp_weights) = layer
        normed = _norm(config, x, attn_norm)
        q, k, v = _project_qkv(normed, wq, wk, wv, biases)
        q = q.reshape(slots, seq, config.num_heads, hd)
        k = k.reshape(slots, seq, config.num_kv_heads, hd)
        v = v.reshape(slots, seq, config.num_kv_heads, hd)
        q = apply_rope(q, freqs, positions)
        k = apply_rope(k, freqs, positions)
        if quantized:
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            kc = write_rows(kc, k_q)
            ks = write_rows(ks, k_s)
            vc = write_rows(vc, v_q)
            vs = write_rows(vs, v_s)
            attn = chunk_attention_quant(
                q, kc, ks, vc, vs, offsets, totals,
                softcap=softcap, window=win, scale=scale,
            )
            kv_out = (kc, vc, ks, vs)
        else:
            kc = write_rows(kc, k)
            vc = write_rows(vc, v)
            attn = chunk_attention(
                q, kc, vc, offsets, totals,
                softcap=softcap, window=win, scale=scale,
            )
            kv_out = (kc, vc)
        attn = qeinsum(
            "sbd,dh->sbh", attn.reshape(slots, seq, config.num_heads * hd), wo
        )
        if post_attn is not None:
            attn = _norm(config, attn, post_attn)
        x = x + attn
        normed = _norm(config, x, mlp_norm)
        delta, _ = _mlp_block(config, normed, mlp_weights, valid=mask,
                              dropless=True)
        if post_mlp is not None:
            delta = _norm(config, delta, post_mlp)
        x = x + delta
        return x, kv_out

    if quantized:
        xs = (layer_inputs, cache["k"], cache["v"],
              cache["k_scale"], cache["v_scale"], windows)
    else:
        xs = (layer_inputs, cache["k"], cache["v"], windows)
    x, kv_caches = jax.lax.scan(layer_fn, x, xs, unroll=_decode_unroll())
    out = dict(cache)
    if quantized:
        out["k"], out["v"], out["k_scale"], out["v_scale"] = kv_caches
    else:
        out["k"], out["v"] = kv_caches
    x = _norm(config, x, params["final_norm"])
    return out, _logits(config, params, x)  # [S, B, V]


# jit: device-context — runs inside the engine's jitted dispatches
def paged_verify_step(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],   # paged pool
    tokens: jnp.ndarray,             # [S, B] int32 block per slot
    lengths: jnp.ndarray,            # [S] length INCLUDING tokens[:, 0]
    valid_lens: jnp.ndarray,         # [S] real tokens (0 = inactive)
    block_tables: jnp.ndarray,       # [S, M]
    freqs: jnp.ndarray,
    write_mask: Optional[jnp.ndarray] = None,  # [S] bool
    mesh=None,
    kernel: str = "fused",
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Paged twin of :func:`verify_step`: the candidate block's KV
    scatters into table-addressed blocks (masked/overflow rows route to
    the null block) and attention is the fused kernel's existing Tq>1
    prefill-at-offset formulation — no new kernel. Blocks were reserved
    worst-case at admission, so verify never allocates and rollback is
    a length-pointer rewind only."""
    slots, seq = tokens.shape
    hd = config.dims_per_head
    offsets = (lengths - 1).astype(jnp.int32)
    positions = offsets[:, None] + jnp.arange(seq)[None, :]  # [S, B] global
    mask = jnp.arange(seq)[None, :] < valid_lens[:, None]
    totals = offsets + valid_lens
    if write_mask is None:
        write_mask = jnp.ones((slots,), dtype=bool)
    wmask = mask & write_mask[:, None]
    x = _embed(config, params, tokens)

    layer_inputs = _stack_layer_params(params, config)
    windows = layer_windows(config)
    quantized = "k_scale" in cache

    def write(pool, new, scale=False):
        return _constrain_kv_shard(
            paged_write_rows(pool, new, block_tables, offsets, wmask),
            mesh, scale=scale,
        )

    def layer_fn(carry, inputs):
        x = carry
        if quantized:
            layer, kp, vp, ks, vs, win = inputs
        else:
            layer, kp, vp, win = inputs
        (attn_norm, wq, wk, wv, biases, wo, post_attn, mlp_norm, post_mlp,
         mlp_weights) = layer
        normed = _norm(config, x, attn_norm)
        q, k, v = _project_qkv(normed, wq, wk, wv, biases)
        q = q.reshape(slots, seq, config.num_heads, hd)
        k = k.reshape(slots, seq, config.num_kv_heads, hd)
        v = v.reshape(slots, seq, config.num_kv_heads, hd)
        q = apply_rope(q, freqs, positions)
        k = apply_rope(k, freqs, positions)
        if quantized:
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            kp = write(kp, k_q)
            ks = write(ks, k_s, scale=True)
            vp = write(vp, v_q)
            vs = write(vs, v_s, scale=True)
            attn = _paged_attn_quant(
                config, q, kp, ks, vp, vs, block_tables, offsets, totals,
                window=win, kernel=kernel, mesh=mesh,
            )
            kv_out = (kp, vp, ks, vs)
        else:
            kp = write(kp, k)
            vp = write(vp, v)
            attn = _paged_attn(
                config, q, kp, vp, block_tables, offsets, totals,
                window=win, kernel=kernel, mesh=mesh,
            )
            kv_out = (kp, vp)
        attn = qeinsum(
            "sbd,dh->sbh", attn.reshape(slots, seq, config.num_heads * hd), wo
        )
        if post_attn is not None:
            attn = _norm(config, attn, post_attn)
        x = x + attn
        normed = _norm(config, x, mlp_norm)
        delta, _ = _mlp_block(config, normed, mlp_weights, valid=mask,
                              dropless=True)
        if post_mlp is not None:
            delta = _norm(config, delta, post_mlp)
        x = x + delta
        return x, kv_out

    if quantized:
        xs = (layer_inputs, cache["k"], cache["v"],
              cache["k_scale"], cache["v_scale"], windows)
    else:
        xs = (layer_inputs, cache["k"], cache["v"], windows)
    x, kv_caches = jax.lax.scan(layer_fn, x, xs, unroll=_decode_unroll())
    out = dict(cache)
    if quantized:
        out["k"], out["v"], out["k_scale"], out["v_scale"] = kv_caches
    else:
        out["k"], out["v"] = kv_caches
    x = _norm(config, x, params["final_norm"])
    return out, _logits(config, params, x)  # [S, B, V]


# jit: device-context — runs inside the engine's jitted dispatches
def paged_mixed_step(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    cache: Dict[str, jnp.ndarray],   # paged pool
    tokens: jnp.ndarray,             # [S, W] int32 per-row new tokens
    offsets: jnp.ndarray,            # [S] existing valid rows per slot
    num_tokens: jnp.ndarray,         # [S] live new tokens (0 = idle row)
    block_tables: jnp.ndarray,       # [S, M]
    freqs: jnp.ndarray,
    write_mask: Optional[jnp.ndarray] = None,  # [S] bool
    mesh=None,
    kernel: str = "fused",
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Unified mixed prefill+decode dispatch — ``decode_step`` and
    ``prefill_at_offset`` as ONE seam over per-row token counts
    (Sarathi-style chunked-prefill batching): a decode row carries its
    pending token (``offsets = length, num_tokens = 1``), an admitting
    row carries a ``prefill_chunk``-token window of its prompt
    (``offsets = taught-so-far``), an idle row carries nothing
    (``num_tokens = 0``). KV scatters through the block tables with
    per-position masking (padding/idle rows route to the null block —
    the :func:`paged_verify_step` machinery, which already proved this
    formulation token-exact against the split paths), attention runs
    the token-ragged fused launch (or the gather reference) through
    :func:`_paged_attn`, and ONE weight pass serves every row — the
    whole point: admitting a prompt costs decode riders a bounded
    mixed step, never a monolithic bucket-sized prefill dispatch.

    Returns (cache, logits [S, V]) of each row's LAST live token — the
    only position the engine samples (decode rows sample their next
    token; an admitting row's sample is meaningful only on the window
    that completes its prompt; idle/mid-prefill rows are discarded)."""
    slots, width = tokens.shape
    hd = config.dims_per_head
    positions = offsets[:, None] + jnp.arange(width)[None, :]  # [S, W]
    mask = jnp.arange(width)[None, :] < num_tokens[:, None]    # [S, W]
    totals = offsets + num_tokens                              # [S]
    if write_mask is None:
        write_mask = jnp.ones((slots,), dtype=bool)
    wmask = mask & write_mask[:, None]
    x = _embed(config, params, tokens)                         # [S, W, H]

    layer_inputs = _stack_layer_params(params, config)
    windows = layer_windows(config)
    quantized = "k_scale" in cache

    def write(pool, new, scale=False):
        return _constrain_kv_shard(
            paged_write_rows(pool, new, block_tables, offsets, wmask),
            mesh, scale=scale,
        )

    def layer_fn(carry, inputs):
        x = carry
        if quantized:
            layer, kp, vp, ks, vs, win = inputs
        else:
            layer, kp, vp, win = inputs
        (attn_norm, wq, wk, wv, biases, wo, post_attn, mlp_norm, post_mlp,
         mlp_weights) = layer
        normed = _norm(config, x, attn_norm)
        q, k, v = _project_qkv(normed, wq, wk, wv, biases)
        q = q.reshape(slots, width, config.num_heads, hd)
        k = k.reshape(slots, width, config.num_kv_heads, hd)
        v = v.reshape(slots, width, config.num_kv_heads, hd)
        q = apply_rope(q, freqs, positions)
        k = apply_rope(k, freqs, positions)
        if quantized:
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            kp = write(kp, k_q)
            ks = write(ks, k_s, scale=True)
            vp = write(vp, v_q)
            vs = write(vs, v_s, scale=True)
            attn = _paged_attn_quant(
                config, q, kp, ks, vp, vs, block_tables, offsets, totals,
                window=win, kernel=kernel, mesh=mesh, q_lens=num_tokens,
            )
            kv_out = (kp, vp, ks, vs)
        else:
            kp = write(kp, k)
            vp = write(vp, v)
            attn = _paged_attn(
                config, q, kp, vp, block_tables, offsets, totals,
                window=win, kernel=kernel, mesh=mesh, q_lens=num_tokens,
            )
            kv_out = (kp, vp)
        attn = qeinsum(
            "sbd,dh->sbh",
            attn.reshape(slots, width, config.num_heads * hd), wo,
        )
        if post_attn is not None:
            attn = _norm(config, attn, post_attn)
        x = x + attn
        normed = _norm(config, x, mlp_norm)
        delta, _ = _mlp_block(config, normed, mlp_weights, valid=mask,
                              dropless=True)
        if post_mlp is not None:
            delta = _norm(config, delta, post_mlp)
        x = x + delta
        return x, kv_out

    if quantized:
        xs = (layer_inputs, cache["k"], cache["v"],
              cache["k_scale"], cache["v_scale"], windows)
    else:
        xs = (layer_inputs, cache["k"], cache["v"], windows)
    x, kv_caches = jax.lax.scan(layer_fn, x, xs, unroll=_decode_unroll())
    out = dict(cache)
    if quantized:
        out["k"], out["v"], out["k_scale"], out["v_scale"] = kv_caches
    else:
        out["k"], out["v"] = kv_caches
    x = _norm(config, x, params["final_norm"])
    last = x[
        jnp.arange(slots),
        jnp.clip(num_tokens - 1, 0, width - 1).astype(jnp.int32),
    ]  # [S, H] — each row's last live token
    return out, _logits(config, params, last)  # [S, V]


# jit: device-context — runs inside the engine's jitted dispatches
def apply_layers(
    config: LlamaConfig,
    layer_inputs,          # stacked layer params (from _stack_layer_params),
                           # possibly a contiguous slice of the layers
    x: jnp.ndarray,        # [B, T, H] activations
    mask: Optional[jnp.ndarray],   # [B, T] valid-token mask or None
    freqs: jnp.ndarray,
    dropless: bool = False,
    layer_offset: int = 0,  # global index of layer_inputs[0] — keeps the
                            # sliding-window parity right for static
                            # layer slices
    windows: Optional[jnp.ndarray] = None,  # per-layer window sizes for
                            # THESE layers (overrides the config-derived
                            # slice — pipeline stages pass their pp-shard
                            # of layer_windows(), since a static offset
                            # cannot vary across SPMD stages)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scan the transformer layers over activations → (x, moe aux sum).

    Factored out of :func:`forward` so pipeline parallelism
    (``parallel.pipeline``) can run a *slice* of the layer stack as one
    pipeline stage."""
    batch, seq = x.shape[:2]
    hd = config.dims_per_head
    positions = jnp.arange(seq)[None, :].repeat(batch, 0)
    if windows is None:
        windows = layer_windows(config)
        if windows is not None:
            n = jax.tree_util.tree_leaves(layer_inputs)[0].shape[0]
            windows = windows[layer_offset:layer_offset + n]

    def layer_fn(carry, inputs):
        (x, aux) = carry
        layer, win = inputs
        (attn_norm, wq, wk, wv, biases, wo, post_attn, mlp_norm, post_mlp,
         mlp_weights) = layer
        normed = _norm(config, x, attn_norm)
        q, k, v = _project_qkv(normed, wq, wk, wv, biases)
        q = q.reshape(batch, seq, config.num_heads, hd)
        k = k.reshape(batch, seq, config.num_kv_heads, hd)
        v = v.reshape(batch, seq, config.num_kv_heads, hd)
        q = apply_rope(q, freqs, positions)
        k = apply_rope(k, freqs, positions)
        attn = prefill_attention(
            q, k, v, mask=mask,
            softcap=config.attn_logit_softcap, window=win,
            scale=_attn_scale(config),
        )
        attn = qeinsum(
            "btd,dh->bth", attn.reshape(batch, seq, config.num_heads * hd), wo
        )
        if post_attn is not None:
            attn = _norm(config, attn, post_attn)
        x = x + attn
        normed = _norm(config, x, mlp_norm)
        delta, layer_aux = _mlp_block(
            config, normed, mlp_weights, valid=mask, dropless=dropless
        )
        if post_mlp is not None:
            delta = _norm(config, delta, post_mlp)
        x = x + delta
        return (x, aux + layer_aux), None

    (x, aux), _ = jax.lax.scan(
        layer_fn, (x, jnp.zeros((), dtype=jnp.float32)),
        (layer_inputs, windows),
    )
    return x, aux


# jit: device-context — runs inside the engine's jitted dispatches
def forward(
    config: LlamaConfig,
    params: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,   # [B, T]
    mask: Optional[jnp.ndarray] = None,  # [B, T] valid-token mask
    freqs: Optional[jnp.ndarray] = None,
    with_aux: bool = False,
    dropless: bool = False,
) -> jnp.ndarray:
    """Cache-free full-sequence forward → logits [B, T, V] (training /
    scoring path; serving uses :func:`prefill`/:func:`decode_step`).
    With ``with_aux`` also returns the mean MoE load-balancing loss.
    ``dropless=True`` selects the exact MoE regime (no token dropping) —
    use it when scoring a dropless-trained checkpoint; training keeps the
    capacity regime so the router feels the balance pressure."""
    if freqs is None:
        freqs = model_freqs(config)
    x = _embed(config, params, tokens)
    layer_inputs = _stack_layer_params(params, config)
    x, aux = apply_layers(config, layer_inputs, x, mask, freqs, dropless)
    x = _norm(config, x, params["final_norm"])
    logits = _logits(config, params, x)
    if with_aux:
        return logits, aux / max(config.num_layers, 1)
    return logits


# ---------------------------------------------------------------------- #
# HuggingFace checkpoint import
# ---------------------------------------------------------------------- #
def config_from_hf(hf_config) -> LlamaConfig:
    rope_scaling = normalize_rope_scaling(
        getattr(hf_config, "rope_scaling", None)
    )
    gemma2 = getattr(hf_config, "model_type", "") == "gemma2"
    if gemma2:
        # Gemma-2 alternates sliding/full starting at layer 0; verify
        # the checkpoint follows that pattern before baking it in
        layer_types = getattr(hf_config, "layer_types", None)
        if layer_types is not None:
            expected = [
                "sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(hf_config.num_hidden_layers)
            ]
            if list(layer_types) != expected:
                raise ValueError(
                    f"unsupported gemma2 layer_types pattern: {layer_types}"
                )
    family = {}
    if getattr(hf_config, "model_type", "") == "qwen2":
        family = dict(qkv_bias=True)
    if gemma2:
        family = dict(
            attn_logit_softcap=getattr(
                hf_config, "attn_logit_softcapping", None
            ),
            final_logit_softcap=getattr(
                hf_config, "final_logit_softcapping", None
            ),
            query_pre_attn_scalar=float(
                getattr(hf_config, "query_pre_attn_scalar", 0) or 0
            ) or None,
            sliding_window=getattr(hf_config, "sliding_window", 0) or 0,
            norm_plus_one=True,
            post_norms=True,
            scale_embedding=True,
            act="gelu_tanh",
        )
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", hf_config.num_attention_heads),
        head_dim=getattr(hf_config, "head_dim", None),
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        norm_eps=hf_config.rms_norm_eps,
        max_seq_len=hf_config.max_position_embeddings,
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        num_experts=getattr(hf_config, "num_local_experts", 0) or 0,
        num_experts_per_tok=getattr(hf_config, "num_experts_per_tok", 2),
        rope_scaling=rope_scaling,
        **family,
    )


def load_hf_checkpoint(path_or_model, dtype=jnp.bfloat16):
    """Convert a HuggingFace Llama checkpoint (local path or loaded torch
    model) into (LlamaConfig, stacked-params pytree).

    The per-layer torch tensors are stacked along a leading layer axis to
    match the lax.scan layout. Linear weights transpose (torch stores
    [out, in]; we use [in, out] so forward is x @ W).
    """
    import torch

    if isinstance(path_or_model, str):
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(
            path_or_model, torch_dtype=torch.float32, local_files_only=True
        )
    else:
        model = path_or_model
    config = config_from_hf(model.config)
    config = dataclasses.replace(config, dtype=dtype)
    state = model.state_dict()

    def get(name):
        return jnp.asarray(state[name].to(torch.float32).numpy(), dtype=dtype)

    def stack(pattern, transpose=True):
        # cast each layer to the target dtype BEFORE stacking so transient
        # host memory is one float32 layer, not the whole float32 stack
        arrays = []
        for layer in range(config.num_layers):
            tensor = state[pattern.format(layer)].to(torch.float32).numpy()
            arrays.append(jnp.asarray(tensor.T if transpose else tensor, dtype=dtype))
        return jnp.stack(arrays)

    if config.num_experts:
        # Mixtral layout: block_sparse_moe.experts.{e}.w1/w3/w2 + gate
        def stack_experts(weight):
            # per-expert dtype cast before stacking: transient host memory
            # is one float32 expert matrix, not layers × experts of them
            arrays = []
            for layer in range(config.num_layers):
                per_expert = [
                    jnp.asarray(
                        state[
                            f"model.layers.{layer}.block_sparse_moe"
                            f".experts.{e}.{weight}.weight"
                        ].to(torch.float32).numpy().T,
                        dtype=dtype,
                    )
                    for e in range(config.num_experts)
                ]
                arrays.append(jnp.stack(per_expert))
            return jnp.stack(arrays)

        mlp_weights = {
            "w_gate": stack_experts("w1"),
            "w_up": stack_experts("w3"),
            "w_down": stack_experts("w2"),
            "router": stack("model.layers.{}.block_sparse_moe.gate.weight"),
        }
    else:
        mlp_weights = {
            "w_gate": stack("model.layers.{}.mlp.gate_proj.weight"),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight"),
            "w_down": stack("model.layers.{}.mlp.down_proj.weight"),
        }
    def stack_norm(pattern):
        return jnp.asarray(
            np.stack([
                state[pattern.format(i)].to(torch.float32).numpy()
                for i in range(config.num_layers)
            ]), dtype=jnp.float32,
        )

    if config.post_norms:
        # Gemma-2 sandwich norms: input_layernorm is the pre-attn norm,
        # post_attention_layernorm the POST-attn one (applied to the
        # block output before the residual add), and the feedforward
        # pair wraps the MLP the same way
        norms = {
            "attn_norm": stack_norm("model.layers.{}.input_layernorm.weight"),
            "post_attn_norm": stack_norm(
                "model.layers.{}.post_attention_layernorm.weight"
            ),
            "mlp_norm": stack_norm(
                "model.layers.{}.pre_feedforward_layernorm.weight"
            ),
            "post_mlp_norm": stack_norm(
                "model.layers.{}.post_feedforward_layernorm.weight"
            ),
        }
    else:
        norms = {
            "attn_norm": stack_norm("model.layers.{}.input_layernorm.weight"),
            "mlp_norm": stack_norm(
                "model.layers.{}.post_attention_layernorm.weight"
            ),
        }
    params = {
        "embedding": get("model.embed_tokens.weight"),
        "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
        "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
        "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
        "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
        **mlp_weights,
        **norms,
        **(
            {
                "bq": stack_norm("model.layers.{}.self_attn.q_proj.bias"),
                "bk": stack_norm("model.layers.{}.self_attn.k_proj.bias"),
                "bv": stack_norm("model.layers.{}.self_attn.v_proj.bias"),
            }
            if config.qkv_bias else {}
        ),
        "final_norm": jnp.asarray(
            state["model.norm.weight"].to(torch.float32).numpy(),
            dtype=jnp.float32,
        ),
    }
    if not config.tie_embeddings:
        params["lm_head"] = get("lm_head.weight").T
    return config, params
