"""Self-drafting speculative decoding — prompt-lookup drafter + on-device
acceptance (ROADMAP item 2).

Decode is memory-bound: every step streams the full weight + KV working
set to emit ONE token per slot. Speculation converts that wasted
bandwidth into useful FLOPs: a draft proposes k candidate tokens per
step, ONE batched forward verifies all k+1 positions at once
(``model.verify_step`` / ``model.paged_verify_step``), and an on-device
acceptance pass emits every candidate the model itself would have
produced — between 1 and k+1 tokens per dispatch for one weight pass.

Two pieces, both pure functions traced inside the engine's decode jit
(no host round trip per step):

- :func:`draft_ngram` — prompt-lookup drafting (PLD): find the most
  recent earlier occurrence of the slot's trailing n-gram in its own
  token history (prompt + generated, maintained as a device array in
  the scan carry) and propose the k tokens that followed it. Free —
  no draft model, no extra weights — and strong on the workloads that
  dominate serving: RAG quotes, code edits, chat templates, structured
  extraction, anywhere the output re-states spans of the input.
- :func:`accept_block` — sequential accept/reject over the verified
  block, preserving the EXACT sampling semantics of ``engine._sample``:
  greedy traffic accepts a candidate iff it equals the argmax
  (token-for-token parity with the non-speculative oracle), stochastic
  traffic runs rejection sampling against the same
  truncated/temperature-scaled distribution ``_sample`` draws from
  (accept candidate d w.p. p(d); on rejection, resample from the
  residual p with d masked — emitted tokens are distributed exactly as
  p at every position). Presence/frequency penalties and logit_bias are
  applied position-by-position with counts updated as candidates are
  accepted, and PRNG keys derive from (seed, position) exactly like the
  oracle — a slot with no draft this step reproduces the plain step
  bitwise, including seeded stochastic sampling.

Rollback needs no allocator work: rejected candidates' KV rows sit past
the accepted length where causal masking makes them invisible, and the
next step overwrites them in order (paged blocks were reserved
worst-case at admission).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


# jit: device-context — runs inside the engine's jitted dispatches
def draft_ngram(
    history: jnp.ndarray,   # [S, T] int32 — token at cache position t
    lengths: jnp.ndarray,   # [S] valid history INCLUDING the pending token
    active: jnp.ndarray,    # [S] bool
    *,
    ngram: int,
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Prompt-lookup drafting: per slot, suffix-match the trailing
    ``ngram`` tokens against the history and propose the ``k`` tokens
    following the MOST RECENT earlier match. Returns (drafts [S, k],
    num_drafted [S]); num is 0 when no match exists (the verify step
    then degenerates to a plain decode step)."""
    slots, width = history.shape
    idx = jnp.arange(width)
    # pattern = the trailing n-gram h[L-n .. L-1]
    pat_pos = jnp.clip(
        lengths[:, None] - ngram + jnp.arange(ngram)[None, :], 0, width - 1
    )
    pattern = jnp.take_along_axis(history, pat_pos, axis=1)  # [S, n]
    match = jnp.ones((slots, width), dtype=bool)
    for j in range(ngram):
        # h[i + j] aligned at i; wrap values are masked below (a valid
        # candidate needs i + n < L <= width, so it never wraps)
        match = match & (jnp.roll(history, -j, axis=1) == pattern[:, j:j + 1])
    # a candidate start i needs the n-gram inside the valid prefix AND
    # at least one continuation token strictly before the pending
    # position (i + n < L) — which also excludes the trailing n-gram's
    # trivial self-match at i = L - n
    match = match & ((idx[None, :] + ngram) < lengths[:, None])
    match = match & (lengths[:, None] >= ngram + 1) & active[:, None]
    best = jnp.max(jnp.where(match, idx[None, :], -1), axis=1)  # [S]
    found = best >= 0
    source = jnp.clip(
        best[:, None] + ngram + jnp.arange(k)[None, :], 0, width - 1
    )
    drafts = jnp.take_along_axis(history, source, axis=1)  # [S, k]
    num = jnp.where(found, jnp.clip(lengths - (best + ngram), 0, k), 0)
    # context-boundary clamp: drafted KV writes reach position
    # L - 1 + num, which must stay inside the cache width
    num = jnp.minimum(num, jnp.maximum(width - lengths, 0))
    return drafts.astype(jnp.int32), num.astype(jnp.int32)


def _accept_or_fallback(
    adjusted: jnp.ndarray,     # [S, V] penalty/bias-adjusted logits
    temperature: jnp.ndarray,  # [S]
    top_k: jnp.ndarray,        # [S]
    top_p: jnp.ndarray,        # [S]
    keys: jnp.ndarray,         # [S] per-slot PRNG keys for this position
    candidate: jnp.ndarray,    # [S] drafted token at this position
    have: jnp.ndarray,         # [S] bool — a draft exists here
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-position accept decision + the token to emit on rejection
    (or when no draft exists). Greedy rows accept iff candidate ==
    argmax and fall back to the argmax itself; stochastic rows accept
    w.p. p(candidate) under the SAME truncated/scaled distribution
    ``_sample`` uses and fall back to the residual distribution —
    token-exact parity for greedy, distribution-exact for sampling."""
    from langstream_tpu.providers.jax_local import engine as engine_lib

    slots, vocab = adjusted.shape
    greedy = jnp.argmax(adjusted, axis=-1)
    stochastic = temperature > 0
    # ONE truncation sort per position (the full-vocab sort dominates a
    # sampling step's cost), shared by the fallback sampler and the
    # acceptance probabilities — same mask, so the two cannot drift;
    # guarded exactly like _sample's truncated tier so greedy-only
    # traffic never pays it
    masked = jax.lax.cond(
        jnp.any(stochastic) & (jnp.any(top_k > 0) | jnp.any(top_p > 0)),
        lambda _: engine_lib._truncation_mask(adjusted, top_k, top_p),
        lambda _: adjusted,
        None,
    )
    # the oracle's own sampler covers the no-draft case: same key, same
    # cond tiering → a slot with no draft reproduces the plain step
    # bitwise (greedy AND seeded stochastic)
    plain = engine_lib._sample(
        adjusted, temperature, top_k, keys, top_p, masked=masked
    )

    def stochastic_case(_):
        scaled = masked / jnp.maximum(temperature, 1e-6)[:, None]
        logz = jax.scipy.special.logsumexp(scaled, axis=-1)
        cand = jnp.clip(candidate, 0, vocab - 1)
        logp = (
            jnp.take_along_axis(scaled, cand[:, None], axis=1)[:, 0] - logz
        )
        accept_keys = jax.vmap(
            lambda key: jax.random.fold_in(key, 1)
        )(keys)
        uniforms = jax.vmap(jax.random.uniform)(accept_keys)
        # accept d w.p. p(d): a draft outside the truncation set has
        # p = 0 (logp = -inf) and is always rejected
        accepted = jnp.log(jnp.maximum(uniforms, 1e-38)) < logp
        residual_keys = jax.vmap(
            lambda key: jax.random.fold_in(key, 2)
        )(keys)
        residual = scaled.at[jnp.arange(slots), cand].set(-jnp.inf)
        resampled = engine_lib._rowwise_categorical(residual_keys, residual)
        return accepted, resampled.astype(jnp.int32)

    def greedy_case(_):
        return jnp.zeros((slots,), dtype=bool), greedy.astype(jnp.int32)

    accept_st, residual_tok = jax.lax.cond(
        jnp.any(stochastic) & jnp.any(have), stochastic_case, greedy_case,
        None,
    )
    accept = jnp.where(stochastic, accept_st, greedy == candidate) & have
    fallback = jnp.where(stochastic & have, residual_tok, plain)
    return accept, fallback


# jit: device-context — runs inside the engine's jitted dispatches
def accept_block(
    logits: jnp.ndarray,       # [S, B, V] raw verify logits
    block: jnp.ndarray,        # [S, B] verified tokens (t0 + drafts)
    num_drafted: jnp.ndarray,  # [S]
    counts: jnp.ndarray,       # [S, V] generated-token counts (penalties)
    active: jnp.ndarray,       # [S] bool
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    seeds: jnp.ndarray,        # [S] uint32
    base_lengths: jnp.ndarray,  # [S] carry lengths at block entry — the
                               # oracle's key-position for emission 0
    presence: jnp.ndarray,
    frequency: jnp.ndarray,
    bias_ids: jnp.ndarray,     # [S, MAX_LOGIT_BIAS]
    bias_vals: jnp.ndarray,
    topk: int,                 # top_logprobs K (0 = off)
):
    """Sequential accept/reject over a verified block. Emission index i
    draws from logits[:, i] (penalties/bias applied with counts as of
    that position — identical ordering to the oracle scan) and checks
    candidate block[:, i+1]; the first rejection emits the fallback and
    stops the slot's block. Returns (emitted [S, B], logprobs [S, B],
    valid [S, B] — a True-prefix mask of emitted positions, updated
    counts, tops or None)."""
    from langstream_tpu.providers.jax_local import engine as engine_lib

    slots, width, _ = logits.shape
    rows = jnp.arange(slots)
    # candidate at emission index i is block[:, i + 1]; none at the last
    candidates = jnp.concatenate(
        [block[:, 1:], jnp.zeros((slots, 1), block.dtype)], axis=1
    )

    def position(carry, xs):
        counts, alive = carry
        logit_i, cand_i, i = xs
        raw = logit_i.astype(jnp.float32)
        adjusted = (
            raw
            - presence[:, None] * (counts > 0)
            - frequency[:, None] * counts
        )
        adjusted = adjusted.at[rows[:, None], bias_ids].add(bias_vals)
        keys = engine_lib._sampling_keys(seeds, base_lengths + i)
        have = (i < num_drafted) & active
        accepted, fallback = _accept_or_fallback(
            adjusted, temperature, top_k, top_p, keys, cand_i, have
        )
        emit = jnp.where(have & accepted, cand_i, fallback)
        emit = jnp.where(active, emit, 0).astype(jnp.int32)
        valid = alive & active
        lp = engine_lib._token_logprob(raw, emit)
        counts = counts.at[rows, emit].add(valid.astype(jnp.int32))
        alive = alive & have & accepted
        ys = (emit, lp, valid)
        if topk:
            ys = ys + engine_lib._top_logprobs(raw, topk)
        return (counts, alive), ys

    (counts, _), ys = jax.lax.scan(
        position,
        (counts, jnp.ones((slots,), dtype=bool)),
        (
            logits.transpose(1, 0, 2),
            candidates.transpose(1, 0),
            jnp.arange(width),
        ),
    )
    emitted = ys[0].transpose(1, 0)   # [S, B]
    logprobs = ys[1].transpose(1, 0)
    valid = ys[2].transpose(1, 0)
    tops: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None
    if topk:
        tops = (ys[3].transpose(1, 0, 2), ys[4].transpose(1, 0, 2))
    return emitted, logprobs, valid, counts, tops
