"""TPU-native text embeddings: a BERT-style bidirectional encoder in JAX.

Replaces the reference's DJL/PyTorch local-embeddings path
(``AbstractHuggingFaceEmbeddingService.java:38`` — all-MiniLM class models)
with an in-process JAX encoder: embed + learned positions, N post-norm
transformer layers with bidirectional attention, masked mean pooling, L2
normalize. Weights import from a local HuggingFace BERT checkpoint
(MiniLM / mpnet shapes); random init serves tests and benches.

Batches arrive already coalesced by the embeddings step's batch executor;
here they are padded to a few fixed length buckets so XLA compiles a
handful of shapes, then run as one fused device call.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = None


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 30522
    hidden_size: int = 384
    intermediate_size: int = 1536
    num_layers: int = 6
    num_heads: int = 12
    max_positions: int = 512
    norm_eps: float = 1e-12
    dtype: Any = jnp.float32

    @classmethod
    def minilm_l6(cls) -> "EncoderConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "EncoderConfig":
        return cls(vocab_size=300, hidden_size=32, intermediate_size=64,
                   num_layers=2, num_heads=4, max_positions=64)

    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "EncoderConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        clean = {k.replace("-", "_"): v for k, v in config.items()}
        preset = clean.pop("preset", None)
        if preset == "minilm-l6":
            return cls.minilm_l6()
        if preset == "tiny":
            return cls.tiny()
        return cls(**{k: v for k, v in clean.items() if k in known})


def init_encoder_params(config: EncoderConfig, seed: int = 0) -> Dict[str, Any]:
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 12)
    h, f, v = config.hidden_size, config.intermediate_size, config.vocab_size
    L = config.num_layers
    dt = config.dtype

    def normal(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(dt)

    return {
        "tok_emb": normal(keys[0], (v, h)),
        "pos_emb": normal(keys[1], (config.max_positions, h)),
        "emb_norm_w": jnp.ones((h,), jnp.float32),
        "emb_norm_b": jnp.zeros((h,), jnp.float32),
        "wq": normal(keys[2], (L, h, h)), "bq": jnp.zeros((L, h), dt),
        "wk": normal(keys[3], (L, h, h)), "bk": jnp.zeros((L, h), dt),
        "wv": normal(keys[4], (L, h, h)), "bv": jnp.zeros((L, h), dt),
        "wo": normal(keys[5], (L, h, h)), "bo": jnp.zeros((L, h), dt),
        "attn_norm_w": jnp.ones((L, h), jnp.float32),
        "attn_norm_b": jnp.zeros((L, h), jnp.float32),
        "w_in": normal(keys[6], (L, h, f)), "b_in": jnp.zeros((L, f), dt),
        "w_out": normal(keys[7], (L, f, h)), "b_out": jnp.zeros((L, h), dt),
        "mlp_norm_w": jnp.ones((L, h), jnp.float32),
        "mlp_norm_b": jnp.zeros((L, h), jnp.float32),
    }


def _layer_norm(x, weight, bias, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (out * weight + bias).astype(x.dtype)


def encode(
    config: EncoderConfig,
    params: Dict[str, Any],
    tokens: jnp.ndarray,  # [B, T]
    mask: jnp.ndarray,    # [B, T] bool
) -> jnp.ndarray:
    """Forward pass → L2-normalized mean-pooled embeddings [B, H]."""
    batch, seq = tokens.shape
    heads = config.num_heads
    hd = config.hidden_size // heads
    x = params["tok_emb"][tokens] + params["pos_emb"][:seq][None]
    x = _layer_norm(x, params["emb_norm_w"], params["emb_norm_b"], config.norm_eps)
    x = x.astype(config.dtype)

    layer_params = (
        params["wq"], params["bq"], params["wk"], params["bk"],
        params["wv"], params["bv"], params["wo"], params["bo"],
        params["attn_norm_w"], params["attn_norm_b"],
        params["w_in"], params["b_in"], params["w_out"], params["b_out"],
        params["mlp_norm_w"], params["mlp_norm_b"],
    )

    def layer_fn(x, layer):
        (wq, bq, wk, bk, wv, bv, wo, bo, anw, anb,
         w_in, b_in, w_out, b_out, mnw, mnb) = layer
        q = (jnp.einsum("bth,hd->btd", x, wq) + bq).reshape(batch, seq, heads, hd)
        k = (jnp.einsum("bth,hd->btd", x, wk) + bk).reshape(batch, seq, heads, hd)
        v = (jnp.einsum("bth,hd->btd", x, wv) + bv).reshape(batch, seq, heads, hd)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        scores = scores / math.sqrt(hd)
        scores = jnp.where(mask[:, None, None, :], scores, -1e30)
        weights = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(batch, seq, -1)
        attn = jnp.einsum("bth,hd->btd", attn, wo) + bo
        x = _layer_norm(x + attn, anw, anb, config.norm_eps)
        mlp = jax.nn.gelu(jnp.einsum("bth,hf->btf", x, w_in) + b_in)
        mlp = jnp.einsum("btf,fh->bth", mlp, w_out) + b_out
        x = _layer_norm(x + mlp, mnw, mnb, config.norm_eps)
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, layer_params)
    # masked mean pooling + L2 normalize (sentence-transformers recipe)
    weights = mask.astype(jnp.float32)[..., None]
    pooled = (x.astype(jnp.float32) * weights).sum(1) / jnp.maximum(
        weights.sum(1), 1e-9
    )
    norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
    return pooled / jnp.maximum(norm, 1e-9)


def load_hf_bert(path_or_model, dtype=jnp.float32) -> Tuple[EncoderConfig, Dict[str, Any]]:
    """Convert a HuggingFace BERT-architecture checkpoint (MiniLM etc.)."""
    import torch

    if isinstance(path_or_model, str):
        from transformers import AutoModel

        model = AutoModel.from_pretrained(
            path_or_model, torch_dtype=torch.float32, local_files_only=True
        )
    else:
        model = path_or_model
    hf = model.config
    config = EncoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.hidden_size,
        intermediate_size=hf.intermediate_size,
        num_layers=hf.num_hidden_layers,
        num_heads=hf.num_attention_heads,
        max_positions=hf.max_position_embeddings,
        norm_eps=hf.layer_norm_eps,
        dtype=dtype,
    )
    state = model.state_dict()
    L = config.num_layers

    def get(name, transpose=False):
        t = state[name].to(torch.float32).numpy()
        return jnp.asarray(t.T if transpose else t, dtype=dtype)

    def stack(pattern, transpose=True):
        return jnp.asarray(
            np.stack([
                state[pattern.format(i)].to(torch.float32).numpy().T
                if transpose else state[pattern.format(i)].to(torch.float32).numpy()
                for i in range(L)
            ]),
            dtype=dtype,
        )

    prefix = "encoder.layer.{}."
    params = {
        "tok_emb": get("embeddings.word_embeddings.weight"),
        "pos_emb": get("embeddings.position_embeddings.weight"),
        "emb_norm_w": get("embeddings.LayerNorm.weight").astype(jnp.float32),
        "emb_norm_b": get("embeddings.LayerNorm.bias").astype(jnp.float32),
        "wq": stack(prefix + "attention.self.query.weight"),
        "bq": stack(prefix + "attention.self.query.bias", transpose=False),
        "wk": stack(prefix + "attention.self.key.weight"),
        "bk": stack(prefix + "attention.self.key.bias", transpose=False),
        "wv": stack(prefix + "attention.self.value.weight"),
        "bv": stack(prefix + "attention.self.value.bias", transpose=False),
        "wo": stack(prefix + "attention.output.dense.weight"),
        "bo": stack(prefix + "attention.output.dense.bias", transpose=False),
        "attn_norm_w": stack(prefix + "attention.output.LayerNorm.weight", transpose=False).astype(jnp.float32),
        "attn_norm_b": stack(prefix + "attention.output.LayerNorm.bias", transpose=False).astype(jnp.float32),
        "w_in": stack(prefix + "intermediate.dense.weight"),
        "b_in": stack(prefix + "intermediate.dense.bias", transpose=False),
        "w_out": stack(prefix + "output.dense.weight"),
        "b_out": stack(prefix + "output.dense.bias", transpose=False),
        "mlp_norm_w": stack(prefix + "output.LayerNorm.weight", transpose=False).astype(jnp.float32),
        "mlp_norm_b": stack(prefix + "output.LayerNorm.bias", transpose=False).astype(jnp.float32),
    }
    # token_type embeddings fold into token embeddings (single-segment use)
    if "embeddings.token_type_embeddings.weight" in state:
        params["tok_emb"] = params["tok_emb"] + get(
            "embeddings.token_type_embeddings.weight"
        )[0][None, :]
    return config, params


def _next_pow2(value: int, floor: int = 1) -> int:
    size = floor
    while size < value:
        size *= 2
    return size


class JaxEmbedder:
    """Bucketed-length batch embedding front-end."""

    def __init__(
        self,
        config: EncoderConfig,
        params: Dict[str, Any],
        tokenizer,
        max_length: int = 256,
    ) -> None:
        self.config = config
        self.params = params
        self.tokenizer = tokenizer
        self.max_length = min(max_length, config.max_positions)
        self._jit = jax.jit(
            lambda p, t, m: encode(config, p, t, m)
        )

    def embed(self, texts: List[str]) -> List[List[float]]:
        token_lists = [
            self.tokenizer.encode(text)[: self.max_length] for text in texts
        ]
        longest = max((len(t) for t in token_lists), default=1)
        bucket = min(_next_pow2(longest, floor=16), self.max_length)
        # pad the batch DIMENSION to a power of two as well: the batch
        # executor flushes partial batches on its linger timer, and every
        # distinct (rows, bucket) shape is its own XLA compilation —
        # without this, ragged traffic compiles up to batch-size variants
        # instead of log2 of them (padding rows are all-masked)
        padded_rows = _next_pow2(max(1, len(texts)))
        batch = np.zeros((padded_rows, bucket), dtype=np.int32)
        mask = np.zeros((padded_rows, bucket), dtype=bool)
        for i, tokens in enumerate(token_lists):
            tokens = tokens[:bucket]
            batch[i, : len(tokens)] = tokens
            mask[i, : len(tokens)] = True
        out = self._jit(self.params, jnp.asarray(batch), jnp.asarray(mask))
        return np.asarray(out)[: len(texts)].tolist()
