"""Provider registry: resource config → completions/embeddings services.

Equivalent of the reference's ServiceLoader registry
(``langstream-agents/langstream-ai-agents/src/main/java/ai/langstream/ai/agents/services/ServiceProviderRegistry.java:58``):
given the app's ``resources:`` entries, find the provider that owns each and
build (cached) service instances.
"""

from __future__ import annotations

import importlib
import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

from langstream_tpu.api.service import (
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
)

logger = logging.getLogger(__name__)

_PROVIDER_FACTORIES: List[Callable[[], ServiceProvider]] = []


def register_provider(factory: Callable[[], ServiceProvider]) -> None:
    _PROVIDER_FACTORIES.append(factory)


def _lazy(module_name: str, class_name: str) -> Callable[[], ServiceProvider]:
    def factory() -> ServiceProvider:
        module = importlib.import_module(module_name)
        return getattr(module, class_name)()

    return factory


register_provider(_lazy("langstream_tpu.providers.mock", "MockServiceProvider"))
register_provider(_lazy("langstream_tpu.providers.jax_local.provider", "JaxLocalServiceProvider"))
register_provider(_lazy("langstream_tpu.providers.openai_compat", "OpenAICompatServiceProvider"))
register_provider(_lazy("langstream_tpu.providers.huggingface", "HuggingFaceServiceProvider"))
register_provider(_lazy("langstream_tpu.providers.bedrock", "BedrockServiceProvider"))
register_provider(_lazy("langstream_tpu.providers.vertex", "VertexServiceProvider"))


class ServiceProviderRegistry:
    """Resolves and caches services per resource entry."""

    def __init__(self, resources: Optional[Dict[str, Dict[str, Any]]] = None):
        self.resources = resources or {}
        self._providers: Optional[List[ServiceProvider]] = None
        self._completions: Dict[str, CompletionsService] = {}
        self._embeddings: Dict[Tuple[str, Optional[str]], EmbeddingsService] = {}

    def _provider_instances(self) -> List[ServiceProvider]:
        if self._providers is None:
            self._providers = []
            for factory in _PROVIDER_FACTORIES:
                try:
                    self._providers.append(factory())
                except Exception as error:  # noqa: BLE001 — optional deps
                    logger.debug("provider factory failed: %s", error)
        return self._providers

    def _find(self, resource_name: Optional[str]) -> Tuple[str, Dict[str, Any], ServiceProvider]:
        candidates: List[Tuple[str, Dict[str, Any]]]
        if resource_name:
            if resource_name not in self.resources:
                raise ValueError(
                    f"unknown resource {resource_name!r}; declared: "
                    f"{sorted(self.resources)}"
                )
            candidates = [(resource_name, self.resources[resource_name])]
        else:
            candidates = list(self.resources.items())
        for name, resource in candidates:
            for provider in self._provider_instances():
                if provider.supports(resource):
                    return name, resource, provider
        raise ValueError(
            "no AI service provider matches the declared resources "
            f"({sorted(self.resources)}); declare one in configuration.yaml"
        )

    def completions(self, resource_name: Optional[str] = None) -> CompletionsService:
        name, resource, provider = self._find(resource_name)
        if name not in self._completions:
            self._completions[name] = provider.get_completions_service(
                resource.get("configuration", resource)
            )
        return self._completions[name]

    def embeddings(
        self, resource_name: Optional[str] = None, model: Optional[str] = None
    ) -> EmbeddingsService:
        name, resource, provider = self._find(resource_name)
        key = (name, model)
        if key not in self._embeddings:
            self._embeddings[key] = provider.get_embeddings_service(
                resource.get("configuration", resource), model=model
            )
        return self._embeddings[key]

    async def close(self) -> None:
        for service in self._completions.values():
            await service.close()
        for service in self._embeddings.values():
            await service.close()
        self._completions.clear()
        self._embeddings.clear()


def default_registry(resources: Dict[str, Dict[str, Any]]) -> ServiceProviderRegistry:
    return ServiceProviderRegistry(resources)
