"""Google Vertex AI provider (REST + service-account OAuth, no SDK).

Reference: ``langstream-agents/langstream-ai-agents/src/main/java/ai/
langstream/ai/agents/services/impl/VertexAIProvider.java:58`` — resources
of type ``vertex-configuration`` with ``url``, ``region``, ``project``,
and either a static ``token`` or ``serviceAccountJson``. Chat/completions
and embeddings go through the ``:predict`` endpoints; the OAuth2 access
token is minted from the service account with an RS256 JWT grant
(the same flow google-auth performs, implemented on ``cryptography``).
"""

from __future__ import annotations

import base64
import json
import time
from typing import Any, Dict, List, Optional

from langstream_tpu.api.service import (
    ChatChunk,
    ChatCompletionResult,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    StreamingChunksConsumer,
)

_OAUTH_TOKEN_URL = "https://oauth2.googleapis.com/token"
_SCOPE = "https://www.googleapis.com/auth/cloud-platform"


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


class _TokenSource:
    """Static token, or service-account JWT-grant tokens with caching."""

    def __init__(self, config: Dict[str, Any]) -> None:
        self.static_token = config.get("token")
        raw = config.get("serviceAccountJson") or config.get(
            "service-account-json"
        )
        self.service_account = (
            json.loads(raw) if isinstance(raw, str) else raw
        )
        self.token_url = config.get("token-url", _OAUTH_TOKEN_URL)
        self._cached: Optional[str] = None
        self._expiry = 0.0
        if not self.static_token and not self.service_account:
            raise ValueError(
                "vertex configuration needs 'token' or 'serviceAccountJson'"
            )

    def _assertion(self) -> str:
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import padding

        now = int(time.time())
        header = {"alg": "RS256", "typ": "JWT"}
        claims = {
            "iss": self.service_account["client_email"],
            "scope": _SCOPE,
            "aud": self.token_url,
            "iat": now,
            "exp": now + 3600,
        }
        signing_input = (
            f"{_b64url(json.dumps(header).encode())}."
            f"{_b64url(json.dumps(claims).encode())}"
        )
        key = serialization.load_pem_private_key(
            self.service_account["private_key"].encode(), password=None
        )
        signature = key.sign(
            signing_input.encode(), padding.PKCS1v15(), hashes.SHA256()
        )
        return f"{signing_input}.{_b64url(signature)}"

    async def token(self, session) -> str:
        if self.static_token:
            return self.static_token
        if self._cached and time.time() < self._expiry - 120:
            return self._cached
        async with session.post(
            self.token_url,
            data={
                "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
                "assertion": self._assertion(),
            },
        ) as response:
            payload = await response.json(content_type=None)
            if response.status >= 300 or "access_token" not in payload:
                raise IOError(f"vertex token exchange failed: {payload}")
        self._cached = payload["access_token"]
        self._expiry = time.time() + float(payload.get("expires_in", 3600))
        return self._cached


class VertexCompletionsService(CompletionsService):
    def __init__(self, config: Dict[str, Any]) -> None:
        self.url = (config.get("url")
                    or "https://us-central1-aiplatform.googleapis.com"
                    ).rstrip("/")
        self.project = config.get("project")
        self.region = config.get("region", "us-central1")
        self.tokens = _TokenSource(config)
        self._session = None

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    def _endpoint(self, model: str) -> str:
        return (
            f"{self.url}/v1/projects/{self.project}/locations/{self.region}"
            f"/publishers/google/models/{model}:predict"
        )

    async def _predict(self, model: str, body: Dict[str, Any]) -> Dict[str, Any]:
        session = await self._get_session()
        token = await self.tokens.token(session)
        async with session.post(
            self._endpoint(model), json=body,
            headers={"Authorization": f"Bearer {token}"},
        ) as response:
            payload = await response.json(content_type=None)
            if response.status >= 300:
                raise IOError(
                    f"vertex predict HTTP {response.status}: "
                    f"{str(payload)[:500]}"
                )
            return payload

    async def get_chat_completions(
        self,
        messages: List[ChatMessage],
        options: Dict[str, Any],
        stream_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionResult:
        model = options.get("model") or "chat-bison"
        parameters = {}
        for src, dst in (
            ("temperature", "temperature"), ("max-tokens", "maxOutputTokens"),
            ("top-p", "topP"), ("top-k", "topK"),
        ):
            if options.get(src) is not None:
                parameters[dst] = options[src]
        body = {
            "instances": [{
                "messages": [
                    {"author": m.role or "user", "content": m.content}
                    for m in messages
                ],
            }],
            "parameters": parameters,
        }
        payload = await self._predict(model, body)
        prediction = payload["predictions"][0]
        candidates = prediction.get("candidates") or []
        content = (
            candidates[0].get("content", "")
            if candidates else prediction.get("content", "")
        )
        if stream_consumer is not None:
            stream_consumer.consume_chunk(
                "vertex", 0, ChatChunk(content=content, index=0), last=True
            )
        return ChatCompletionResult(
            content=content, finish_reason="stop",
            prompt_tokens=0, completion_tokens=0,
        )

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()


class VertexEmbeddingsService(EmbeddingsService):
    def __init__(self, completions: VertexCompletionsService, model: str):
        self._svc = completions
        self.model = model or "textembedding-gecko"

    async def compute_embeddings(self, texts: List[str]) -> List[List[float]]:
        payload = await self._svc._predict(  # noqa: SLF001 — same client
            self.model, {"instances": [{"content": t} for t in texts]}
        )
        return [
            p["embeddings"]["values"] for p in payload["predictions"]
        ]

    async def close(self) -> None:
        await self._svc.close()


class VertexServiceProvider(ServiceProvider):
    name = "vertex"

    def supports(self, resource_config: Dict[str, Any]) -> bool:
        return (
            resource_config.get("type") == "vertex-configuration"
            or "vertex" in resource_config
        )

    def get_completions_service(
        self, resource_config: Dict[str, Any]
    ) -> CompletionsService:
        return VertexCompletionsService(
            resource_config.get("configuration", resource_config)
        )

    def get_embeddings_service(
        self, resource_config: Dict[str, Any], model: Optional[str] = None
    ) -> EmbeddingsService:
        return VertexEmbeddingsService(
            VertexCompletionsService(
                resource_config.get("configuration", resource_config)
            ),
            model,
        )
