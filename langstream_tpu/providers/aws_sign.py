"""AWS SigV4 request signing (shared by the S3 client and Bedrock).

Stdlib-only; the same canonical-request flow the S3 source uses
(``agents/storage.py``), generalized over the service name so
``bedrock-runtime`` requests sign identically.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse
from typing import Dict, Optional


def sign_request(
    *,
    method: str,
    url: str,
    region: str,
    service: str,
    access_key: str,
    secret_key: str,
    body: bytes = b"",
    headers: Optional[Dict[str, str]] = None,
    session_token: Optional[str] = None,
) -> Dict[str, str]:
    """Return the full header set (including Authorization) for ``url``."""
    parsed = urllib.parse.urlparse(url)
    host = parsed.netloc
    raw_path = parsed.path or "/"
    if service == "s3":
        # S3 is the one service whose canonical URI is the path as-is
        # (no re-encoding); everything else URI-encodes each segment —
        # e.g. Bedrock model ids contain ':' which must sign as %3A
        path = raw_path
    else:
        path = "/".join(
            urllib.parse.quote(segment, safe="-._~")
            for segment in raw_path.split("/")
        ) or "/"
    # canonical query: keys and values URI-encoded, sorted
    pairs = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    query = "&".join(
        f"{urllib.parse.quote(k, safe='-._~')}="
        f"{urllib.parse.quote(v, safe='-._~')}"
        for k, v in sorted(pairs)
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date_stamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(body).hexdigest()

    all_headers = {k.lower(): v for k, v in (headers or {}).items()}
    all_headers.update({
        "host": host,
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
    })
    if session_token:
        all_headers["x-amz-security-token"] = session_token

    signed_names = ";".join(sorted(all_headers))
    canonical_headers = "".join(
        f"{name}:{all_headers[name].strip()}\n" for name in sorted(all_headers)
    )
    canonical_request = "\n".join(
        [method, path, query, canonical_headers, signed_names, payload_hash]
    )
    scope = f"{date_stamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256",
        amz_date,
        scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])

    def _hmac(key: bytes, message: str) -> bytes:
        return hmac.new(key, message.encode(), hashlib.sha256).digest()

    key = _hmac(f"AWS4{secret_key}".encode(), date_stamp)
    key = _hmac(key, region)
    key = _hmac(key, service)
    key = _hmac(key, "aws4_request")
    signature = hmac.new(
        key, string_to_sign.encode(), hashlib.sha256
    ).hexdigest()
    all_headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_names}, Signature={signature}"
    )
    return all_headers
