"""Checkpoint / resume for model weights and trainer state (orbax).

The reference's checkpoint story is broker offsets + per-agent persistent
volumes + agent-custom status files (SURVEY §5 "Checkpoint / resume" —
e.g. the webcrawler's S3 status,
langstream-agent-webcrawler/src/main/java/ai/langstream/agents/webcrawler/WebCrawlerSource.java:381-440).
The TPU build adds the missing piece the reference never needed: *model
state* — sharded parameter pytrees, optimizer state, and the training
step — saved asynchronously with orbax so a preempted TPU job resumes
from the last step. Serving engines load the same checkpoints (weights
only) by path, giving one artifact format across train → serve.

Layout: ``<dir>/<step>/{params,opt_state,meta}`` managed by
``orbax.checkpoint.CheckpointManager`` (retention, atomicity, async
commit). Sharded arrays restore with the *target* sharding provided by
the caller, so a checkpoint written on one mesh reloads onto another
(e.g. train on dp×fsdp, serve on tp).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin wrapper over orbax for (params, opt_state, step, config)."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
    ) -> None:
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    def save(
        self,
        step: int,
        params: Any,
        opt_state: Any = None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """Queue an async save; returns whether a save was started."""
        items = {"params": ocp.args.StandardSave(params)}
        if opt_state is not None:
            items["opt_state"] = ocp.args.StandardSave(opt_state)
        if meta is not None:
            items["meta"] = ocp.args.JsonSave(meta)
        return self._manager.save(step, args=ocp.args.Composite(**items))

    def restore(
        self,
        step: Optional[int] = None,
        *,
        params_target: Any = None,
        opt_state_target: Any = None,
    ) -> Dict[str, Any]:
        """Restore a checkpoint (latest if ``step`` is None).

        Targets are abstract pytrees (e.g. ``jax.eval_shape`` results or
        arrays with the desired sharding); passing them restores each
        array directly onto its target sharding/devices.
        """
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        items: Dict[str, Any] = {}
        if params_target is not None:
            items["params"] = ocp.args.StandardRestore(params_target)
        else:
            items["params"] = ocp.args.StandardRestore()
        saved = self._manager.item_metadata(step)
        if saved is not None and "opt_state" in saved:
            if opt_state_target is not None:
                items["opt_state"] = ocp.args.StandardRestore(opt_state_target)
            else:
                items["opt_state"] = ocp.args.StandardRestore()
        if saved is not None and "meta" in saved:
            items["meta"] = ocp.args.JsonRestore()
        restored = self._manager.restore(step, args=ocp.args.Composite(**items))

        def match_sharding(value, target):
            # orbax can bring scalar leaves (e.g. optimizer step counts)
            # back on a single device even when the target is replicated
            # over a mesh — re-place leaves whose sharding diverges from
            # the target's (no-op when orbax already honored it)
            if target is None:
                return value

            def fix(restored_leaf, target_leaf):
                want = getattr(target_leaf, "sharding", None)
                if want is None or getattr(restored_leaf, "sharding", None) == want:
                    return restored_leaf
                return jax.device_put(restored_leaf, want)

            return jax.tree.map(fix, value, target)

        out = {
            "step": step,
            "params": match_sharding(restored["params"], params_target),
        }
        if "opt_state" in items:
            out["opt_state"] = match_sharding(
                restored.get("opt_state"), opt_state_target
            )
        if "meta" in items:
            out["meta"] = restored.get("meta")
        return out

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def all_steps(self):
        return list(self._manager.all_steps())

    def wait(self) -> None:
        """Block until queued async saves are committed."""
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()


def config_meta(config) -> Dict[str, Any]:
    """JSON-safe dict of a model config dataclass (dtype by name)."""
    out = {
        k: v for k, v in dataclasses.asdict(config).items()
        if isinstance(v, (int, float, str, bool))
    }
    dtype = getattr(config, "dtype", None)
    if dtype is not None:
        out["dtype"] = jax.numpy.dtype(dtype).name
    return out


def save_model(directory: str, config, params) -> None:
    """One-shot weights-only export (serving artifact): step 0 with the
    model config embedded as JSON meta."""
    manager = CheckpointManager(directory, max_to_keep=1)
    manager.save(0, params, meta={"model_config": config_meta(config)})
    manager.close()


def load_model(directory: str, config_cls=None):
    """Load (config, params) from a weights export. ``config_cls``
    defaults to the jax-local LlamaConfig."""
    if config_cls is None:
        from langstream_tpu.providers.jax_local.model import LlamaConfig

        config_cls = LlamaConfig
    manager = CheckpointManager(directory)
    restored = manager.restore()
    manager.close()
    meta = restored.get("meta") or {}
    config = config_cls.from_dict(meta.get("model_config", {}))
    return config, restored["params"]
