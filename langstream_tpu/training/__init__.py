"""Fine-tuning: sharded train step over the dp/fsdp/tp mesh.

The reference has no training at all (models are remote APIs); this
subsystem exists because a TPU-native framework that serves models should
also fine-tune them in place (LoRA/full-parameter next-token training on
the same sharded model definition the engine serves).
"""

from langstream_tpu.training.trainer import Trainer, TrainConfig

__all__ = ["Trainer", "TrainConfig"]
