"""Sharded next-token training on the Llama model definition.

One jitted train step over a ``dp × fsdp × tp`` mesh:

- parameters are placed by the same logical-axis rules the serving engine
  uses (``parallel.mesh.DEFAULT_RULES``: tp shards heads/mlp, fsdp shards
  the embed axis — ZeRO-3 style);
- the batch shards over dp (and fsdp, which also acts as a data axis for
  the forward);
- optimizer state mirrors parameter shardings (optax adamw);
- gradients are averaged by XLA's automatic collectives — no explicit
  psum: sharding constraints on inputs/outputs drive the partitioner.

``jax.checkpoint`` wraps the layer scan to rematerialize activations —
trading FLOPs for HBM, the standard TPU training recipe.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from langstream_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    logical_to_physical,
    param_shardings,
    shard_params,
    validate_mesh,
)
from langstream_tpu.providers.jax_local import model as model_lib


@dataclasses.dataclass
class TrainConfig:
    learning_rate: float = 1e-5
    weight_decay: float = 0.0
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    remat: bool = True
    # MoE load-balancing loss weight (ignored for dense models)
    moe_aux_weight: float = 0.01
    # GPipe microbatches per step on pp>1 meshes (default: 2 per stage —
    # bubble fraction (pp-1)/(M+pp-1) ≈ 1/3; raise for bigger batches)
    num_microbatches: int = 0


def loss_fn(config, params, tokens, mask, freqs, moe_aux_weight):
    """Causal next-token cross-entropy (mean over valid positions), plus
    the router load-balancing aux loss for MoE models."""
    from langstream_tpu.ops.losses import causal_ce_loss

    aux = 0.0
    if config.num_experts:
        logits, aux = model_lib.forward(
            config, params, tokens, mask=mask, freqs=freqs, with_aux=True
        )
        aux = moe_aux_weight * aux
    else:
        logits = model_lib.forward(config, params, tokens, mask=mask, freqs=freqs)
    return causal_ce_loss(logits, tokens, mask) + aux


class Trainer:
    def __init__(
        self,
        model_config: model_lib.LlamaConfig,
        params: Dict[str, Any],
        *,
        mesh_config: Optional[MeshConfig] = None,
        train_config: Optional[TrainConfig] = None,
    ) -> None:
        self.model_config = model_config
        self.train_config = train_config or TrainConfig()
        validate_mesh(
            mesh_config or MeshConfig(),
            num_heads=model_config.num_heads,
            num_kv_heads=model_config.num_kv_heads,
            intermediate_size=model_config.intermediate_size,
            num_experts=model_config.num_experts,
            num_layers=model_config.num_layers,
            allow_pp=True,
        )
        self.mesh = build_mesh(
            mesh_config or MeshConfig(),
            devices=jax.devices()[: (mesh_config or MeshConfig()).size],
        )
        axes = model_lib.logical_axes(model_config)
        with self.mesh:
            self.params = shard_params(params, axes, self.mesh)
        self._param_shardings = param_shardings(axes, self.mesh)
        self.freqs = model_lib.model_freqs(model_config)

        tc = self.train_config
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(tc.grad_clip),
            optax.adamw(
                tc.learning_rate, b1=tc.b1, b2=tc.b2,
                weight_decay=tc.weight_decay,
            ),
        )
        with self.mesh:
            self.opt_state = jax.jit(
                self.optimizer.init,
            )(self.params)
        self._step_fn = None
        self.step = 0

    def _data_sharding(self):
        from jax.sharding import NamedSharding

        return NamedSharding(
            self.mesh, logical_to_physical(("batch", None), self.mesh)
        )

    def _build_step(self):
        config = self.model_config
        freqs = self.freqs
        optimizer = self.optimizer
        remat = self.train_config.remat

        aux_w = self.train_config.moe_aux_weight

        pp = self.mesh.shape.get("pp", 1)
        if pp > 1:
            from langstream_tpu.parallel.pipeline import pipelined_loss_fn

            num_mb = self.train_config.num_microbatches or 2 * pp
            mesh = self.mesh

            def base_loss(p, t, m):
                return pipelined_loss_fn(
                    config, p, t, m, freqs, mesh, num_mb, moe_aux_weight=aux_w
                )
        else:

            def base_loss(p, t, m):
                return loss_fn(config, p, t, m, freqs, aux_w)

        def compute_loss(params, tokens, mask):
            if remat:
                fn = jax.checkpoint(
                    base_loss,
                    policy=jax.checkpoint_policies.nothing_saveable,
                )
                return fn(params, tokens, mask)
            return base_loss(params, tokens, mask)

        @functools.partial(
            jax.jit,
            donate_argnums=(0, 1),
        )
        def train_step(params, opt_state, tokens, mask):
            loss, grads = jax.value_and_grad(compute_loss)(params, tokens, mask)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return train_step

    def train_step(self, tokens, mask) -> float:
        """Run one step; tokens/mask are host arrays [B, T]."""
        if self._step_fn is None:
            self._step_fn = self._build_step()
        sharding = self._data_sharding()
        with self.mesh:
            tokens = jax.device_put(jnp.asarray(tokens, dtype=jnp.int32), sharding)
            mask = jax.device_put(jnp.asarray(mask, dtype=bool), sharding)
            self.params, self.opt_state, loss = self._step_fn(
                self.params, self.opt_state, tokens, mask
            )
        self.step += 1
        return float(loss)

    # ------------------------------------------------------------------ #
    # checkpoint / resume
    # ------------------------------------------------------------------ #
    def save_checkpoint(self, manager, *, wait: bool = False) -> None:
        """Queue an async save of (params, opt_state, step) through a
        ``training.checkpoint.CheckpointManager``."""
        from langstream_tpu.training.checkpoint import config_meta

        manager.save(
            self.step, self.params, self.opt_state,
            meta={"model_config": config_meta(self.model_config)},
        )
        if wait:
            manager.wait()

    def _opt_state_shardings(self):
        """Target shardings for restored optimizer state: array leaves
        (mu/nu) shard like the same-shaped parameter, scalars (step
        counts) replicate over the mesh. Needed because freshly-init'd
        opt_state leaves are *uncommitted* (jit may place them anywhere)
        while orbax restores *committed* single-device arrays that would
        otherwise conflict with the mesh-sharded params inside jit."""
        from jax.sharding import NamedSharding, PartitionSpec

        shape_to_sharding = {}
        for leaf in jax.tree.leaves(self.params):
            shape_to_sharding.setdefault(leaf.shape, leaf.sharding)
        replicated = NamedSharding(self.mesh, PartitionSpec())
        return jax.tree.map(
            lambda leaf: shape_to_sharding.get(leaf.shape, replicated),
            self.opt_state,
        )

    def restore_checkpoint(self, manager, step=None) -> int:
        """Restore params/opt_state/step in place (arrays land on this
        trainer's shardings). Returns the restored step."""
        # abstract targets with explicit shardings: orbax restores each
        # leaf straight onto the mesh, no post-hoc copies
        opt_target = jax.tree.map(
            lambda leaf, sharding: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=sharding
            ),
            self.opt_state, self._opt_state_shardings(),
        )
        restored = manager.restore(
            step,
            params_target=self.params,
            opt_state_target=opt_target,
        )
        self.params = restored["params"]
        if restored.get("opt_state") is not None:
            self.opt_state = restored["opt_state"]
        self.step = restored["step"]
        return self.step
