"""Command-line interface (reference analogue: ``langstream-cli`` picocli
commands — apps run / gateway chat / docs)."""
