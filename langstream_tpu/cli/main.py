"""``langstream-tpu`` CLI.

Reference parity (``langstream-cli/src/main/java/ai/langstream/cli/commands/RootCmd.java:38``):

- ``apps run <dir>``     — the ``langstream docker run`` local path
  (``docker/LocalRunApplicationCmd.java:56``): run the whole app in-process
  with the in-memory broker + gateway.
- ``apps plan <dir>``    — print the compiled execution plan.
- ``gateway chat|produce|consume`` — WebSocket client commands
  (``gateway/ChatGatewayCmd.java:39``).
- ``docs``               — agent-type documentation listing.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import uuid
from typing import List, Optional


def _parse_params(values: List[str]) -> dict:
    out = {}
    for item in values or []:
        if "=" not in item:
            raise SystemExit(f"bad parameter {item!r}: expected name=value")
        name, _, value = item.partition("=")
        out[name] = value
    return out


# ---------------------------------------------------------------------- #
# apps
# ---------------------------------------------------------------------- #
async def _apps_run(args, ui: bool = False) -> None:
    from langstream_tpu.gateway import GatewayServer
    from langstream_tpu.runtime.local import run_application

    runner = await run_application(
        args.app_dir, instance_file=args.instance, secrets_file=args.secrets
    )
    print(f"application {runner.application.application_id} running:")
    for node in runner.plan.agents:
        print(
            f"  agent {node.id}: {node.input_topic or '(source)'} -> "
            f"{node.output_topic or '(sink)'}"
        )
    http = None
    if getattr(args, "http_port", -1) >= 0:
        from langstream_tpu.runtime.pod import AgentHttpServer

        def _engine_module():
            import sys

            return sys.modules.get(
                "langstream_tpu.providers.jax_local.engine"
            )

        http = AgentHttpServer(
            info=runner.info,
            metrics=runner.metrics,
            gauges=lambda: (
                _engine_module().engines_snapshot()
                if _engine_module() else {}
            ),
            histograms=lambda: (
                _engine_module().engines_histograms()
                if _engine_module() else {}
            ),
            port=args.http_port,
            host="127.0.0.1",
        )
        try:
            await http.start()
            http.ready = True
            print(f"metrics on http://127.0.0.1:{http.port}/metrics")
        except OSError as error:
            print(f"(metrics server disabled: {error})")
            http = None
    gateway = None
    if runner.application.gateways:
        gateway = GatewayServer(port=args.gateway_port)
        gateway.register_local_runner(runner, tenant=args.tenant)
        await gateway.start()
        print(f"gateway on ws://127.0.0.1:{args.gateway_port}/v1/...")
        ui_url = (
            f"http://127.0.0.1:{args.gateway_port}/ui/{args.tenant}/"
            f"{runner.application.application_id}"
        )
        print(f"ui: {ui_url}")
        if ui:
            import webbrowser

            try:
                webbrowser.open(ui_url)
            except Exception:  # noqa: BLE001 — headless is fine
                pass
    elif ui:
        print("no gateways declared; the UI needs at least one")
    try:
        await runner.join()
    except KeyboardInterrupt:
        pass
    finally:
        if gateway is not None:
            await gateway.stop()
        if http is not None:
            await http.stop()
        await runner.stop()


def _apps_plan(args) -> None:
    from langstream_tpu.compiler import build_application, build_execution_plan

    application = build_application(
        args.app_dir, instance_file=args.instance, secrets_file=args.secrets
    )
    plan = build_execution_plan(application)
    out = {
        "topics": {
            name: {"partitions": t.partitions, "implicit": t.implicit}
            for name, t in plan.topics.items()
        },
        "agents": [
            {
                "id": node.id,
                "input": node.input_topic,
                "output": node.output_topic,
                "source": node.source.agent_type if node.source else None,
                "processors": [p.agent_type for p in node.processors],
                "sink": node.sink.agent_type if node.sink else None,
                "service": node.service.agent_type if node.service else None,
                "parallelism": node.resources.parallelism,
            }
            for node in plan.agents
        ],
        "gateways": [g.id for g in application.gateways],
    }
    print(json.dumps(out, indent=2))


# ---------------------------------------------------------------------- #
# gateway client
# ---------------------------------------------------------------------- #
def _gateway_url(args, kind: str) -> str:
    base = args.url.rstrip("/")
    url = f"{base}/v1/{kind}/{args.tenant}/{args.application}/{args.gateway}"
    query = [f"param:{k}={v}" for k, v in _parse_params(args.param).items()]
    if args.credentials:
        query.append(f"credentials={args.credentials}")
    if query:
        url += "?" + "&".join(query)
    return url


async def _gateway_chat(args) -> None:
    import websockets

    session = args.session or uuid.uuid4().hex
    if not any(p.startswith("session-id=") for p in (args.param or [])):
        args.param = (args.param or []) + [f"session-id={session}"]
    url = _gateway_url(args, "chat")
    print(f"connected to {url}")
    async with websockets.connect(url) as ws:

        async def reader():
            async for frame in ws:
                message = json.loads(frame)
                record = message.get("record", {})
                value = record.get("value")
                headers = record.get("headers", {})
                if headers.get("stream-last-message") == "true":
                    print(f"\n< {value}" if value else "")
                elif headers.get("stream-index"):
                    print(value, end="", flush=True)
                else:
                    print(f"< {value}")

        reader_task = asyncio.ensure_future(reader())
        loop = asyncio.get_running_loop()
        try:
            while True:
                line = await loop.run_in_executor(None, sys.stdin.readline)
                if not line:
                    break
                await ws.send(json.dumps({"value": line.strip()}))
        finally:
            reader_task.cancel()


async def _gateway_produce(args) -> None:
    import websockets

    url = _gateway_url(args, "produce")
    async with websockets.connect(url) as ws:
        await ws.send(
            json.dumps({"key": args.key, "value": args.value, "headers": {}})
        )
        print(await ws.recv())


async def _gateway_consume(args) -> None:
    import websockets

    url = _gateway_url(args, "consume")
    if args.position:
        url += ("&" if "?" in url else "?") + f"option:position={args.position}"
    async with websockets.connect(url) as ws:
        async for frame in ws:
            print(frame)


# ---------------------------------------------------------------------- #
# control-plane commands (reference: RootCmd.java:38 apps/tenants/profiles)
# ---------------------------------------------------------------------- #
def _admin(args):
    from langstream_tpu.admin.client import client_from_profile

    return client_from_profile(
        getattr(args, "profile", None),
        url=getattr(args, "api_url", None),
        tenant=getattr(args, "cp_tenant", None),
        token=getattr(args, "token", None),
    )


def _print_json(doc) -> None:
    print(json.dumps(doc, indent=2))


async def _apps_deploy(args, update: bool) -> None:
    client = _admin(args)
    instance_yaml = secrets_yaml = None
    if args.instance:
        with open(args.instance) as handle:
            instance_yaml = handle.read()
    if args.secrets:
        with open(args.secrets) as handle:
            secrets_yaml = handle.read()
    result = await client.deploy_application_directory(
        args.app_id, args.app_dir,
        instance_yaml=instance_yaml, secrets_yaml=secrets_yaml,
        update=update, dry_run=args.dry_run,
    )
    _print_json(result)


async def _apps_get(args) -> None:
    _print_json(await _admin(args).get_application(args.app_id))


async def _apps_list(args) -> None:
    _print_json(await _admin(args).list_applications())


async def _apps_delete(args) -> None:
    _print_json(await _admin(args).delete_application(args.app_id))


async def _apps_logs(args) -> None:
    print(await _admin(args).get_logs(args.app_id), end="")


async def _apps_download(args) -> None:
    data = await _admin(args).download_code(args.app_id)
    target = args.output or f"{args.app_id}.zip"
    with open(target, "wb") as handle:
        handle.write(data)
    print(f"wrote {len(data)} bytes to {target}")


async def _archetypes_cmd(args) -> None:
    client = _admin(args)
    if args.archetypes_command == "list":
        _print_json(await client.list_archetypes())
    elif args.archetypes_command == "get":
        _print_json(await client.get_archetype(args.archetype_id))
    elif args.archetypes_command == "deploy":
        _print_json(await client.deploy_from_archetype(
            args.archetype_id, args.app_id, _parse_params(args.param)
        ))


async def _tenants_cmd(args) -> None:
    client = _admin(args)
    if args.tenants_command == "list":
        _print_json(await client.list_tenants())
    elif args.tenants_command == "get":
        _print_json(await client.get_tenant(args.name))
    elif args.tenants_command in ("put", "create"):
        _print_json(await client.put_tenant(args.name))
    elif args.tenants_command == "delete":
        _print_json(await client.delete_tenant(args.name))


def _profiles_cmd(args) -> None:
    from langstream_tpu.admin.client import load_profiles, save_profiles

    config = load_profiles()
    if args.profiles_command == "list":
        _print_json({
            "current": config.get("current"),
            "profiles": config.get("profiles", {}),
        })
    elif args.profiles_command == "create" or args.profiles_command == "update":
        # update merges: omitted flags keep their stored values
        existing = config.get("profiles", {}).get(args.name, {})
        profile = dict(existing) if args.profiles_command == "update" else {}
        if args.api_url:
            profile["webServiceUrl"] = args.api_url
        if args.cp_tenant:
            profile["tenant"] = args.cp_tenant
        elif "tenant" not in profile:
            profile["tenant"] = "default"
        if args.token:
            profile["token"] = args.token
        config.setdefault("profiles", {})[args.name] = profile
        if args.set_current or config.get("current") is None:
            config["current"] = args.name
        save_profiles(config)
        print(f"profile {args.name} saved")
    elif args.profiles_command == "get":
        profile = config.get("profiles", {}).get(args.name)
        if profile is None:
            raise SystemExit(f"unknown profile {args.name!r}")
        _print_json({args.name: profile})
    elif args.profiles_command == "delete":
        config.get("profiles", {}).pop(args.name, None)
        if config.get("current") == args.name:
            config["current"] = None
        save_profiles(config)
        print(f"profile {args.name} deleted")
    elif args.profiles_command == "set-current":
        if args.name not in config.get("profiles", {}):
            raise SystemExit(f"unknown profile {args.name!r}")
        config["current"] = args.name
        save_profiles(config)
        print(f"current profile: {args.name}")


# ---------------------------------------------------------------------- #
# broker
# ---------------------------------------------------------------------- #
async def _broker_serve(args) -> None:
    from langstream_tpu.topics.log.server import serve

    server = await serve(args.directory, host=args.host, port=args.port)
    print(f"tpulog broker serving {args.directory} on {server.address}")
    try:
        await asyncio.Event().wait()
    finally:
        await server.close()


# ---------------------------------------------------------------------- #
# observability: trace merge + live engine top
# ---------------------------------------------------------------------- #
def _trace_cmd(args) -> None:
    """Merge per-pod Chrome-trace dumps (LANGSTREAM_TRACE_DIR) into one
    Perfetto-loadable timeline, optionally filtered to one trace id."""
    from langstream_tpu.runtime.tracing import run_trace_merge

    for line in run_trace_merge(
        args.paths, output=args.output, trace_id=args.trace_id,
        list_ids=args.list,
    ):
        print(line)


def _journey_cmd(args) -> None:
    """Join fleet-wide flight-recorder artifacts by trace id into
    per-request journey waterfalls, per-stage percentiles, and SLO
    blame tables (docs/observability.md, "Request journeys")."""
    from langstream_tpu.runtime.journey import run_journey

    for line in run_journey(
        args.paths, trace_id=args.trace_id,
        slo_ttft_ms=args.slo_ttft_ms, slo_tpot_ms=args.slo_tpot_ms,
        as_json=args.json, waterfalls=args.waterfalls,
    ):
        print(line)


async def _profile_cmd(args) -> None:
    """Trigger an on-demand profiler capture on a serving process via
    its guarded ``/debug/profile`` endpoint (runner pod :8080, serve
    :8000) and print the artifact directory."""
    import aiohttp

    url = args.url.rstrip("/")
    if not url.endswith("/debug/profile"):
        url += "/debug/profile"
    timeout = aiohttp.ClientTimeout(total=args.seconds + 60)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        async with session.get(
            url, params={"seconds": args.seconds}
        ) as response:
            if response.status == 409:
                raise SystemExit(
                    "capture already in progress on the target "
                    "(one at a time); retry when it finishes"
                )
            if response.status != 200:
                # body may be anything (a proxy's HTML, an older
                # server's 404 text) — report it raw, don't parse it
                raise SystemExit(
                    f"capture failed ({response.status}): "
                    f"{(await response.text())[:300]}"
                )
            body = await response.json(content_type=None)
    print(f"profile ({args.seconds:.0f}s) -> {body['path']}")
    print("  inspect with TensorBoard's profile plugin or xprof; "
          "device_memory.json holds the HBM snapshot")


async def _top_cmd(args) -> None:
    """Poll a /metrics endpoint and render a live engine table
    (occupancy, step time, token throughput from poll deltas) plus an
    SLO panel (TTFT/TPOT percentiles vs targets, burn rates) when the
    target exports SLO gauges."""
    import time as _time

    import aiohttp

    from langstream_tpu.api.metrics import (
        parse_prometheus_text,
        quantile_from_buckets,
    )

    previous_tokens: Optional[float] = None
    previous_at: Optional[float] = None
    iteration = 0
    async with aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=5)
    ) as session:
        while True:
            iteration += 1
            try:
                async with session.get(args.url) as response:
                    text = await response.text()
                metrics = parse_prometheus_text(text)
            except (
                aiohttp.ClientError, asyncio.TimeoutError, ValueError,
            ) as error:
                print(f"[{args.url}] scrape failed: {error}")
                metrics = None
            if metrics is not None:

                def gauge(name: str, default: float = 0.0) -> float:
                    samples = metrics.get(name)
                    return samples[0][1] if samples else default

                now = _time.monotonic()
                tokens = gauge("jax_engine_tokens_generated")
                tok_s = 0.0
                if previous_at is not None and now > previous_at:
                    tok_s = max(0.0, tokens - (previous_tokens or 0.0)) / (
                        now - previous_at
                    )
                previous_tokens, previous_at = tokens, now
                p50 = quantile_from_buckets(
                    metrics.get(
                        "jax_engine_decode_step_seconds_bucket", []
                    ),
                    0.5,
                )
                rows = [
                    ("slot occupancy",
                     f"{gauge('jax_engine_slot_occupancy'):7.1%}"),
                    ("decode ms/step (mean)",
                     f"{gauge('jax_engine_decode_ms_per_step'):9.2f}"),
                    ("decode ms/step (p50 interp)",
                     "      n/a" if p50 is None else f"{p50 * 1e3:9.2f}"),
                    ("output tok/s (poll delta)", f"{tok_s:9.1f}"),
                    ("tokens generated", f"{tokens:9.0f}"),
                    ("decode steps",
                     f"{gauge('jax_engine_decode_steps'):9.0f}"),
                    ("prefix KV rows reused",
                     f"{gauge('jax_engine_prefix_tokens_reused'):9.0f}"),
                    ("session hits",
                     f"{gauge('jax_engine_session_hits'):9.0f}"),
                ]
                if "jax_engine_mfu" in metrics:
                    rows.append(("MFU / MBU (roofline)",
                                 f"{gauge('jax_engine_mfu'):7.1%} / "
                                 f"{gauge('jax_engine_mbu'):5.1%}"))
                if "jax_engine_goodput_ratio" in metrics:
                    rows.append(("goodput (useful/total tokens)",
                                 f"{gauge('jax_engine_goodput_ratio'):7.1%}"))
                if "spec_tokens_drafted_total" in metrics:
                    # speculative decoding: drafted vs verify-accepted
                    # candidates — a collapsed rate means the workload
                    # has no self-repetition for the drafter to mine
                    rows.append((
                        "spec accept (drafted tokens)",
                        f"{gauge('spec_acceptance_rate'):7.1%} "
                        f"({gauge('spec_tokens_drafted_total'):.0f})",
                    ))
                stamp = _time.strftime("%H:%M:%S")
                print(f"-- langstream-tpu top  {args.url}  {stamp} --")
                if tokens or gauge("jax_engine_decode_steps"):
                    for label, value in rows:
                        print(f"  {label:28s} {value}")
                else:
                    print("  engine idle (no decode activity yet)")
                # SLO panel: measured percentiles (interpolated from the
                # exported buckets) against the configured targets, plus
                # the multi-window burn rates the engine derives from
                # the same histograms
                slo_rows = []
                for key, label in (("ttft", "TTFT"), ("tpot", "TPOT")):
                    target = metrics.get(
                        f"jax_engine_slo_{key}_p95_target_ms"
                    )
                    if not target:
                        continue
                    target_ms = target[0][1]
                    p95 = quantile_from_buckets(
                        metrics.get(
                            f"jax_engine_{key}_seconds_bucket", []
                        ),
                        0.95,
                    )
                    p95_ms = None if p95 is None else p95 * 1e3

                    def burn(window: str) -> str:
                        # absent gauge = no sample landed in the window
                        # yet — render n/a, NOT a perfect-looking 0.00x
                        sample = metrics.get(
                            f"jax_engine_slo_{key}_burn_rate_{window}"
                        )
                        return (
                            f"{sample[0][1]:5.2f}x" if sample
                            else "  n/a"
                        )

                    status = (
                        "  n/a" if p95_ms is None
                        else ("BREACH" if p95_ms > target_ms else "ok")
                    )
                    measured = (
                        "     n/a" if p95_ms is None else f"{p95_ms:8.1f}"
                    )
                    # honest labeling: the p95 (and its ok/BREACH) is
                    # computed from lifetime-cumulative buckets — a past
                    # breach lingers there; the burn rates are the
                    # windowed "is it breaching NOW" signal
                    slo_rows.append(
                        f"  {label} p95(life) {measured} ms  "
                        f"(target {target_ms:7.1f} ms)  "
                        f"burn 5m {burn('5m')} / 1h {burn('1h')}  "
                        f"[{status}]"
                    )
                if slo_rows:
                    print("  -- SLO --")
                    for row in slo_rows:
                        print(row)
                # journey stage panel: per-stage latency histograms
                # from the request-journey ledger — rendered only for
                # stages that have observed at least one sample
                stage_rows = []
                for stage in (
                    "route", "queue", "admit", "prefill",
                    "handoff_export", "handoff_transit",
                    "handoff_import", "decode", "finish",
                ):
                    base = f"jax_engine_journey_{stage}_seconds"
                    count_samples = metrics.get(f"{base}_count")
                    if not count_samples or not count_samples[0][1]:
                        continue
                    count = count_samples[0][1]
                    buckets = metrics.get(f"{base}_bucket", [])
                    p50s = quantile_from_buckets(buckets, 0.5)
                    p95s = quantile_from_buckets(buckets, 0.95)
                    sum_samples = metrics.get(f"{base}_sum")
                    total = sum_samples[0][1] if sum_samples else 0.0

                    def ms(value: Optional[float]) -> str:
                        return (
                            "     n/a" if value is None
                            else f"{value * 1e3:8.1f}"
                        )

                    stage_rows.append(
                        f"    {stage:16s} n={count:6.0f}  "
                        f"p50 {ms(p50s)} ms  p95 {ms(p95s)} ms  "
                        f"total {total:8.2f} s"
                    )
                if stage_rows:
                    print("  -- journey stages --")
                    for row in stage_rows:
                        print(row)
                # fleet panel: rendered when the target serves fleet
                # gauges (a gateway with a registered FleetRouter /
                # FleetController) — per-replica queue depth + state,
                # the affinity hit rate, and current/target replicas
                if "fleet_replicas_current" in metrics or (
                    "fleet_replica_queue_depth" in metrics
                ):
                    # a bare FleetRouter (no controller) exports only
                    # the known-replica count
                    current = gauge(
                        "fleet_replicas_current",
                        gauge("fleet_replicas_known"),
                    )
                    target_samples = metrics.get("fleet_replicas_target")
                    target = (
                        f"{target_samples[0][1]:.0f}" if target_samples
                        else "n/a"
                    )
                    print(
                        f"  -- fleet --  replicas {current:.0f} "
                        f"(target {target}, "
                        f"routable {gauge('fleet_replicas_routable'):.0f})"
                    )
                    if "fleet_affinity_hit_rate" in metrics:
                        routed = {
                            labels.get("policy", "?"): value
                            for labels, value in metrics.get(
                                "fleet_routed_total", []
                            )
                        }
                        routed_txt = " ".join(
                            f"{policy}={count:.0f}"
                            for policy, count in sorted(routed.items())
                            if count
                        )
                        print(
                            f"  affinity hit rate "
                            f"{gauge('fleet_affinity_hit_rate'):7.1%}  "
                            f"(prefix tokens matched "
                            f"{gauge('fleet_prefix_match_tokens_total'):.0f}"
                            f"; routed {routed_txt or '0'})"
                        )
                    states = {
                        labels.get("replica", "?"): labels.get("state", "?")
                        for labels, value in metrics.get(
                            "fleet_replica_state", []
                        )
                        if value
                    }
                    for labels, depth in sorted(
                        metrics.get("fleet_replica_queue_depth", []),
                        key=lambda s: s[0].get("replica", ""),
                    ):
                        replica = labels.get("replica", "?")
                        state = states.get(replica, "?")
                        print(
                            f"    {replica:20s} queue {depth:5.0f}  "
                            f"[{state}]"
                        )
            if args.count and iteration >= args.count:
                break
            await asyncio.sleep(args.interval)


# ---------------------------------------------------------------------- #
# docs
# ---------------------------------------------------------------------- #
def _docs(args) -> None:
    import json as _json

    from langstream_tpu.model.docs import all_docs, generate_docs_model, get_doc
    from langstream_tpu.runtime.registry import _ensure_builtin_loaded

    _ensure_builtin_loaded()
    agent_type = getattr(args, "agent_type", None)
    as_json = getattr(args, "json", False)
    if agent_type:
        doc = get_doc(agent_type)
        if doc is None:
            raise SystemExit(f"no documentation for agent type {agent_type!r}")
        if as_json:
            print(_json.dumps(doc.to_dict(), indent=2))
            return
        print(f"{doc.agent_type} ({doc.category})")
        print(f"  {doc.description}")
        for prop in doc.properties:
            req = " (required)" if prop.required else ""
            default = f" [default: {prop.default}]" if prop.default is not None else ""
            print(f"  - {prop.name}: {prop.type}{req}{default}")
            if prop.description:
                print(f"      {prop.description}")
            if prop.choices:
                print(f"      choices: {', '.join(prop.choices)}")
        return
    if as_json:
        print(_json.dumps(generate_docs_model(), indent=2))
        return
    print("agent types (docs <type> for details):")
    for name, doc in sorted(all_docs().items()):
        print(f"  {name:28s} {doc.category:10s} {doc.description}")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="langstream-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    def add_admin_flags(cmd) -> None:
        cmd.add_argument("--api-url", default=None,
                         help="control-plane URL (or LANGSTREAM_API_URL)")
        cmd.add_argument("--cp-tenant", default=None,
                         help="control-plane tenant (default from profile)")
        cmd.add_argument("--token", default=None)
        cmd.add_argument("--profile", default=None)

    apps = sub.add_parser("apps", help="application commands")
    apps_sub = apps.add_subparsers(dest="apps_command", required=True)
    for name in ("run", "plan", "ui"):
        cmd = apps_sub.add_parser(
            name,
            help="run the app locally and open the web UI"
            if name == "ui" else None,
        )
        cmd.add_argument("app_dir")
        cmd.add_argument("-i", "--instance", default=None)
        cmd.add_argument("-s", "--secrets", default=None)
        if name in ("run", "ui"):
            cmd.add_argument("--gateway-port", type=int, default=8091)
            cmd.add_argument("--tenant", default="default")
            cmd.add_argument(
                "--http-port", type=int, default=8080,
                help="/info + /metrics port (-1 disables)",
            )
    # control-plane application commands (reference: apps deploy/update/...)
    for name in ("deploy", "update"):
        cmd = apps_sub.add_parser(name, help=f"{name} via the control plane")
        cmd.add_argument("app_id")
        cmd.add_argument("app_dir")
        cmd.add_argument("-i", "--instance", default=None)
        cmd.add_argument("-s", "--secrets", default=None)
        cmd.add_argument("--dry-run", action="store_true")
        add_admin_flags(cmd)
    for name in ("get", "delete", "logs"):
        cmd = apps_sub.add_parser(name)
        cmd.add_argument("app_id")
        add_admin_flags(cmd)
    cmd = apps_sub.add_parser("list")
    add_admin_flags(cmd)
    cmd = apps_sub.add_parser("download", help="download the app's code zip")
    cmd.add_argument("app_id")
    cmd.add_argument("-o", "--output", default=None)
    add_admin_flags(cmd)

    archetypes = sub.add_parser("archetypes", help="application archetypes")
    archetypes_sub = archetypes.add_subparsers(
        dest="archetypes_command", required=True
    )
    cmd = archetypes_sub.add_parser("list")
    add_admin_flags(cmd)
    cmd = archetypes_sub.add_parser("get")
    cmd.add_argument("archetype_id")
    add_admin_flags(cmd)
    cmd = archetypes_sub.add_parser(
        "deploy", help="deploy an app from an archetype"
    )
    cmd.add_argument("archetype_id")
    cmd.add_argument("app_id")
    cmd.add_argument("-p", "--param", action="append", default=[],
                     help="archetype parameter name=value")
    add_admin_flags(cmd)

    tenants = sub.add_parser("tenants", help="tenant administration")
    tenants_sub = tenants.add_subparsers(dest="tenants_command", required=True)
    for name in ("list", "get", "put", "create", "delete"):
        cmd = tenants_sub.add_parser(name)
        if name != "list":
            cmd.add_argument("name")
        add_admin_flags(cmd)

    profiles = sub.add_parser("profiles", help="control-plane profiles")
    profiles_sub = profiles.add_subparsers(
        dest="profiles_command", required=True
    )
    for name in ("create", "update"):
        cmd = profiles_sub.add_parser(name)
        cmd.add_argument("name")
        cmd.add_argument("--api-url", required=name == "create")
        cmd.add_argument("--cp-tenant", default=None)
        cmd.add_argument("--token", default=None)
        cmd.add_argument("--set-current", action="store_true")
    for name in ("get", "delete", "set-current"):
        cmd = profiles_sub.add_parser(name)
        cmd.add_argument("name")
    profiles_sub.add_parser("list")

    gateway = sub.add_parser("gateway", help="gateway client commands")
    gateway_sub = gateway.add_subparsers(dest="gateway_command", required=True)
    for name in ("chat", "produce", "consume"):
        cmd = gateway_sub.add_parser(name)
        cmd.add_argument("-u", "--url", default="ws://127.0.0.1:8091")
        cmd.add_argument("-t", "--tenant", default="default")
        cmd.add_argument("-a", "--application", required=True)
        cmd.add_argument("-g", "--gateway", required=True)
        cmd.add_argument("-p", "--param", action="append", default=[])
        cmd.add_argument("--credentials", default=None)
        if name == "chat":
            cmd.add_argument("--session", default=None)
        if name == "produce":
            cmd.add_argument("-k", "--key", default=None)
            cmd.add_argument("-v", "--value", required=True)
        if name == "consume":
            cmd.add_argument("--position", default=None)

    broker = sub.add_parser("broker", help="serve a durable tpulog broker")
    broker.add_argument("directory", help="broker data directory")
    broker.add_argument("--host", default="127.0.0.1")
    broker.add_argument("--port", type=int, default=4551)

    docs = sub.add_parser("docs", help="agent-type documentation")
    docs.add_argument("agent_type", nargs="?", help="show one agent's docs")
    docs.add_argument("--json", action="store_true", help="emit the JSON doc model")

    trace = sub.add_parser(
        "trace",
        help="merge per-pod Chrome-trace dumps (LANGSTREAM_TRACE_DIR) "
             "into one Perfetto timeline",
    )
    trace.add_argument(
        "paths", nargs="+",
        help="trace dump files and/or directories of *.json dumps",
    )
    trace.add_argument("-o", "--output", default="merged_trace.json")
    trace.add_argument(
        "--trace-id", default=None,
        help="keep only spans of this request (langstream-trace-id)",
    )
    trace.add_argument(
        "--list", action="store_true",
        help="list trace ids and the components each one crossed",
    )

    journey = sub.add_parser(
        "journey",
        help="join fleet-wide flight artifacts (LANGSTREAM_FLIGHT_DIR) "
             "by trace id into per-request waterfalls, per-stage "
             "p50/p95, and SLO blame",
    )
    journey.add_argument(
        "paths", nargs="+",
        help="flight_*.jsonl artifacts and/or directories of them "
             "(pass every replica's artifact dir to join "
             "cross-replica journeys)",
    )
    journey.add_argument(
        "--trace-id", default=None,
        help="render the full stage waterfall of one request",
    )
    journey.add_argument(
        "--slo-ttft-ms", type=float, default=0.0,
        help="TTFT SLO for blame attribution (0 = no TTFT blame)",
    )
    journey.add_argument(
        "--slo-tpot-ms", type=float, default=0.0,
        help="per-token TPOT SLO for blame attribution "
             "(0 = no TPOT blame)",
    )
    journey.add_argument(
        "--waterfalls", type=int, default=3,
        help="how many slowest-request waterfalls to render",
    )
    journey.add_argument(
        "--json", action="store_true",
        help="emit the joined journeys as JSON instead of tables",
    )

    top = sub.add_parser(
        "top",
        help="poll a /metrics endpoint and render a live engine "
             "occupancy/step-time table",
    )
    top.add_argument(
        "url", nargs="?", default="http://127.0.0.1:8000/metrics",
        help="scrape URL (runner pod :8080, serve :8000, gateway :8091)",
    )
    top.add_argument("--interval", type=float, default=2.0)
    top.add_argument(
        "--count", type=int, default=0,
        help="stop after N polls (0 = until interrupted)",
    )

    check = sub.add_parser(
        "check",
        help="static analysis over the runtime: lock-discipline + "
             "jit-hazard AST passes and the compiled-HLO invariant "
             "matrix; non-zero exit on unsuppressed findings "
             "(docs/analysis.md)",
    )
    from langstream_tpu.analysis.check import build_parser as _check_parser

    _check_parser(check)

    profile = sub.add_parser(
        "profile",
        help="trigger an on-demand device-profiler capture on a serving "
             "process (guarded /debug/profile endpoint; one at a time)",
    )
    profile.add_argument(
        "url", nargs="?", default="http://127.0.0.1:8000",
        help="server base URL (runner pod :8080, serve :8000) or the "
             "full /debug/profile URL",
    )
    profile.add_argument(
        "--seconds", type=float, default=3.0,
        help="capture window (everything the devices run in it lands "
             "in the trace)",
    )

    # pod entry points (invoked by the deployer's generated manifests;
    # reference: AgentRunnerStarter.java:39, RuntimeDeployer.java:40,
    # ApplicationSetupRunner.java:40)
    runner = sub.add_parser(
        "agent-runner", help="run one plan node from a mounted pod config"
    )
    runner.add_argument("--config", required=True,
                        help="path to pod-configuration.json")
    runner.add_argument("--http-port", type=int, default=8080,
                        help="/info + /metrics port (0 = kernel-assigned)")

    download = sub.add_parser(
        "code-download", help="fetch the app code archive (init container)"
    )
    download.add_argument("--config", required=True)
    download.add_argument("--target", required=True)

    setup = sub.add_parser(
        "application-setup", help="create topics + assets (setup Job)"
    )
    setup.add_argument("--delete", action="store_true")

    deployer = sub.add_parser(
        "deployer", help="build the plan and write Agent CRs (deployer Job)"
    )
    deployer.add_argument("--delete", action="store_true")

    # long-running services (what the helm chart's Deployments invoke)
    cp = sub.add_parser("controlplane", help="run the REST control plane")
    cp.add_argument("--host", default="0.0.0.0")
    cp.add_argument("--port", type=int, default=8090)
    cp.add_argument("--storage-path", default="/var/lib/langstream")
    cp.add_argument("--code-storage", default=None,
                    help="code storage config JSON (default: local-disk)")
    cp.add_argument("--executor", choices=["kubernetes", "local", "none"],
                    default="kubernetes")
    cp.add_argument("--reconcile", action="store_true",
                    help="also run the operator loop in-process")
    cp.add_argument("--image", default="langstream-tpu/runtime:latest")
    cp.add_argument("--auth-token", default=None)
    cp.add_argument("--archetypes", default=None)

    op = sub.add_parser("operator", help="run the reconcile loop")
    op.add_argument("--interval", type=float, default=2.0)
    op.add_argument("--image", default="langstream-tpu/runtime:latest")
    op.add_argument("--code-storage", default=None)

    gws = sub.add_parser("gateway-server", help="serve application gateways")
    gws.add_argument("--host", default="0.0.0.0")
    gws.add_argument("--port", type=int, default=8091)
    gws.add_argument("--sync-interval", type=float, default=5.0)

    serve = sub.add_parser(
        "serve",
        help="OpenAI-compatible HTTP server over the TPU engine "
             "(/v1/chat/completions, /v1/completions, /v1/embeddings)",
    )
    serve.add_argument("--model", default="tiny", help="model preset or name")
    serve.add_argument("--checkpoint", default=None, help="HF/orbax dir")
    serve.add_argument("--tokenizer", default=None, help="HF tokenizer path")
    serve.add_argument("--quantization", default=None, choices=["int8"])
    serve.add_argument("--tp", type=int, default=1, help="tensor parallelism")
    serve.add_argument("--max-slots", type=int, default=8)
    serve.add_argument("--max-seq-len", type=int, default=2048)
    serve.add_argument("--decode-chunk", type=int, default=16)
    serve.add_argument(
        "--admission-chunk", type=int, default=0,
        help="cap the decode chunk at this many steps while admissions "
             "wait, so new requests join the batch sooner (TTFT lever; "
             "0 = off)",
    )
    serve.add_argument("--precompile", action="store_true")
    # pipelined dispatch hides the host/tunnel gap between decode
    # chunks (the bench's winning config); token-identical by test
    serve.add_argument(
        "--no-pipeline-decode", action="store_true",
        help="disable pipelined decode dispatch (on by default)",
    )
    serve.add_argument(
        "--no-prefix-cache", action="store_true",
        help="disable cross-slot prompt-prefix KV reuse (on by default)",
    )
    serve.add_argument(
        "--logprobs-top-k", type=int, default=0,
        help="enable OpenAI top_logprobs up to K alternatives per token "
             "(static — adds a top_k to the serving jits; 0 = off)",
    )
    serve.add_argument(
        "--kv-layout", default="dense", choices=["dense", "paged"],
        help="KV cache layout: dense per-slot regions, or a paged "
             "block pool with a persistent refcounted prefix cache "
             "(docs/perf.md 'KV layouts')",
    )
    serve.add_argument(
        "--kv-block-size", type=int, default=16,
        help="paged layout: tokens per pool block",
    )
    serve.add_argument(
        "--kv-blocks", type=int, default=0,
        help="paged layout: pool size in blocks (0 = the dense-"
             "equivalent worst case, slots x ceil(max_seq/block))",
    )
    serve.add_argument(
        "--kv-host-blocks", type=int, default=0,
        help="paged layout: host-DRAM demotion tier capacity in "
             "blocks (0 = off). Evicted chains demote to pinned host "
             "RAM and promote back on a prefix digest hit instead of "
             "recomputing (docs/perf.md 'KV tiers')",
    )
    serve.add_argument(
        "--paged-kernel", default="fused", choices=["fused", "reference"],
        help="paged attention kernel: fused ragged Pallas launch over "
             "the block tables (default) or the gather/scatter "
             "reference oracle (docs/perf.md 'Ragged paged attention')",
    )
    serve.add_argument(
        "--prefill-mode", default="split", choices=["split", "mixed"],
        help="paged prefill scheduling: split (dedicated bucketed "
             "prefill dispatches) or mixed (token-budget chunked "
             "prefill fused into the decode step — bounds every "
             "dispatch, docs/perf.md 'Chunked prefill & mixed "
             "dispatch')",
    )
    serve.add_argument(
        "--prefill-chunk", type=int, default=64,
        help="mixed prefill mode: max prompt tokens any single decode "
             "step carries",
    )
    serve.add_argument(
        "--mixed-carry", default="on", choices=["on", "off"],
        help="mixed prefill mode: pipeline consecutive mixed steps off "
             "the previous step's device-resident outputs (two-step "
             "window plan — hides the per-step host round trip; "
             "docs/perf.md 'Mixed-step carry')",
    )
    serve.add_argument(
        "--spec-decode", default="off", choices=["off", "ngram"],
        help="speculative decoding: self-drafting prompt-lookup drafts "
             "spec-k tokens per decode step, one batched forward "
             "verifies them (docs/perf.md 'Speculative decoding')",
    )
    serve.add_argument(
        "--spec-k", type=int, default=4,
        help="drafted tokens verified per decode step (spec-decode)",
    )
    serve.add_argument(
        "--spec-ngram", type=int, default=2,
        help="suffix n-gram length the prompt-lookup drafter matches",
    )
    serve.add_argument(
        "--slo-ttft-ms", type=float, default=0,
        help="TTFT p95 SLO target in ms: enables burn-rate gauges on "
             "/metrics and the `top` SLO panel (0 = off)",
    )
    serve.add_argument(
        "--slo-tpot-ms", type=float, default=0,
        help="TPOT p95 SLO target in ms (0 = off)",
    )
    serve.add_argument(
        "--no-watchdog", action="store_true",
        help="disable the decode-stall watchdog (on by default for "
             "serve: EWMA step-latency degradation, no-progress and "
             "KV-pool livelock detection with automatic evidence "
             "capture)",
    )
    serve.add_argument(
        "--no-supervisor", action="store_true",
        help="disable the engine supervisor (on by default: an engine "
             "crash or watchdog escalation snapshots every live "
             "session, rebuilds the engine, and resumes each stream "
             "bitwise — docs/robustness.md)",
    )
    serve.add_argument(
        "--max-restarts", type=int, default=3,
        help="supervisor: engine rebuilds allowed inside the restart "
             "window before giving up (crash-loop circuit breaker)",
    )
    serve.add_argument(
        "--queue-timeout-s", type=float, default=0,
        help="admission deadline: pending requests older than this are "
             "shed with 503 + Retry-After instead of waiting in the "
             "queue forever (0 = off)",
    )
    # fleet membership (langstream_tpu/fleet): role-aware heartbeat
    # gossip over the topic fabric — the router's liveness/affinity/
    # disaggregation view is built ENTIRELY from these beats
    serve.add_argument(
        "--fleet-role", default="unified",
        choices=["unified", "prefill", "decode"],
        help="disaggregation pool this replica serves (gossiped in "
             "every heartbeat; the FleetRouter sends cold prompts to "
             "the prefill pool and pinned handoff continuations to "
             "the decode pool — docs/fleet.md)",
    )
    serve.add_argument(
        "--fleet-gossip", default=None, metavar="JSON",
        help="streaming-cluster config for the heartbeat fabric, e.g. "
             '\'{"type":"kafka","configuration":{...}}\' — when set, '
             "this replica publishes build_heartbeat on a period "
             "(fleet/heartbeat.publish_loop) so routers see it without "
             "scraping",
    )
    serve.add_argument(
        "--fleet-replica-id", default=None,
        help="stable pod identity stamped on heartbeats (default: "
             "$HOSTNAME — the StatefulSet ordinal name on kube)",
    )
    serve.add_argument(
        "--fleet-heartbeat-s", type=float, default=2.0,
        help="heartbeat publish period in seconds",
    )
    serve.add_argument("--embeddings-checkpoint", default=None)
    serve.add_argument("--host", default="0.0.0.0")
    serve.add_argument("--port", type=int, default=8000)
    # multi-host SPMD serving (tp spanning hosts): host 0 serves HTTP
    # and mirrors every dispatch; followers replay the stream on their
    # shard of the global mesh. jax.distributed comes up first either
    # way (runtime/multihost.py plan, or LANGSTREAM_* env on pods).
    serve.add_argument(
        "--followers", type=int, default=0,
        help="leader: number of follower hosts to wait for",
    )
    serve.add_argument(
        "--mirror-port", type=int, default=8477,
        help="leader: port the dispatch mirror listens on",
    )
    serve.add_argument(
        "--follower-of", default=None, metavar="HOST:PORT",
        help="run as a follower replaying the leader's dispatch stream",
    )

    python_cmd = sub.add_parser(
        "python", help="application Python dependency tooling"
    )
    python_sub = python_cmd.add_subparsers(
        dest="python_command", required=True
    )
    deps = python_sub.add_parser(
        "load-deps",
        help="pip-install python/requirements.txt into python/lib "
             "(shipped with the code archive; reference: "
             "langstream python load-pip-requirements)",
    )
    deps.add_argument("app_dir")

    plugins = sub.add_parser("plugins", help="agent plugin packaging")
    plugins_sub = plugins.add_subparsers(dest="plugins_command", required=True)
    pkg = plugins_sub.add_parser(
        "package", help="zip a plugin dir (the NAR-build equivalent)"
    )
    pkg.add_argument("plugin_dir")
    pkg.add_argument("-o", "--output", default=None)
    plugins_sub.add_parser("list", help="show loaded plugins")
    return parser


def main(argv: Optional[List[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    if args.command == "apps" and args.apps_command in ("run", "ui"):
        asyncio.run(_apps_run(args, ui=args.apps_command == "ui"))
    elif args.command == "apps" and args.apps_command == "plan":
        _apps_plan(args)
    elif args.command == "apps" and args.apps_command in ("deploy", "update"):
        asyncio.run(_apps_deploy(args, update=args.apps_command == "update"))
    elif args.command == "apps" and args.apps_command == "get":
        asyncio.run(_apps_get(args))
    elif args.command == "apps" and args.apps_command == "list":
        asyncio.run(_apps_list(args))
    elif args.command == "apps" and args.apps_command == "delete":
        asyncio.run(_apps_delete(args))
    elif args.command == "apps" and args.apps_command == "logs":
        asyncio.run(_apps_logs(args))
    elif args.command == "apps" and args.apps_command == "download":
        asyncio.run(_apps_download(args))
    elif args.command == "archetypes":
        asyncio.run(_archetypes_cmd(args))
    elif args.command == "tenants":
        asyncio.run(_tenants_cmd(args))
    elif args.command == "profiles":
        _profiles_cmd(args)
    elif args.command == "gateway" and args.gateway_command == "chat":
        asyncio.run(_gateway_chat(args))
    elif args.command == "gateway" and args.gateway_command == "produce":
        asyncio.run(_gateway_produce(args))
    elif args.command == "gateway" and args.gateway_command == "consume":
        asyncio.run(_gateway_consume(args))
    elif args.command == "broker":
        asyncio.run(_broker_serve(args))
    elif args.command == "docs":
        _docs(args)
    elif args.command == "trace":
        _trace_cmd(args)
    elif args.command == "journey":
        _journey_cmd(args)
    elif args.command == "top":
        try:
            asyncio.run(_top_cmd(args))
        except KeyboardInterrupt:
            pass
    elif args.command == "check":
        from langstream_tpu.analysis.check import run_check

        raise SystemExit(run_check(args))
    elif args.command == "profile":
        asyncio.run(_profile_cmd(args))
    elif args.command == "agent-runner":
        from langstream_tpu.runtime.pod import agent_runner_main

        asyncio.run(
            agent_runner_main(args.config, http_port=args.http_port)
        )
    elif args.command == "code-download":
        from langstream_tpu.runtime.pod import code_download_main

        code_download_main(args.config, args.target)
    elif args.command == "application-setup":
        from langstream_tpu.runtime.pod import application_setup_main

        asyncio.run(application_setup_main(delete=args.delete))
    elif args.command == "deployer":
        from langstream_tpu.runtime.pod import deployer_main

        asyncio.run(deployer_main(delete=args.delete))
    elif args.command == "controlplane":
        from langstream_tpu.cli.services import controlplane_main

        asyncio.run(controlplane_main(args))
    elif args.command == "operator":
        from langstream_tpu.cli.services import operator_main

        asyncio.run(operator_main(args))
    elif args.command == "gateway-server":
        from langstream_tpu.cli.services import gateway_server_main

        asyncio.run(gateway_server_main(args))
    elif args.command == "serve":
        from langstream_tpu.cli.services import serve_main

        asyncio.run(serve_main(args))
    elif args.command == "python" and args.python_command == "load-deps":
        import os
        import subprocess

        requirements = os.path.join(
            args.app_dir, "python", "requirements.txt"
        )
        target = os.path.join(args.app_dir, "python", "lib")
        if not os.path.isfile(requirements):
            raise SystemExit(f"no {requirements}")
        os.makedirs(target, exist_ok=True)
        subprocess.run(
            [sys.executable, "-m", "pip", "install",
             "--target", target, "--upgrade",
             "-r", requirements],
            check=True,
        )
        print(f"installed {requirements} -> {target}")
    elif args.command == "plugins" and args.plugins_command == "package":
        import os
        import zipfile

        from langstream_tpu.runtime.plugins import load_plugin

        plugin_dir = args.plugin_dir.rstrip("/")
        # validate before packaging: a bad manifest fails at build time
        load_plugin(plugin_dir)
        output = args.output or f"{os.path.basename(plugin_dir)}.zip"
        with zipfile.ZipFile(output, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, _dirs, files in os.walk(plugin_dir):
                for name in files:
                    if name.endswith(".pyc"):
                        continue
                    full = os.path.join(root, name)
                    zf.write(full, os.path.relpath(full, plugin_dir))
        print(f"packaged {plugin_dir} -> {output}")
    elif args.command == "plugins" and args.plugins_command == "list":
        from langstream_tpu.runtime.plugins import loaded_plugins

        _print_json(loaded_plugins())


if __name__ == "__main__":
    main()
