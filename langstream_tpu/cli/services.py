"""Long-running service entry points: control plane, operator, gateway.

The reference deploys these as separate images (langstream-webservice,
langstream-k8s-deployer operator, langstream-api-gateway); here they are
subcommands of the one runtime image, which is what the helm chart's
Deployments invoke:

- ``controlplane`` — REST webservice + (optionally) the reconcile loop,
  file-backed stores under ``--storage-path``.
- ``operator``     — standalone reconcile loop against the cluster's API
  server (Application/Agent CRs → StatefulSets).
- ``gateway-server`` — serves every deployed application's gateways,
  discovering apps from Application CRs and connecting to each app's
  own ``streamingCluster``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
from typing import Any, Dict

logger = logging.getLogger(__name__)


def _install_stop(loop, stop: asyncio.Event) -> None:
    def _signalled() -> None:
        # flush the flight recorder the moment the signal lands: a k8s
        # preStop SIGTERM gives a bounded grace period, and the async
        # teardown below it can be cut short by SIGKILL — the ring's
        # evidence must already be on disk by then (no-op when the
        # recorder is disabled)
        try:
            from langstream_tpu.runtime import flight

            flight.flush()
        except Exception:  # noqa: BLE001 — never block the shutdown path
            pass
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, _signalled)
        except (NotImplementedError, RuntimeError):
            pass


async def controlplane_main(args) -> None:
    from langstream_tpu.controlplane import (
        ApplicationService,
        FileSystemApplicationStore,
        GlobalMetadataStore,
        TenantService,
    )
    from langstream_tpu.controlplane.codestorage import create_code_storage
    from langstream_tpu.controlplane.webservice import ControlPlaneWebService

    storage = args.storage_path
    os.makedirs(storage, exist_ok=True)
    store = FileSystemApplicationStore(os.path.join(storage, "apps"))
    metadata = GlobalMetadataStore(os.path.join(storage, "metadata.json"))
    tenants = TenantService(metadata)
    if "default" not in {t.name for t in tenants.list()}:
        tenants.create("default")
    code_config = json.loads(args.code_storage) if args.code_storage else {
        "type": "local-disk", "path": os.path.join(storage, "code"),
    }
    code = create_code_storage(code_config)

    executor = None
    operator = None
    if args.executor == "kubernetes":
        from langstream_tpu.deployer.kubeclient import create_kube_api
        from langstream_tpu.deployer.operator import (
            KubernetesExecutor,
            Operator,
        )

        kube = create_kube_api()
        operator = Operator(
            kube, image=args.image, code_storage_config=code_config
        )
        executor = KubernetesExecutor(
            kube, operator if args.reconcile else None
        )
    elif args.executor == "local":
        from langstream_tpu.controlplane.service import LocalExecutor

        executor = LocalExecutor()

    service = ApplicationService(store, code, tenants, executor=executor)
    webservice = ControlPlaneWebService(
        service,
        auth_token=args.auth_token or os.environ.get("LANGSTREAM_AUTH_TOKEN"),
        archetypes_path=args.archetypes,
    )
    port = await webservice.start(args.host, args.port)
    logger.info("control plane on %s:%d (storage %s)", args.host, port, storage)
    print(f"control plane listening on http://{args.host}:{port}", flush=True)

    stop = asyncio.Event()
    _install_stop(asyncio.get_running_loop(), stop)
    tasks = []
    if operator is not None and args.reconcile:
        tasks.append(asyncio.get_running_loop().create_task(
            operator.run(stop=stop)
        ))
    try:
        await stop.wait()
    finally:
        for task in tasks:
            task.cancel()
        await webservice.stop()


async def operator_main(args) -> None:
    from langstream_tpu.deployer.kubeclient import create_kube_api
    from langstream_tpu.deployer.operator import Operator

    code_config = (
        json.loads(args.code_storage) if args.code_storage else {}
    )
    operator = Operator(
        create_kube_api(), image=args.image, code_storage_config=code_config
    )
    stop = asyncio.Event()
    _install_stop(asyncio.get_running_loop(), stop)
    logger.info("operator reconcile loop started (interval %ss)", args.interval)
    print("operator running", flush=True)
    await operator.run(interval=args.interval, stop=stop)


class GatewayAppWatcher:
    """Polls Application CRs and (de)registers them with the gateway,
    each with a topic runtime for its own streamingCluster (reference:
    the api-gateway reads apps through the k8s application store)."""

    def __init__(self, gateway, kube) -> None:
        self.gateway = gateway
        self.kube = kube
        self._registered: Dict[tuple, Any] = {}

    async def sync(self) -> None:
        from langstream_tpu.deployer.crds import ApplicationCustomResource
        from langstream_tpu.model.application import Application
        from langstream_tpu.topics import create_topic_runtime

        seen = set()
        for doc in self.kube.list("Application"):
            cr = ApplicationCustomResource.from_manifest(doc)
            key = (cr.namespace, cr.name)
            seen.add(key)
            if key in self._registered:
                continue
            try:
                application = Application.from_document(
                    cr.application, cr.instance
                )
                application.application_id = cr.name
                application.tenant = cr.namespace
                runtime = create_topic_runtime(
                    application.instance.streaming_cluster
                )
            except Exception:  # noqa: BLE001 — one bad app can't stop sync
                logger.exception("cannot register app %s", key)
                continue
            self.gateway.register(cr.namespace, application, runtime)
            self._registered[key] = runtime
            logger.info("gateway registered %s/%s", *key)
        for key in list(self._registered):
            if key not in seen:
                runtime = self._registered.pop(key)
                self.gateway._apps.pop(key, None)  # noqa: SLF001
                await runtime.close()
                logger.info("gateway unregistered %s/%s", *key)

    async def run(self, stop: asyncio.Event, interval: float = 5.0) -> None:
        while not stop.is_set():
            try:
                await self.sync()
            except Exception:  # noqa: BLE001
                logger.exception("gateway app sync failed")
            try:
                await asyncio.wait_for(stop.wait(), timeout=interval)
            except asyncio.TimeoutError:
                pass


async def gateway_server_main(args) -> None:
    from langstream_tpu.deployer.kubeclient import create_kube_api
    from langstream_tpu.gateway import GatewayServer

    gateway = GatewayServer(host=args.host, port=args.port)
    await gateway.start()
    print(f"gateway listening on ws://{args.host}:{args.port}", flush=True)
    stop = asyncio.Event()
    _install_stop(asyncio.get_running_loop(), stop)
    watcher = GatewayAppWatcher(gateway, create_kube_api())
    try:
        await watcher.run(stop, interval=args.sync_interval)
    finally:
        await gateway.stop()


def _mirror_fingerprint(config: Dict[str, Any]) -> bytes:
    """Leader/follower config digest over the keys that shape the jit
    programs. Observability-only knobs (SLO targets, watchdog) must not
    force flag parity across hosts — a follower has no HTTP surface to
    serve SLOs from."""
    from langstream_tpu.serving.mirror import config_fingerprint

    scrubbed = {k: v for k, v in config.items() if k != "slo"}
    scrubbed["engine"] = {
        k: v for k, v in config.get("engine", {}).items()
        if k != "watchdog"
    }
    return config_fingerprint(scrubbed)


async def serve_main(args) -> None:
    """`langstream-tpu serve`: OpenAI-compatible HTTP server straight
    over the jax-local engine (no pipeline needed) — existing OpenAI
    clients point their base URL at this process."""
    import os

    import jax

    # the TPU plugin's sitecustomize overrides the JAX_PLATFORMS env
    # var; restore normal env semantics (JAX_PLATFORMS=cpu must work)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    # flight recorder: ON for every serve run (override the dir with
    # LANGSTREAM_FLIGHT_DIR, disable with LANGSTREAM_FLIGHT_DIR="") — a
    # run that dies at backend init must still leave the init-phase
    # timeline on disk
    import langstream_tpu
    from langstream_tpu.runtime import flight

    # default next to the repo's other bench artifacts when running
    # from a checkout (where tools/ab_analyze.py looks by default);
    # CWD-relative otherwise — never inside an installed site-packages
    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(langstream_tpu.__file__))
    )
    default_dir = (
        os.path.join(repo_root, "bench_artifacts", "flight")
        if os.path.isdir(os.path.join(repo_root, "bench_artifacts"))
        else os.path.join("bench_artifacts", "flight")
    )
    flight_dir = os.environ.get("LANGSTREAM_FLIGHT_DIR", default_dir)
    # stamp fleet identity before configure so it rides the artifact's
    # meta record — `langstream-tpu journey` joins per-replica artifacts
    # by trace id and labels each stage with this replica id
    import socket

    flight.set_identity(
        getattr(args, "fleet_replica_id", None)
        or os.environ.get("HOSTNAME")
        or socket.gethostname(),
        getattr(args, "fleet_role", "unified") or "unified",
    )
    if flight_dir:
        path = flight.configure(flight_dir, run_id=f"serve-{args.model}")
        print(f"flight recorder -> {path}", flush=True)
    flight.record("phase", name="backend-init", model=args.model)
    flight.flush()

    # multi-host slice: bring up jax.distributed from StatefulSet/env
    # identity before any device access, so the global mesh spans hosts
    from langstream_tpu.runtime.multihost import initialize_multihost

    initialize_multihost()

    from langstream_tpu.providers.jax_local.provider import (
        JaxCompletionsService,
        JaxEmbeddingsService,
    )
    from langstream_tpu.serving.openai_api import OpenAIApiServer

    config = {
        "model": {"preset": args.model, "max_seq_len": args.max_seq_len},
        "engine": {
            "max-slots": args.max_slots,
            "max-seq-len": args.max_seq_len,
            "decode-chunk": args.decode_chunk,
            "admission-chunk": getattr(args, "admission_chunk", 0) or "",
            "precompile": bool(args.precompile),
            "pipeline-decode": not getattr(args, "no_pipeline_decode", False),
            "prefix-cache": not getattr(args, "no_prefix_cache", False),
            "logprobs-top-k": getattr(args, "logprobs_top_k", 0),
            "kv-layout": getattr(args, "kv_layout", "dense"),
            "kv-block-size": getattr(args, "kv_block_size", 16),
            "kv-blocks": getattr(args, "kv_blocks", 0) or "",
            "kv-host-blocks": getattr(args, "kv_host_blocks", 0) or "",
            "paged-kernel": getattr(args, "paged_kernel", "fused"),
            "spec-decode": getattr(args, "spec_decode", "off"),
            "spec-k": getattr(args, "spec_k", 4),
            "spec-ngram": getattr(args, "spec_ngram", 2),
            "prefill-mode": getattr(args, "prefill_mode", "split"),
            "prefill-chunk": getattr(args, "prefill_chunk", 64),
            "mixed-carry": getattr(args, "mixed_carry", "on"),
            # decode-stall watchdog: on by default for serve (the
            # provider starts it; --no-watchdog disables)
            "watchdog": not getattr(args, "no_watchdog", False),
            # engine supervisor (self-healing serving): crash →
            # snapshot → rebuild → bitwise session resurrection; the
            # multi-host mirror path disables it below (a rebuilt
            # leader cannot resynchronize followers yet)
            "supervisor": not getattr(args, "no_supervisor", False),
            "max-restarts": getattr(args, "max_restarts", 3),
            # admission deadline / load shedding (0 = off)
            "queue-timeout-s": getattr(args, "queue_timeout_s", 0) or "",
        },
    }
    if getattr(args, "followers", 0) or getattr(args, "follower_of", None):
        # mirror serving: every leader dispatch must replay on the
        # followers in stream order — a supervisor rebuild would fork
        # the stream, so the heal arc is disabled rather than divergent
        config["engine"]["supervisor"] = False
    slo_targets = {
        "ttft-ms-p95": getattr(args, "slo_ttft_ms", 0) or 0,
        "tpot-ms-p95": getattr(args, "slo_tpot_ms", 0) or 0,
    }
    if any(slo_targets.values()):
        config["slo"] = {k: v for k, v in slo_targets.items() if v}
    from langstream_tpu.providers.jax_local.model import LlamaConfig

    try:
        LlamaConfig.from_dict({"preset": args.model})
        known_preset = True
    except KeyError:
        known_preset = False
    if args.checkpoint:
        config["checkpoint"] = args.checkpoint
        if not known_preset:
            # the checkpoint carries the real model config; --model is
            # then just the served model NAME, not a preset
            config["model"] = {}
    elif not known_preset:
        raise SystemExit(
            f"unknown model preset {args.model!r} and no --checkpoint "
            "given; pass a preset (tiny, llama-3-1b, llama-3-8b, "
            "llama-3-70b) or point --checkpoint at a model directory"
        )
    if args.tokenizer:
        config["tokenizer"] = {"type": "hf", "path": args.tokenizer}
    if args.quantization:
        config["quantization"] = args.quantization
    if args.tp and args.tp > 1:
        config["mesh"] = {"tp": args.tp}
    # --kv-layout paged composes with multi-host serving: paged
    # dispatch records carry their block-table rows and COW copies
    # publish block_copy records, so followers replay the identical
    # pool mutations on their shard (serving/mirror.py).
    if getattr(args, "spec_decode", "off") != "off" and (
        getattr(args, "followers", 0) or getattr(args, "follower_of", None)
    ):
        # configuration-time guard: the mirror replays fixed-width
        # dispatch records; spec dispatches carry the device
        # token-history operand and return variable-width outputs
        # (engine._check_mirror_layout backstops)
        raise SystemExit(
            "--spec-decode is not supported with multi-host serving "
            "(--followers/--follower-of) yet"
        )
    completions = JaxCompletionsService(config)
    if getattr(args, "follower_of", None):
        # follower host of a multi-host replica: no HTTP surface — just
        # replay the leader's dispatch stream on this process's shard
        from langstream_tpu.serving.mirror import FollowerExecutor

        completions.engine.stop()  # executor owns the dispatches
        leader_host, _, leader_port = args.follower_of.rpartition(":")
        executor = FollowerExecutor(completions.engine)
        executor.connect(
            leader_host or "127.0.0.1", int(leader_port),
            fingerprint=_mirror_fingerprint(config),
        )
        print(
            f"follower: replaying dispatch stream from {args.follower_of}",
            flush=True,
        )
        records = await asyncio.to_thread(executor.run)
        print(f"follower: stream ended after {records} records", flush=True)
        return
    mirror = None
    if getattr(args, "followers", 0):
        from langstream_tpu.serving.mirror import DispatchMirror

        mirror = DispatchMirror(
            host=args.host, port=args.mirror_port,
            fingerprint=_mirror_fingerprint(config),
        )
        print(
            f"mirror: waiting for {args.followers} follower(s) "
            f"on :{mirror.port}",
            flush=True,
        )
        await asyncio.to_thread(mirror.wait_for_followers, args.followers)
        completions.engine.mirror = mirror
    embeddings = None
    if args.embeddings_checkpoint:
        embeddings = JaxEmbeddingsService(
            {"embeddings-model": {"checkpoint": args.embeddings_checkpoint}},
            None,
        )
    from langstream_tpu.providers.jax_local.engine import (
        engines_histograms,
        engines_snapshot,
    )

    server = OpenAIApiServer(
        completions, embeddings,
        model=args.model, host=args.host, port=args.port,
        gauges=engines_snapshot, histograms=engines_histograms,
    )
    await server.start()
    port = server.addresses[0][1] if server.addresses else args.port
    flight.record("phase", name="serving", port=port)
    flight.flush()
    print(
        f"OpenAI-compatible API on http://{args.host}:{port}/v1 "
        f"(model {args.model})",
        flush=True,
    )
    stop = asyncio.Event()
    _install_stop(asyncio.get_running_loop(), stop)
    gossip_task, gossip_runtime = await _start_fleet_gossip(
        args, completions, port, stop
    )
    try:
        await stop.wait()
    finally:
        if gossip_task is not None:
            gossip_task.cancel()
            try:
                # wait the cancel out: a mid-write publish must not
                # race the runtime close below
                await gossip_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if gossip_runtime is not None:
            try:
                await gossip_runtime.close()
            except Exception:  # noqa: BLE001 — shutdown best-effort
                pass
        await server.stop()
        await completions.close()


async def _start_fleet_gossip(args, completions, port: int, stop):
    """``serve --fleet-gossip``: publish role-aware heartbeats on the
    topic fabric so fleet routers see this replica without scraping —
    the runner-pod wiring of ``fleet/heartbeat.publish_loop`` (ROADMAP
    item 4). Returns (task, topic_runtime), both None when gossip is
    not configured. A bad fabric config logs and disables gossip; it
    never takes the serving process down."""
    gossip = getattr(args, "fleet_gossip", None)
    if not gossip:
        return None, None
    import socket

    from langstream_tpu.fleet.heartbeat import (
        HEARTBEAT_TOPIC,
        build_heartbeat,
        publish_loop,
    )
    from langstream_tpu.topics import create_topic_runtime

    role = getattr(args, "fleet_role", "unified") or "unified"
    replica_id = (
        getattr(args, "fleet_replica_id", None)
        or os.environ.get("HOSTNAME")
        or f"{socket.gethostname()}:{port}"
    )
    runtime = None
    try:
        runtime = create_topic_runtime(json.loads(gossip))
        producer = runtime.create_producer(
            f"fleet-gossip-{replica_id}", {"topic": HEARTBEAT_TOPIC}
        )
        await producer.start()
    except Exception:  # noqa: BLE001 — gossip must not kill serving
        logger.exception("fleet gossip disabled: bad --fleet-gossip")
        if runtime is not None:
            # the runtime came up before the producer failed: close it
            # or its client connections/threads outlive the feature
            try:
                await runtime.close()
            except Exception:  # noqa: BLE001
                pass
        return None, None
    seq = {"n": 0}

    def beat():
        seq["n"] += 1
        # the CURRENT engine: the supervisor swaps it on rebuild, and
        # the degraded/rebuilding state rides the beat so routers
        # drain this replica instead of 503-discovering it
        return build_heartbeat(
            replica_id,
            seq["n"],
            engine=completions.engine,
            supervisor=getattr(completions, "_supervisor", None),
            role=role,
        )

    task = asyncio.get_running_loop().create_task(
        publish_loop(
            producer, beat,
            interval_s=getattr(args, "fleet_heartbeat_s", 2.0),
            stop=stop,
        )
    )
    print(
        f"fleet gossip: {replica_id} role={role} -> "
        f"{HEARTBEAT_TOPIC} every {getattr(args, 'fleet_heartbeat_s', 2.0)}s",
        flush=True,
    )
    return task, runtime
