"""The record model — the unit of data flowing through every pipeline.

Equivalent of the reference's record contract
(``langstream-api/src/main/java/ai/langstream/api/runner/code/Record.java:20``
and ``SimpleRecord.java:28``): a record carries a key, a value, the topic of
origin, an event timestamp, and a set of headers.

TPU-first deviations from the reference:

- Records are immutable (frozen dataclass) — the runtime may hold a record in
  several async pipelines at once (batch coalescing for XLA calls), so
  aliasing must be safe.
- Headers are a tuple of ``(name, value)`` pairs rather than a mutable list;
  helper accessors provide dict-like reads.
- Values are plain Python objects (str / bytes / dict / list / numbers).
  Schema handling is structural: dict values behave like the reference's Avro
  GenericRecord for field access in the expression language, without dragging
  a schema registry into the core (the reference's schema plumbing lives in
  ``langstream-agents-commons`` converters).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Iterable, Mapping, Optional, Sequence, Tuple

Header = Tuple[str, Any]


@dataclasses.dataclass(frozen=True)
class Record:
    """An immutable record: key, value, origin topic, timestamp, headers.

    ``timestamp`` is epoch milliseconds, matching the reference
    (``Record.java:20`` exposes ``Long timestamp()`` in ms).
    """

    value: Any = None
    key: Any = None
    origin: Optional[str] = None
    timestamp: Optional[int] = None
    headers: Tuple[Header, ...] = ()

    # ------------------------------------------------------------------ #
    # header helpers
    # ------------------------------------------------------------------ #
    def header(self, name: str, default: Any = None) -> Any:
        """Return the value of the first header named ``name``."""
        for key, value in self.headers:
            if key == name:
                return value
        return default

    def header_values(self, name: str) -> Tuple[Any, ...]:
        return tuple(v for k, v in self.headers if k == name)

    def headers_as_dict(self) -> dict:
        """Collapse headers into a dict (last occurrence wins)."""
        return dict(self.headers)

    # ------------------------------------------------------------------ #
    # builders
    # ------------------------------------------------------------------ #
    def with_value(self, value: Any) -> "Record":
        return dataclasses.replace(self, value=value)

    def with_key(self, key: Any) -> "Record":
        return dataclasses.replace(self, key=key)

    def with_origin(self, origin: Optional[str]) -> "Record":
        return dataclasses.replace(self, origin=origin)

    def with_timestamp(self, timestamp: Optional[int]) -> "Record":
        return dataclasses.replace(self, timestamp=timestamp)

    def with_headers(self, headers: Iterable[Header]) -> "Record":
        return dataclasses.replace(self, headers=tuple(headers))

    def with_header(self, name: str, value: Any) -> "Record":
        """Return a copy with header ``name`` set (replacing existing)."""
        kept = tuple((k, v) for k, v in self.headers if k != name)
        return dataclasses.replace(self, headers=kept + ((name, value),))

    def without_header(self, name: str) -> "Record":
        return dataclasses.replace(
            self, headers=tuple((k, v) for k, v in self.headers if k != name)
        )

    # ------------------------------------------------------------------ #
    # misc
    # ------------------------------------------------------------------ #
    def value_as_text(self) -> str:
        """Best-effort textual view of the value (for prompts / logging)."""
        value = self.value
        if value is None:
            return ""
        if isinstance(value, str):
            return value
        if isinstance(value, bytes):
            return value.decode("utf-8", errors="replace")
        if isinstance(value, (dict, list)):
            return json.dumps(value, ensure_ascii=False, default=str)
        return str(value)

    def estimated_size(self) -> int:
        """Rough payload size in bytes, used by batch byte budgeting."""
        size = 0
        for part in (self.key, self.value):
            if part is None:
                continue
            if isinstance(part, bytes):
                size += len(part)
            elif isinstance(part, str):
                size += len(part.encode("utf-8", errors="replace"))
            else:
                try:
                    size += len(json.dumps(part, default=str))
                except (TypeError, ValueError):
                    size += 64
        for name, value in self.headers:
            size += len(name) + (len(str(value)) if value is not None else 0)
        return size


class SimpleRecord(Record):
    """Alias preserved for parity with the reference's ``SimpleRecord``."""


def now_millis() -> int:
    return int(time.time() * 1000)


def record_from_value(
    value: Any,
    *,
    key: Any = None,
    origin: Optional[str] = None,
    headers: Sequence[Header] = (),
    timestamp: Optional[int] = None,
) -> Record:
    """Coerce loose agent return values into a :class:`Record`.

    Mirrors the coercion rules of the reference Python SDK
    (``langstream-runtime/langstream-runtime-impl/src/main/python/langstream_grpc/api.py:34-195``):
    agents may return a Record, a bare value, a ``(key, value)`` tuple, or a
    dict with record-shaped keys.
    """
    if isinstance(value, Record):
        return value
    if isinstance(value, tuple) and len(value) == 2:
        key, value = value
    if isinstance(value, Mapping) and set(value.keys()) <= {
        "key",
        "value",
        "headers",
        "origin",
        "timestamp",
    } and "value" in value:
        headers_in = value.get("headers", ())
        if isinstance(headers_in, Mapping):
            headers_in = tuple(headers_in.items())
        return Record(
            value=value.get("value"),
            key=value.get("key", key),
            origin=value.get("origin", origin),
            timestamp=value.get("timestamp", timestamp),
            headers=tuple(headers_in),
        )
    return Record(
        value=value,
        key=key,
        origin=origin,
        timestamp=timestamp,
        headers=tuple(headers),
    )
