"""The agent SPI — the contract every op of the framework implements.

Equivalent of the reference's agent contracts
(``langstream-api/src/main/java/ai/langstream/api/runner/code/AgentCode.java:25``,
``AgentSource.java:22``, ``AgentProcessor.java:23``, ``AgentSink.java:22``,
``AgentService.java:21``, ``AgentContext.java:25``): four agent kinds —
Source, Processor, Sink, Service — plus a shared lifecycle.

TPU-first deviations:

- The whole runtime is **asyncio-native**. The reference runs a single main
  thread with CompletableFuture-based async sinks; here every lifecycle and
  data method is a coroutine and the event loop is shared with the broker and
  gateway. Blocking work (XLA dispatch, file IO) belongs in executors —
  the ``jax_local`` provider runs device work on a dedicated thread.
- ``AgentProcessor.process(records, sink)`` keeps the reference's
  emit-as-you-complete contract (``AgentProcessor.java:23`` +
  ``SourceRecordAndResult`` record, line 41): results for each source record
  are pushed to a :class:`RecordSink` *as they finish*, out of order. This is
  load-bearing for TPU continuous batching — the LLM step completes records
  at different decode lengths and must not barrier the batch.
"""

from __future__ import annotations

import abc
import asyncio
import dataclasses
import enum
from typing import Any, Callable, Dict, List, Optional, Sequence

from langstream_tpu.api.records import Record


class ComponentType(enum.Enum):
    """Mirrors ``langstream-api/.../runtime/ComponentType.java``."""

    SOURCE = "source"
    PROCESSOR = "processor"
    SINK = "sink"
    SERVICE = "service"


@dataclasses.dataclass
class SourceRecordAndResult:
    """Result of processing one source record.

    Mirrors ``AgentProcessor.SourceRecordAndResult``
    (``AgentProcessor.java:41``): the source record, the records it produced
    (0..n), and an error if processing failed.
    """

    source_record: Record
    result_records: List[Record] = dataclasses.field(default_factory=list)
    error: Optional[BaseException] = None


class RecordSink:
    """Callback target for processor results (``RecordSink`` in the SPI).

    The runtime hands one to :meth:`AgentProcessor.process`; implementations
    must be safe to call from any asyncio task on the runner loop.
    """

    def emit(self, result: SourceRecordAndResult) -> None:
        raise NotImplementedError

    def emit_single(
        self, source_record: Record, result_records: List[Record]
    ) -> None:
        self.emit(SourceRecordAndResult(source_record, result_records))

    def emit_error(self, source_record: Record, error: BaseException) -> None:
        self.emit(SourceRecordAndResult(source_record, [], error))


class Agent(abc.ABC):
    """Shared lifecycle for all agent kinds (``AgentCode.java:25``).

    Lifecycle order enforced by the runner:
    ``init(config)`` → ``set_context(ctx)`` → ``start()`` → ... → ``close()``.
    """

    agent_id: str = ""
    agent_type: str = ""

    async def init(self, configuration: Dict[str, Any]) -> None:
        """Receive the agent's configuration map."""

    async def set_context(self, context: "AgentContext") -> None:
        self.context = context

    async def start(self) -> None:
        """Allocate runtime resources (connections, device buffers...)."""

    async def close(self) -> None:
        """Release resources; called on drain/shutdown."""

    def agent_info(self) -> Dict[str, Any]:
        """Introspection payload served at ``/info``
        (reference: ``AgentCode.getAgentStatus`` via
        ``agent/api/AgentAPIController.java``)."""
        return {
            "agent-id": self.agent_id,
            "agent-type": self.agent_type,
            "component-type": self.component_type().value,
        }

    @abc.abstractmethod
    def component_type(self) -> ComponentType:
        ...


class AgentSource(Agent):
    """A source reads records from an external system (``AgentSource.java:22``)."""

    def component_type(self) -> ComponentType:
        return ComponentType.SOURCE

    @abc.abstractmethod
    async def read(self, max_records: int = 100) -> List[Record]:
        """Return the next batch of records (may be empty; must not block
        the loop forever — poll with a timeout). ``max_records`` is the
        runner's remaining pending-record budget; honoring it is what makes
        backpressure exact (custom sources may treat it as advisory)."""

    async def commit(self, records: List[Record]) -> None:
        """All downstream writes for ``records`` are durable; advance offsets."""

    async def permanent_failure(
        self, record: Record, error: BaseException
    ) -> None:
        """A record exhausted its error policy with ``fail``; default:
        re-raise so the runner dies and the supervisor restarts it
        (reference behavior: ``AgentSource.java`` default + AgentRunner
        ``mainErrorHandler``)."""
        raise error


class AgentProcessor(Agent):
    """A processor maps each source record to 0..n result records
    (``AgentProcessor.java:23``)."""

    def component_type(self) -> ComponentType:
        return ComponentType.PROCESSOR

    @abc.abstractmethod
    def process(self, records: List[Record], sink: RecordSink) -> None:
        """Schedule processing of ``records``; emit each record's
        :class:`SourceRecordAndResult` on ``sink`` as it completes.

        Must not await — schedule tasks on the running loop and return.
        """


class SingleRecordProcessor(AgentProcessor):
    """Convenience base: implement per-record async processing
    (reference: ``SingleRecordAgentProcessor.java:24``)."""

    async def process_record(self, record: Record) -> List[Record]:
        raise NotImplementedError

    def process(self, records: List[Record], sink: RecordSink) -> None:
        loop = asyncio.get_running_loop()
        for record in records:
            loop.create_task(self._process_one(record, sink))

    async def _process_one(self, record: Record, sink: RecordSink) -> None:
        try:
            results = await self.process_record(record)
            sink.emit_single(record, list(results))
        except BaseException as error:  # noqa: BLE001 — forwarded to policy
            sink.emit_error(record, error)


class AgentSink(Agent):
    """A sink writes records to an external system (``AgentSink.java:22``)."""

    def component_type(self) -> ComponentType:
        return ComponentType.SINK

    @abc.abstractmethod
    async def write(self, record: Record) -> None:
        """Durably write one record; awaiting it is the reference's
        ``CompletableFuture<Void>`` completion."""

    def handles_commit(self) -> bool:
        """True if the sink commits source offsets itself (reference:
        Kafka Connect sink adapter path, ``AgentRunner.java:716-722``)."""
        return False

    def set_commit_callback(
        self, callback: Callable[[List[Record]], None]
    ) -> None:
        """Used when :meth:`handles_commit` is True."""


class AgentService(Agent):
    """A long-running service with no record loop (``AgentService.java:21``)."""

    def component_type(self) -> ComponentType:
        return ComponentType.SERVICE

    @abc.abstractmethod
    async def join(self) -> None:
        """Run until shutdown."""


class AgentContext:
    """Runtime context handed to every agent (``AgentContext.java:25``).

    Exposes topic access for agents that need side-channels (dispatch,
    stream-to-topic), the persistent state directory, metrics, and the
    bad-record handler.
    """

    def __init__(
        self,
        *,
        agent_id: str = "",
        application_id: str = "",
        tenant: str = "default",
        topic_connections=None,
        persistent_state_directory: Optional[str] = None,
        metrics=None,
        global_agent_id: Optional[str] = None,
        bad_record_handler: Optional[Callable[[Record, BaseException], None]] = None,
        service_provider_registry=None,
        resources: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> None:
        self.agent_id = agent_id
        self.application_id = application_id
        self.tenant = tenant
        self.topic_connections = topic_connections
        self._persistent_state_directory = persistent_state_directory
        self.metrics = metrics
        self.global_agent_id = global_agent_id or agent_id
        self.bad_record_handler = bad_record_handler
        self.service_provider_registry = service_provider_registry
        # resolved `resources:` section of configuration.yaml (datasources,
        # ai services) so agents can look up shared service configs
        self.resources = resources or {}

    def persistent_state_directory(self) -> Optional[str]:
        """Per-agent durable scratch dir (reference:
        ``AgentContext.getPersistentStateDirectoryForAgent``,
        ``AgentContext.java:42-44``); None when no disk was requested."""
        return self._persistent_state_directory
